"""Process-wide verified-signature cache — the "verify once" hot path.

Every signature hot path in the tree (VoteSet.add_votes, VerifyCommit*
during ApplyBlock, blocksync v0/v1/v2, the light client) routes through
one crypto.BatchVerifier, but before this module they verified the SAME
signatures repeatedly: a precommit checked at vote ingestion was
re-verified by verify_commit on the very next height's ApplyBlock,
blocksync re-verified commits the node already tallied, and a vote
relayed by N peers burned N padded batch lanes. PERF.md's step
breakdown shows dispatch count and lane occupancy are the cost drivers
on both CPU and the ~70 ms/RPC tunnel, so a lane that never exists is
the cheapest lane there is.

Design:

- Entries are keyed by ``sha256(type ‖ len(pk) ‖ pk ‖ len(msg) ‖ msg ‖
  len(sig) ‖ sig)`` — length-prefixed so no two distinct triples can
  collide by concatenation ambiguity, and curve-typed so identical key
  bytes on two curves stay distinct entries. The SAME ``(pubkey, msg)``
  under two DIFFERENT signatures occupies two distinct entries (the
  equivocation case: both must verify independently).
- **Only successful verifications are cached.** A cached entry asserts
  "this exact (pubkey, msg, sig) triple verified" — a pure statement of
  signature math that no validator-set rotation, peer behavior, or
  restart can invalidate, so a hit can never be a stale false-positive.
  Failures are NOT cached: invalid signatures are rare, attacker-
  controlled (a negative cache is a memory DoS lever), and re-verifying
  them only slows the attacker down.
- Sharded + lock-striped: the key's first bytes pick one of
  ``shards`` independent LRU maps, each with its own lock, so vote
  ingestion, ApplyBlock, and blocksync threads do not serialize on one
  mutex. Per-shard capacity bounds total memory (entries are 32-byte
  keys + OrderedDict overhead; the default 131072 entries is a few MB).
- Explicit invalidation: ``invalidate_all()`` (operator action, tests)
  and ``configure()`` (node wiring from ``[crypto] sigcache_*`` knobs;
  shrinking capacity evicts immediately).

Every hit/miss/insert/evict lands in the
``tendermint_crypto_sigcache_*`` metric set (libs/metrics.py) and batch
verifies with cache activity emit ``crypto.sigcache`` timeline events
(docs/OBSERVABILITY.md runbook).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional

DEFAULT_MAX_ENTRIES = 131072
DEFAULT_SHARDS = 16


def cache_key(type_value: str, pk_bytes: bytes, msg: bytes,
              sig: bytes) -> bytes:
    """The 32-byte cache key for one (curve, pubkey, msg, sig) triple.
    Length-prefixed fields make the encoding injective; the curve name
    keeps equal byte-strings on different curves apart."""
    h = hashlib.sha256()
    t = type_value.encode()
    for part in (t, pk_bytes, msg, sig):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class SigCache:
    """Sharded, lock-striped LRU set of verified-signature keys."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 shards: int = DEFAULT_SHARDS, enabled: bool = True):
        shards = max(1, int(shards))
        # round shards down to a power of two so the key byte masks
        # uniformly (sha256 output is uniform; masking keeps it so)
        while shards & (shards - 1):
            shards -= 1
        self._shard_mask = shards - 1
        self._shards = [OrderedDict() for _ in range(shards)]
        self._locks = [threading.Lock() for _ in range(shards)]
        self._max_entries = max(shards, int(max_entries))
        self._per_shard = max(1, self._max_entries // shards)
        self._enabled = bool(enabled)
        # lifetime counters (metrics carry the cross-restart totals;
        # these back stats() so tools need no metrics scrape)
        self._hits = 0
        self._misses = 0
        self._inserts = 0
        self._evictions = 0
        self._stats_lock = threading.Lock()

    # -- core ---------------------------------------------------------------

    def _shard(self, key: bytes):
        i = key[0] & self._shard_mask
        return self._shards[i], self._locks[i]

    def contains(self, key: bytes) -> bool:
        """True iff ``key`` was inserted as verified. Hits refresh LRU
        recency. Counts a hit/miss in both stats and metrics."""
        if not self._enabled:
            return False
        shard, lock = self._shard(key)
        with lock:
            hit = key in shard
            if hit:
                shard.move_to_end(key)
        self._note(hit)
        return hit

    def add(self, key: bytes) -> None:
        """Record one VERIFIED triple. Evicts LRU entries past the
        per-shard cap; never blocks other shards."""
        if not self._enabled:
            return
        evicted = 0
        shard, lock = self._shard(key)
        with lock:
            already = key in shard
            shard[key] = True
            shard.move_to_end(key)
            while len(shard) > self._per_shard:
                shard.popitem(last=False)
                evicted += 1
        from tmtpu.libs import metrics as _m

        with self._stats_lock:
            if not already:
                self._inserts += 1
            self._evictions += evicted
        if not already:
            _m.crypto_sigcache_inserts.inc()
        if evicted:
            _m.crypto_sigcache_evictions.inc(evicted)
        _m.crypto_sigcache_entries.set(self.size())

    def check(self, type_value: str, pk_bytes: bytes, msg: bytes,
              sig: bytes) -> bool:
        """Convenience: key + contains in one call."""
        return self.contains(cache_key(type_value, pk_bytes, msg, sig))

    def record(self, type_value: str, pk_bytes: bytes, msg: bytes,
               sig: bytes) -> None:
        """Convenience: key + add in one call."""
        self.add(cache_key(type_value, pk_bytes, msg, sig))

    def _note(self, hit: bool) -> None:
        from tmtpu.libs import metrics as _m

        with self._stats_lock:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
        if hit:
            _m.crypto_sigcache_hits.inc()
        else:
            _m.crypto_sigcache_misses.inc()

    # -- control ------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self._enabled = bool(enabled)
        if not self._enabled:
            self.invalidate_all()

    def enabled(self) -> bool:
        return self._enabled

    def invalidate_all(self) -> None:
        """Drop every entry (operator hook / tests). Never invalidates
        correctness — entries are context-free signature-math facts —
        but frees memory and forces fresh verifies."""
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                shard.clear()
        from tmtpu.libs import metrics as _m

        _m.crypto_sigcache_entries.set(0)

    def resize(self, max_entries: int, shards: Optional[int] = None) -> None:
        """Apply new capacity (config reload). Changing the shard count
        rebuilds the stripe array (entries are dropped — simpler than
        rehashing, and a reload is rare); shrinking capacity in place
        evicts LRU immediately."""
        if shards is not None and (max(1, int(shards)) !=
                                   self._shard_mask + 1):
            self.__init__(max_entries, shards, self._enabled)
            return
        self._max_entries = max(self._shard_mask + 1, int(max_entries))
        self._per_shard = max(1, self._max_entries //
                              (self._shard_mask + 1))
        evicted = 0
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                while len(shard) > self._per_shard:
                    shard.popitem(last=False)
                    evicted += 1
        if evicted:
            from tmtpu.libs import metrics as _m

            with self._stats_lock:
                self._evictions += evicted
            _m.crypto_sigcache_evictions.inc(evicted)
            _m.crypto_sigcache_entries.set(self.size())

    # -- reading ------------------------------------------------------------

    def size(self) -> int:
        return sum(len(s) for s in self._shards)

    def stats(self) -> Dict:
        with self._stats_lock:
            hits, misses = self._hits, self._misses
            inserts, evictions = self._inserts, self._evictions
        lookups = hits + misses
        return {
            "enabled": self._enabled,
            "entries": self.size(),
            "max_entries": self._max_entries,
            "shards": self._shard_mask + 1,
            "hits": hits,
            "misses": misses,
            "inserts": inserts,
            "evictions": evictions,
            "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        }


# --- the process-wide instance ----------------------------------------------
#
# One cache per process, like the breaker registry: vote ingestion,
# ApplyBlock, blocksync and the light client must all see each other's
# verifications or the "verify once" property is lost.

DEFAULT = SigCache()


def configure(max_entries: int, shards: int, enabled: bool = True) -> None:
    """Apply the ``[crypto] sigcache_*`` knobs (node wiring / config
    reload)."""
    DEFAULT.set_enabled(enabled)
    if enabled:
        DEFAULT.resize(max_entries, shards)


def check(type_value: str, pk_bytes: bytes, msg: bytes, sig: bytes) -> bool:
    return DEFAULT.check(type_value, pk_bytes, msg, sig)


def record(type_value: str, pk_bytes: bytes, msg: bytes, sig: bytes) -> None:
    DEFAULT.record(type_value, pk_bytes, msg, sig)


def stats() -> Dict:
    return DEFAULT.stats()


def invalidate_all() -> None:
    DEFAULT.invalidate_all()
