"""sr25519 keys — schnorrkel (Schnorr over ristretto255 with merlin
transcripts), pure Python (reference: crypto/sr25519/pubkey.go:50 via
ChainSafe/go-schnorrkel).

Protocol per schnorrkel sign.rs:
  t = merlin("SigningContext"); t.append("", ctx); t.append("sign-bytes", m)
  t.append("proto-name", "Schnorr-sig"); t.append("sign:pk", A)
  r = witness scalar from (transcript, nonce); R = r*B
  t.append("sign:R", R); k = challenge_scalar("sign:c")
  s = k*key + r;  sig = R || s with bit 7 of byte 63 set (schnorrkel marker)
Verification recomputes k and checks R == s*B - k*A.

Private key bytes = the 32-byte MiniSecretKey, expanded ExpandEd25519-style
(sha512, ed25519 clamp, divide by cofactor) on use — matching the
reference's privkey.go Sign/PubKey round-trip. The merlin layer is
KAT-verified; ristretto against the spec's small-multiple vectors.
"""

from __future__ import annotations

import hashlib
import os

from tmtpu.crypto import ristretto, tmhash
from tmtpu.crypto.keys import PrivKey, PubKey, register_key_type
from tmtpu.crypto.merlin import Transcript

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

# group order l (same as ed25519's L)
L = 2**252 + 27742317777372353535851937790883648493


def _signing_context(msg: bytes) -> Transcript:
    """go-schnorrkel NewSigningContext([]byte{}, msg)."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    return t


def _expand_ed25519(mini: bytes):
    """schnorrkel MiniSecretKey::expand_ed25519 -> (key scalar, nonce)."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    # divide_scalar_bytes_by_cofactor: LE >> 3 (exact: low bits clamped 0)
    scalar = int.from_bytes(key, "little") >> 3
    return scalar, h[32:64]


def _challenge_scalar(t: Transcript, label: bytes) -> int:
    return int.from_bytes(t.challenge_bytes(label, 64), "little") % L


class PubKeySr25519(PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, key_bytes: bytes):
        if len(key_bytes) != PUB_KEY_SIZE:
            raise ValueError(f"sr25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(key_bytes)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if not (sig[63] & 0x80):
            return False  # not marked as a schnorrkel signature
        A = ristretto.decode(self._bytes)
        if A is None:
            return False
        r_bytes = sig[:32]
        s_bytes = bytearray(sig[32:])
        s_bytes[63 - 32] &= 0x7F
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            return False  # non-canonical scalar
        t = _signing_context(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", self._bytes)
        t.append_message(b"sign:R", bytes(r_bytes))
        k = _challenge_scalar(t, b"sign:c")
        # R' = s*B - k*A
        R = ristretto.point_add(
            ristretto.scalar_mult(s, ristretto.BASEPOINT),
            ristretto.scalar_mult(k, ristretto.point_neg(A)),
        )
        return ristretto.encode(R) == bytes(r_bytes)

    def type_value(self) -> str:
        return KEY_TYPE

    def equals(self, other) -> bool:
        return isinstance(other, PubKeySr25519) and \
            self._bytes == other._bytes

    def __repr__(self):
        return f"PubKeySr25519{{{self._bytes.hex().upper()}}}"


class PrivKeySr25519(PrivKey):
    __slots__ = ("_bytes",)

    def __init__(self, key_bytes: bytes):
        if len(key_bytes) != PRIV_KEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(key_bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        key, nonce = _expand_ed25519(self._bytes)
        pub = self.pub_key().bytes()
        t = _signing_context(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pub)
        # witness nonce via the merlin transcript rng; the rng input is
        # derived deterministically (nonce+msg) — any choice verifies
        rng = hashlib.sha512(nonce + msg).digest()[:32]
        wb = t.witness_bytes(b"signing", nonce, 64, rng_bytes=rng)
        r = int.from_bytes(wb, "little") % L
        R = ristretto.encode(
            ristretto.scalar_mult(r, ristretto.BASEPOINT))
        t.append_message(b"sign:R", R)
        k = _challenge_scalar(t, b"sign:c")
        s = (k * key + r) % L
        sig = bytearray(R + s.to_bytes(32, "little"))
        sig[63] |= 0x80
        return bytes(sig)

    def pub_key(self) -> PubKeySr25519:
        key, _ = _expand_ed25519(self._bytes)
        return PubKeySr25519(ristretto.encode(
            ristretto.scalar_mult(key, ristretto.BASEPOINT)))

    def type_value(self) -> str:
        return KEY_TYPE

    def equals(self, other) -> bool:
        return isinstance(other, PrivKeySr25519) and \
            self._bytes == other._bytes


def gen_priv_key() -> PrivKeySr25519:
    return PrivKeySr25519(os.urandom(PRIV_KEY_SIZE))


def gen_priv_key_from_secret(secret: bytes) -> PrivKeySr25519:
    return PrivKeySr25519(hashlib.sha256(secret).digest())


register_key_type(KEY_TYPE, PubKeySr25519, PrivKeySr25519)
