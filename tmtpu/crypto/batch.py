"""BatchVerifier — the framework's batch-first signature verification API.

The reference has NO batch verifier (SURVEY.md: every signature goes through
crypto.PubKey.VerifySignature one at a time — crypto/crypto.go:25). This
interface is the new hot-path primitive every upper layer is written
against (VoteSet, VerifyCommit*, light client, evidence):

    bv = new_batch_verifier()          # picks TPU when available
    for pk, msg, sig in ...: bv.add(pk, msg, sig)
    all_ok, mask = bv.verify()

Backends:
- ``cpu``: serial per-signature verify through the PubKey objects (OpenSSL
  under the hood) — the fallback and the small-batch fast path;
- ``tpu``: groups items per curve into device batches — ed25519
  (tmtpu.tpu.verify.batch_verify), sr25519
  (tmtpu.tpu.sr_verify.batch_verify_sr), secp256k1
  (tmtpu.tpu.k1_verify.batch_verify_k1) — so mixed-curve sets get one
  device dispatch per curve present. Per-lane semantics are identical to
  serial verification (no probabilistic batch equation), so the returned
  mask is exact for mixed valid/invalid batches.

Backend selection: ``set_default_backend`` / config ``crypto.backend``;
``auto`` probes for a usable jax device under the ``crypto.tpu`` circuit
breaker — a transient probe failure no longer pins the node to CPU
forever: the breaker opens after a few consecutive failures, backs off,
and re-probes (libs/breaker.py, docs/RESILIENCE.md).

Verify-once hot path (crypto/sigcache.py): before any lane is assigned,
every (pubkey, msg, sig) triple is checked against the process-wide
verified-signature cache — a cached triple never occupies a lane, and
identical in-flight triples within one batch collapse onto a single
lane (one verify, N results). Successful verifications are inserted on
the way out, so a precommit verified at vote ingestion costs ZERO
dispatches when verify_commit re-checks it during the next height's
ApplyBlock, and blocksync/light-client re-verification of already-seen
commits short-circuits the same way. Cache hits never touch the
breaker: only real device round-trips advance ``half_open → closed``.

Adaptive flush scheduling: the module-level ``SCHEDULER`` tracks lane
arrival rate (EWMA over ``add()`` calls) and device dispatch RTT (EWMA
over timed ``_dispatch`` round-trips) and picks a flush size between
min-latency (dispatch what you have) and max-amortization (wait one RTT
worth of arrivals): ``target_lanes = clamp(rate × rtt)``. The consensus
receive loop consults ``gather_wait_s`` to decide whether a few extra
milliseconds of draining buys a materially fuller batch; the breaker
and per-batch deadline machinery are unchanged.
"""

from __future__ import annotations

import os
import threading
import time as _time_mod
from typing import Dict, List, Optional, Tuple

from tmtpu.crypto import keys, sigcache
from tmtpu.crypto.keys import PubKey
from tmtpu.libs import breaker as _bk

ED25519 = "ed25519"
SR25519 = "sr25519"
SECP256K1 = "secp256k1"

# below this, device dispatch overhead beats CPU serial (env-overridable so
# small-validator integration tests can force the device path)
_TPU_MIN_BATCH = int(os.environ.get("TMTPU_TPU_MIN_BATCH", "8"))

_default_backend = os.environ.get("TMTPU_CRYPTO_BACKEND", "auto")
_probe_lock = threading.Lock()
# memo of the last SUCCESSFUL device probe (None = not yet probed /
# last probe failed → re-probe when the breaker next allows it). Tests
# monkeypatch this to True to force the device code path.
_tpu_usable: Optional[bool] = None

# the breaker governing every device touch from this module; one name so
# probe failures and batch failures share the same failure budget
BREAKER_NAME = "crypto.tpu"

# the breaker governing the sidecar round-trip path: connection failures,
# request deadlines, and hard daemon errors share one failure budget, so
# a dead daemon costs a few failed round-trips and then every batch rides
# in-process until the backoff elapses and a half-open probe reconnects.
# Overload backpressure (an explicitly HEALTHY daemon saying "not now")
# never counts against it.
SIDECAR_BREAKER_NAME = "crypto.sidecar"

# sidecar client wiring: configure_sidecar() fills this from config (and
# Node.__init__ calls it before the first verifier is built); the client
# object is built lazily on first use so importing this module never
# touches a socket. Tests monkeypatch "addr" / reset "client".
_sidecar_lock = threading.Lock()
_sidecar_state: Dict = {
    "addr": "",
    "home": "",
    "client": None,
    "connect_timeout_s": 2.0,
    "request_deadline_s": 10.0,
    "retry_backoff_s": 1.0,
    "max_frame_bytes": 8 * 1024 * 1024,
}

# defaults mirror config/config.py CryptoConfig; Node.__init__ overwrites
# via configure() before the first verifier is built
_probe_timeout_s = 20.0
_batch_deadline_s = 120.0


def _tpu_breaker() -> "_bk.CircuitBreaker":
    return _bk.get(BREAKER_NAME)


class AdaptiveFlushScheduler:
    """Pick the flush size between min-latency and max-amortization.

    Two EWMAs: lane ARRIVAL RATE (updated by every ``BatchVerifier.add``)
    and device dispatch RTT (updated by every successful timed device
    round-trip in ``_dispatch`` — serial fallbacks and cache hits do not
    count, they carry no tunnel latency signal). The optimal batch under
    a fixed per-dispatch cost is the number of lanes that arrive during
    one RTT: fewer and the dispatch overhead dominates, more and queue
    latency dominates. So ``target_lanes = clamp(rate × rtt, min, max)``
    and ``gather_wait_s(pending)`` answers "is it worth draining a few
    more ms before flushing?" — capped at ``max_wait_s`` so consensus
    latency is bounded, and ZERO until both EWMAs have real samples
    (CPU-only nodes and fresh processes keep the legacy flush-now
    behavior)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._alpha = 0.2
        self._rate = 0.0          # lanes/s
        self._rtt = 0.0           # seconds per device round-trip
        self._last_arrival: Optional[float] = None
        self.enabled = True
        self.min_lanes = _TPU_MIN_BATCH
        self.max_lanes = 4096
        self.max_wait_s = 0.008

    def note_arrivals(self, n: int = 1) -> None:
        now = _time_mod.monotonic()
        with self._lock:
            last, self._last_arrival = self._last_arrival, now
            if last is None:
                return
            dt = now - last
            if dt <= 0:
                return
            # arrivals more than ~1s apart mean an idle gap, not a rate
            # sample — consensus rounds are sub-second; skip them so one
            # quiet stretch does not zero the EWMA
            if dt > 1.0:
                return
            inst = n / dt
            a = self._alpha
            self._rate = inst if self._rate <= 0 else \
                (1 - a) * self._rate + a * inst

    def note_dispatch(self, lanes: int, seconds: float) -> None:
        if seconds <= 0:
            return
        # compilation outliers (first XLA trace per bucket shape) would
        # poison the steady-state RTT; clamp the sample
        seconds = min(seconds, 2.0)
        with self._lock:
            a = self._alpha
            self._rtt = seconds if self._rtt <= 0 else \
                (1 - a) * self._rtt + a * seconds

    def snapshot(self) -> Dict:
        with self._lock:
            return {"rate_lanes_per_s": round(self._rate, 3),
                    "rtt_s": round(self._rtt, 6),
                    "enabled": self.enabled,
                    "target_lanes": self._target_locked()}

    def _target_locked(self) -> int:
        if not self.enabled or self._rtt <= 0 or self._rate <= 0:
            return self.min_lanes
        return int(max(self.min_lanes,
                       min(self.max_lanes, self._rate * self._rtt)))

    def target_lanes(self) -> int:
        with self._lock:
            t = self._target_locked()
        from tmtpu.libs import metrics as _m

        _m.crypto_flush_target_lanes.set(t)
        return t

    def gather_wait_s(self, pending: int) -> float:
        """Seconds the drain loop may linger to fill ``pending`` toward
        the target before flushing. 0.0 when adaptive data is absent,
        the target is already met, or the scheduler is disabled."""
        with self._lock:
            if (not self.enabled or self._rtt <= 0 or self._rate <= 0):
                return 0.0
            target = self._target_locked()
            rate = self._rate
        if pending >= target:
            return 0.0
        return min((target - pending) / rate, self.max_wait_s)

    def reset(self) -> None:
        with self._lock:
            self._rate = 0.0
            self._rtt = 0.0
            self._last_arrival = None


SCHEDULER = AdaptiveFlushScheduler()


def configure(crypto_cfg) -> None:
    """Apply a config/config.py ``CryptoConfig``: probe + per-batch
    deadlines for this module, thresholds/backoff for the ``crypto.tpu``
    breaker, ``sigcache_*`` knobs for the verified-signature cache, and
    the adaptive flush window. Safe to call again on config reload."""
    global _probe_timeout_s, _batch_deadline_s
    _probe_timeout_s = crypto_cfg.probe_timeout_ns / 1e9
    _batch_deadline_s = crypto_cfg.batch_deadline_ns / 1e9
    _bk.configure(
        BREAKER_NAME,
        failure_threshold=crypto_cfg.breaker_failure_threshold,
        backoff_base_s=crypto_cfg.breaker_backoff_base_ns / 1e9,
        backoff_max_s=crypto_cfg.breaker_backoff_max_ns / 1e9,
        half_open_probes=crypto_cfg.breaker_half_open_probes)
    sigcache.configure(
        getattr(crypto_cfg, "sigcache_max_entries",
                sigcache.DEFAULT_MAX_ENTRIES),
        getattr(crypto_cfg, "sigcache_shards", sigcache.DEFAULT_SHARDS),
        getattr(crypto_cfg, "sigcache_enable", True))
    SCHEDULER.enabled = getattr(crypto_cfg, "adaptive_flush", True)
    SCHEDULER.max_wait_s = getattr(
        crypto_cfg, "flush_max_wait_ns", 8_000_000) / 1e9
    SCHEDULER.max_lanes = getattr(crypto_cfg, "flush_max_lanes", 4096)
    from tmtpu.tpu import mesh_dispatch as _mesh

    _mesh.configure(crypto_cfg)


def probe_timeout_s() -> float:
    """The device-probe deadline. The env var is read at CALL time (it
    was import-time before, which froze the value for the process) so
    tests and operators can override without re-importing; config
    (via ``configure``) provides the base value."""
    raw = os.environ.get("TMTPU_TPU_PROBE_TIMEOUT", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return _probe_timeout_s


def batch_deadline_s() -> float:
    """Per-batch deadline on device dispatch (<= 0 disables). Same
    call-time env override pattern as ``probe_timeout_s``."""
    raw = os.environ.get("TMTPU_TPU_BATCH_DEADLINE", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return _batch_deadline_s


def set_default_backend(backend: str) -> None:
    global _default_backend, _tpu_usable
    if backend not in ("auto", "cpu", "tpu", "sidecar"):
        raise ValueError(f"unknown crypto backend {backend!r}")
    _default_backend = backend
    if backend != "auto":
        _tpu_usable = None


def configure_sidecar(sidecar_cfg, home: str = "") -> None:
    """Apply a config/config.py ``SidecarConfig`` to the client side:
    address resolution inputs, connection/request timeouts, and the
    ``crypto.sidecar`` breaker thresholds (backoff shape is shared with
    the crypto breaker config via ``configure``). Drops any existing
    client so a config reload reconnects with the new parameters."""
    with _sidecar_lock:
        old = _sidecar_state.get("client")
        _sidecar_state.update(
            addr=sidecar_cfg.addr,
            home=home,
            client=None,
            connect_timeout_s=sidecar_cfg.connect_timeout_ns / 1e9,
            request_deadline_s=sidecar_cfg.request_deadline_ns / 1e9,
            retry_backoff_s=sidecar_cfg.retry_backoff_ns / 1e9,
            max_frame_bytes=sidecar_cfg.max_frame_bytes)
    if old is not None:
        old.close()
    _bk.configure(
        SIDECAR_BREAKER_NAME,
        failure_threshold=sidecar_cfg.breaker_failure_threshold)


def _sidecar_client():
    """The process-wide sidecar client, built lazily from the configured
    (or env/home-derived) address; None when no address resolves."""
    from tmtpu.sidecar import client as _sc

    with _sidecar_lock:
        c = _sidecar_state["client"]
        if c is not None:
            return c
        addr = _sidecar_state["addr"] or _sc.default_addr(
            _sidecar_state["home"])
        if not addr:
            return None
        c = _sc.SidecarClient(
            addr,
            connect_timeout_s=_sidecar_state["connect_timeout_s"],
            request_deadline_s=_sidecar_state["request_deadline_s"],
            retry_backoff_s=_sidecar_state["retry_backoff_s"],
            max_frame_bytes=_sidecar_state["max_frame_bytes"])
        _sidecar_state["client"] = c
        return c


def reset_sidecar_client() -> None:
    """Drop the cached client (tests; config/addr changes)."""
    with _sidecar_lock:
        old, _sidecar_state["client"] = _sidecar_state["client"], None
    if old is not None:
        old.close()


def _tpu_available() -> bool:
    """Probe for a usable jax device under the ``crypto.tpu`` breaker,
    with a hard timeout: a wedged PJRT plugin/tunnel can hang backend
    init indefinitely, and consensus must degrade to the CPU path
    rather than stall. Unlike the old one-shot latch, only SUCCESS is
    cached — a failed probe counts against the breaker and is retried
    on the next call until the breaker opens, after which callers get
    CPU immediately until the backoff elapses and a half-open probe
    runs. Every attempt, timeout, and the up/down verdict land in the
    crypto metric set (docs/OBSERVABILITY.md)."""
    global _tpu_usable
    br = _tpu_breaker()
    if not br.allow():
        return False
    if _tpu_usable:
        return True
    with _probe_lock:
        if _tpu_usable:
            return True
        from tmtpu.libs import metrics as _m

        def probe() -> bool:
            import jax

            return len(jax.devices()) > 0

        _m.crypto_device_probe_attempts.inc()
        try:
            ok = _bk.call_with_deadline(probe, probe_timeout_s())
            if ok:
                br.record_success()
            else:
                br.record_failure(RuntimeError("no jax devices"))
        except _bk.DeadlineExceeded as e:
            _m.crypto_device_probe_timeouts.inc()
            br.record_failure(e)
            ok = False
        except Exception as e:  # noqa: BLE001 — import/init failure
            br.record_failure(e)
            ok = False
        _m.crypto_tpu_backend_up.set(1.0 if ok else 0.0)
        if ok:
            _tpu_usable = True
        else:
            _m.crypto_cpu_fallback.inc(curve="any", reason="probe-failed")
        return ok


class BatchVerifier(keys.BatchVerifier):
    """Accumulate (pubkey, msg, sig[, power]) items, then verify at once.

    ``verify``/``verify_tally`` run the verify-once resolve: every lane
    is checked against the process-wide sigcache first (a hit costs no
    lane), identical in-flight triples collapse onto one lane with their
    powers folded so the fused device tally still counts every member,
    and only the deduped miss list reaches the backend hook
    ``_verify_pending``. Successful lanes are inserted into the cache on
    the way out. ``self.cache_stats`` carries the per-flush breakdown
    (lanes/hits/dedup/dispatched) for callers and the timeline."""

    def __init__(self):
        self._items: List[Tuple[PubKey, bytes, bytes, int]] = []
        self.cache_stats: Dict = {"lanes": 0, "hits": 0, "dedup": 0,
                                  "dispatched": 0}

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes,
            power: int = 0) -> None:
        self._items.append((pub_key, bytes(msg), bytes(sig), int(power)))
        SCHEDULER.note_arrivals(1)

    def count(self) -> int:
        return len(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def _verify_pending(self, items: List[Tuple[PubKey, bytes, bytes, int]],
                        tally: bool) -> Tuple[List[bool], int]:
        """Backend hook: verify the deduped cache-miss lanes. Returns
        (mask over ``items``, tallied power of valid lanes)."""
        raise NotImplementedError

    def _resolve(self, tally: bool) -> Tuple[bool, List[bool], int]:
        items = self._items
        n = len(items)
        cache = sigcache.DEFAULT
        if not cache.enabled():
            # cache off: no keys, no dedup — byte-for-byte the legacy
            # behavior (tests that count device calls rely on this)
            mask, tallied = self._verify_pending(items, tally)
            self.cache_stats = {"lanes": n, "hits": 0, "dedup": 0,
                                "dispatched": n}
            return all(mask), mask, tallied
        mask = [False] * n
        tallied = 0
        hits = 0
        dedup = 0
        ks = [sigcache.cache_key(pk.type_value(), pk.bytes(), msg, sig)
              for pk, msg, sig, _p in items]
        group_of: Dict[bytes, int] = {}
        pending: List[int] = []       # representative index per unique miss
        members: List[List[int]] = []  # all indices sharing that triple
        for i, k in enumerate(ks):
            if cache.contains(k):
                mask[i] = True
                tallied += items[i][3]
                hits += 1
                continue
            pos = group_of.get(k)
            if pos is None:
                group_of[k] = len(pending)
                pending.append(i)
                members.append([i])
            else:
                members[pos].append(i)
                dedup += 1
        if pending:
            sub_items = []
            for pos, i in enumerate(pending):
                pk, msg, sig, _p = items[i]
                # fold dup-group powers into the unique lane so the
                # fused device tally counts every member exactly once
                sub_items.append((pk, msg, sig,
                                  sum(items[j][3] for j in members[pos])))
            sub_mask, sub_tallied = self._verify_pending(sub_items, tally)
            tallied += sub_tallied
            for pos, ok in enumerate(sub_mask):
                if ok:
                    cache.add(ks[pending[pos]])
                for j in members[pos]:
                    mask[j] = bool(ok)
        if dedup:
            from tmtpu.libs import metrics as _m

            _m.crypto_sigcache_dedup_lanes.inc(dedup)
        self.cache_stats = {"lanes": n, "hits": hits, "dedup": dedup,
                            "dispatched": len(pending)}
        if n and (hits or dedup):
            from tmtpu.libs import timeline as _tl

            _tl.record_sigcache(lanes=n, hits=hits, dedup=dedup,
                                dispatched=len(pending))
        return all(mask), mask, tallied

    def verify(self) -> Tuple[bool, List[bool]]:
        all_ok, mask, _ = self._resolve(tally=False)
        return all_ok, mask

    def verify_tally(self) -> Tuple[bool, List[bool], int]:
        """Fused verify + power tally. Cache hits contribute their power
        host-side; the device sum covers only dispatched lanes, so the
        total still equals the sum over every valid input lane."""
        return self._resolve(tally=True)


class CPUBatchVerifier(BatchVerifier):
    def _verify_pending(self, items, tally) -> Tuple[List[bool], int]:
        """ed25519 lanes go through ONE native batched-libcrypto call
        (tmtpu/native ed25519_verify_batch — python-cryptography's
        per-call overhead roughly halves the serial rate); everything
        else, and any lane when the native library is unavailable,
        verifies per item in Python."""
        import time

        from tmtpu.libs import metrics as _m
        from tmtpu.libs import trace

        t0 = time.perf_counter()
        mask = [False] * len(items)
        ed_idx = [i for i, (pk, _, sig, _) in enumerate(items)
                  if pk.type_value() == ED25519 and len(sig) == 64]
        done = set()
        impl = "serial"
        with trace.span("crypto.cpu_batch_verify", lanes=len(items)):
            if len(ed_idx) >= 2:
                try:
                    from tmtpu import native

                    ok = native.ed25519_verify_batch(
                        [items[i][0].bytes() for i in ed_idx],
                        [items[i][1] for i in ed_idx],
                        [items[i][2] for i in ed_idx])
                except Exception:  # noqa: BLE001 — never break verification
                    ok = None
                if ok is not None:
                    impl = "native"
                    for i, v in zip(ed_idx, ok):
                        mask[i] = v
                    done = set(ed_idx)
            for i, (pk, msg, sig, _) in enumerate(items):
                if i not in done:
                    mask[i] = pk.verify_signature(msg, sig)
        dt = time.perf_counter() - t0
        by_curve: dict = {}
        for pk, _msg, _sig, _p in items:
            c = pk.type_value()
            by_curve[c] = by_curve.get(c, 0) + 1
        for c, n in by_curve.items():
            _m.observe_crypto_batch(c, "cpu",
                                    impl if c == ED25519 else "serial",
                                    n, 0, dt)
        from tmtpu.libs import timeline as _tl

        _tl.record_flush(backend="cpu", lanes=len(items),
                         ok=sum(mask), seconds=round(dt, 6))
        tallied = sum(it[3] for it, ok in zip(items, mask) if ok)
        return mask, tallied


class TPUBatchVerifier(BatchVerifier):
    @staticmethod
    def _split(items):
        """Partition items into per-curve device-eligible lanes and CPU
        lanes (mixed-curve valsets dispatch one device batch per curve)."""
        ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers = [], [], [], [], []
        sr_idx, k1_idx, cpu_idx = [], [], []
        for i, (pk, msg, sig, power) in enumerate(items):
            if pk.type_value() == ED25519 and len(sig) == 64:
                ed_idx.append(i)
                ed_pks.append(pk.bytes())
                ed_msgs.append(msg)
                ed_sigs.append(sig)
                ed_powers.append(power)
            elif pk.type_value() == SR25519 and len(sig) == 64:
                sr_idx.append(i)
            elif pk.type_value() == SECP256K1 and len(sig) == 64:
                k1_idx.append(i)
            else:
                cpu_idx.append(i)
        return (ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers,
                sr_idx, k1_idx, cpu_idx)

    def _verify_pending(self, items, tally) -> Tuple[List[bool], int]:
        """Fused verify + power tally over the deduped miss lanes:
        ed25519 lanes get ONE device dispatch that (for ``tally``)
        returns both the validity mask and the psum of valid lanes'
        powers (tmtpu.tpu.sharding.verify_tally_step_compact); sr25519
        and secp256k1 lanes get their own device dispatches (mask only —
        powers summed on host); sub-threshold groups verify serially."""
        import time as _time

        from tmtpu.libs import metrics as _m

        t0 = _time.perf_counter()
        (ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers,
         sr_idx, k1_idx, cpu_idx) = self._split(items)
        if cpu_idx:
            _m.crypto_cpu_fallback.inc(len(cpu_idx), curve="other",
                                       reason="unsupported")
        if sr_idx and len(sr_idx) < _TPU_MIN_BATCH:
            cpu_idx += sr_idx  # below dispatch threshold: serial path
            _m.crypto_cpu_fallback.inc(len(sr_idx), curve=SR25519,
                                       reason="small-batch")
            sr_idx = []
        if k1_idx and len(k1_idx) < _TPU_MIN_BATCH:
            cpu_idx += k1_idx
            _m.crypto_cpu_fallback.inc(len(k1_idx), curve=SECP256K1,
                                       reason="small-batch")
            k1_idx = []
        mask: List[bool] = [False] * len(items)
        tallied = 0
        for i in cpu_idx:
            pk, msg, sig, power = items[i]
            mask[i] = pk.verify_signature(msg, sig)
            if mask[i]:
                tallied += power
        br = _tpu_breaker()
        deadline = batch_deadline_s()

        def _serial(idx_list, curve, reason):
            # CPU-serial fallback for lanes whose device batch failed
            # (or was never attempted: open breaker / small batch)
            nonlocal tallied
            _m.crypto_cpu_fallback.inc(len(idx_list), curve=curve,
                                       reason=reason)
            for i in idx_list:
                pk, msg, sig, power = items[i]
                mask[i] = pk.verify_signature(msg, sig)
                if mask[i]:
                    tallied += power

        def _dispatch(curve, idx_list, thunk, apply):
            """One per-curve device batch under the breaker and the
            per-batch deadline. Any failure — hung dispatch past the
            deadline, device/runtime error — records against the
            breaker and re-verifies exactly these lanes serially, so
            the flush always returns an exact mask. Successful
            round-trips feed the adaptive flush scheduler's RTT
            estimate (cache hits and serial fallbacks never do)."""
            if not br.allow():
                _serial(idx_list, curve, "breaker-open")
                return
            d0 = _time.perf_counter()
            try:
                out = _bk.call_with_deadline(thunk, deadline)
            except _bk.DeadlineExceeded as e:
                _m.crypto_batch_deadline_exceeded.inc(curve=curve)
                br.record_failure(e)
                _serial(idx_list, curve, "deadline")
                return
            except Exception as e:  # noqa: BLE001 — a broken device
                # path must never take down verification
                br.record_failure(e)
                _serial(idx_list, curve, "device-error")
                return
            br.record_success()
            SCHEDULER.note_dispatch(len(idx_list),
                                    _time.perf_counter() - d0)
            apply(out)

        def _apply_mask(idx_list):
            def apply(dev_mask):
                nonlocal tallied
                for j, i in enumerate(idx_list):
                    mask[i] = bool(dev_mask[j])
                    if mask[i]:
                        tallied += items[i][3]
            return apply

        from tmtpu.tpu import mesh_dispatch as _mesh

        def _mesh_first(curve, n_lanes, mesh_thunk, single_thunk):
            """Thunk combinator for _dispatch: flushes past the
            shard_min_lanes threshold try the multi-chip mesh first. A
            mesh failure records against the ``crypto.mesh`` breaker —
            never ``crypto.tpu``, whose single-device path may be
            perfectly healthy — and the SAME flush falls through to the
            single-device call inside the same deadline window, so the
            degradation ladder is mesh → single-device → CPU-serial."""
            def thunk():
                if _mesh.route(curve, n_lanes):
                    try:
                        return mesh_thunk()
                    except Exception as e:  # noqa: BLE001 — broken
                        # collectives must not take down verification
                        _mesh.note_failure(curve, n_lanes, e)
                return single_thunk()
            return thunk

        if sr_idx:
            from tmtpu.tpu.sr_verify import batch_verify_sr

            sr_pks = [items[i][0].bytes() for i in sr_idx]
            sr_msgs = [items[i][1] for i in sr_idx]
            sr_sigs = [items[i][2] for i in sr_idx]
            _dispatch(SR25519, sr_idx, _mesh_first(
                SR25519, len(sr_idx),
                lambda: _mesh.batch_verify_mesh(
                    SR25519, sr_pks, sr_msgs, sr_sigs),
                lambda: batch_verify_sr(sr_pks, sr_msgs, sr_sigs),
            ), _apply_mask(sr_idx))
        if k1_idx:
            from tmtpu.tpu.k1_verify import batch_verify_k1

            k1_pks = [items[i][0].bytes() for i in k1_idx]
            k1_msgs = [items[i][1] for i in k1_idx]
            k1_sigs = [items[i][2] for i in k1_idx]
            _dispatch(SECP256K1, k1_idx, _mesh_first(
                SECP256K1, len(k1_idx),
                lambda: _mesh.batch_verify_mesh(
                    SECP256K1, k1_pks, k1_msgs, k1_sigs),
                lambda: batch_verify_k1(k1_pks, k1_msgs, k1_sigs),
            ), _apply_mask(k1_idx))
        if ed_idx:
            if len(ed_idx) < _TPU_MIN_BATCH:
                _serial(ed_idx, ED25519, "small-batch")
            elif tally:
                from tmtpu.tpu import sharding as sh

                def _apply_tally(out):
                    nonlocal tallied
                    dev_mask, dev_sum = out
                    for j, i in enumerate(ed_idx):
                        mask[i] = bool(dev_mask[j])
                    tallied += dev_sum

                _dispatch(ED25519, ed_idx, _mesh_first(
                    ED25519, len(ed_idx),
                    lambda: _mesh.batch_verify_tally_mesh(
                        ed_pks, ed_msgs, ed_sigs, ed_powers),
                    lambda: sh.batch_verify_tally(
                        ed_pks, ed_msgs, ed_sigs, ed_powers),
                ), _apply_tally)
            else:
                from tmtpu.tpu import verify as tv

                _dispatch(ED25519, ed_idx, _mesh_first(
                    ED25519, len(ed_idx),
                    lambda: _mesh.batch_verify_mesh(
                        ED25519, ed_pks, ed_msgs, ed_sigs),
                    lambda: tv.batch_verify(ed_pks, ed_msgs, ed_sigs),
                ), _apply_mask(ed_idx))
        from tmtpu.libs import timeline as _tl

        _tl.record_flush(backend="tpu", lanes=len(items),
                         ok=sum(mask),
                         seconds=round(_time.perf_counter() - t0, 6))
        return mask, tallied


class SidecarBatchVerifier(BatchVerifier):
    """Ship the deduped miss lanes to the shared verification daemon.

    Slots UNDER the sigcache→dedup layer exactly like the other
    backends: ``_verify_pending`` only ever sees lanes the cache could
    not answer. Per curve present, one sidecar round-trip under the
    ``crypto.sidecar`` breaker; the daemon coalesces concurrent clients'
    lanes into joint device dispatches and returns this request's exact
    mask slice.

    Degradation ladder (never a wrong result, only a slower one):

    1. breaker open / no address → in-process verify immediately;
    2. overload backpressure → in-process verify, NO breaker penalty
       (the daemon is healthy and explicitly shedding load);
    3. connect failure / request deadline / hard error → breaker
       failure + in-process verify;
    4. the in-process fallback is TPU when a local device answers the
       probe, else CPU — and the TPU path carries its own serial
       fallback, so the ladder bottoms out at exact serial verify.
    """

    def _fallback_pending(self, sub_items, tally, reason):
        from tmtpu.libs import metrics as _m

        _m.sidecar_client_fallback.inc(len(sub_items), reason=reason)
        fb = TPUBatchVerifier() if _tpu_available() else CPUBatchVerifier()
        return fb._verify_pending(sub_items, tally)

    def _verify_pending(self, items, tally) -> Tuple[List[bool], int]:
        import time as _time

        from tmtpu.libs import timeline as _tl
        from tmtpu.sidecar import client as _sc

        mask: List[bool] = [False] * len(items)
        tallied = 0
        by_curve: Dict[str, List[int]] = {}
        for i, (pk, _msg, _sig, _p) in enumerate(items):
            by_curve.setdefault(pk.type_value(), []).append(i)
        br = _bk.get(SIDECAR_BREAKER_NAME)
        client = _sidecar_client()

        def _apply(idx_list, sub_mask):
            nonlocal tallied
            for j, i in enumerate(idx_list):
                mask[i] = bool(sub_mask[j])
                if mask[i]:
                    tallied += items[i][3]

        for curve, idx in by_curve.items():
            sub_items = [items[i] for i in idx]
            if client is None:
                sub_mask, _t = self._fallback_pending(
                    sub_items, tally, "no-addr")
                _apply(idx, sub_mask)
                continue
            if not br.allow():
                sub_mask, _t = self._fallback_pending(
                    sub_items, tally, "breaker-open")
                _apply(idx, sub_mask)
                continue
            lanes = [(pk.bytes(), msg, sig, power)
                     for pk, msg, sig, power in sub_items]
            t0 = _time.perf_counter()
            try:
                sub_mask, _stallied, info = client.verify(
                    curve, lanes, tally=tally,
                    deadline_s=_sidecar_state["request_deadline_s"])
            except _sc.SidecarOverloaded:
                sub_mask, _t = self._fallback_pending(
                    sub_items, tally, "overloaded")
                _apply(idx, sub_mask)
                continue
            except _sc.SidecarUnavailable as e:
                br.record_failure(e)
                sub_mask, _t = self._fallback_pending(
                    sub_items, tally, "unavailable")
                _apply(idx, sub_mask)
                continue
            dt = _time.perf_counter() - t0
            br.record_success()
            # a sidecar round-trip IS this process's verify RTT: feed
            # the adaptive gather window exactly like a device dispatch
            SCHEDULER.note_dispatch(len(idx), dt)
            _tl.record_sidecar(
                role="client", curve=curve, lanes=len(idx),
                dispatch_lanes=info["dispatch_lanes"],
                dispatch_clients=info["dispatch_clients"],
                seconds=round(dt, 6))
            _apply(idx, sub_mask)
        return mask, tallied


def new_batch_verifier(backend: Optional[str] = None) -> BatchVerifier:
    b = backend or _default_backend
    if b == "auto":
        b = "tpu" if _tpu_available() else "cpu"
    if b == "sidecar":
        return SidecarBatchVerifier()
    if b == "tpu":
        return TPUBatchVerifier()
    return CPUBatchVerifier()


def batch_verify_items(items, backend: Optional[str] = None):
    bv = new_batch_verifier(backend)
    for pk, msg, sig in items:
        bv.add(pk, msg, sig)
    return bv.verify()


def verify_one(pub_key: PubKey, msg: bytes, sig: bytes) -> bool:
    """Cache-aware single-signature verify for paths that cannot batch
    (proposal signature, Vote.verify, privval handshakes): consults the
    verified-signature cache before the serial PubKey verify and records
    successes, so e.g. a proposal re-checked after a WAL replay, or a
    vote object verified outside a VoteSet, rides the verify-once path."""
    cache = sigcache.DEFAULT
    if not cache.enabled():
        return pub_key.verify_signature(msg, sig)
    k = sigcache.cache_key(pub_key.type_value(), pub_key.bytes(), msg, sig)
    if cache.contains(k):
        return True
    ok = pub_key.verify_signature(msg, sig)
    if ok:
        cache.add(k)
    return ok
