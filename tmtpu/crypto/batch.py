"""BatchVerifier — the framework's batch-first signature verification API.

The reference has NO batch verifier (SURVEY.md: every signature goes through
crypto.PubKey.VerifySignature one at a time — crypto/crypto.go:25). This
interface is the new hot-path primitive every upper layer is written
against (VoteSet, VerifyCommit*, light client, evidence):

    bv = new_batch_verifier()          # picks TPU when available
    for pk, msg, sig in ...: bv.add(pk, msg, sig)
    all_ok, mask = bv.verify()

Backends:
- ``cpu``: serial per-signature verify through the PubKey objects (OpenSSL
  under the hood) — the fallback and the small-batch fast path;
- ``tpu``: groups items per curve into device batches — ed25519
  (tmtpu.tpu.verify.batch_verify), sr25519
  (tmtpu.tpu.sr_verify.batch_verify_sr), secp256k1
  (tmtpu.tpu.k1_verify.batch_verify_k1) — so mixed-curve sets get one
  device dispatch per curve present. Per-lane semantics are identical to
  serial verification (no probabilistic batch equation), so the returned
  mask is exact for mixed valid/invalid batches.

Backend selection: ``set_default_backend`` / config ``crypto.backend``;
``auto`` probes for a usable jax device once and caches the answer.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

from tmtpu.crypto import keys
from tmtpu.crypto.keys import PubKey

ED25519 = "ed25519"
SR25519 = "sr25519"
SECP256K1 = "secp256k1"

# below this, device dispatch overhead beats CPU serial (env-overridable so
# small-validator integration tests can force the device path)
_TPU_MIN_BATCH = int(os.environ.get("TMTPU_TPU_MIN_BATCH", "8"))

_default_backend = os.environ.get("TMTPU_CRYPTO_BACKEND", "auto")
_probe_lock = threading.Lock()
_tpu_usable: Optional[bool] = None


def set_default_backend(backend: str) -> None:
    global _default_backend, _tpu_usable
    if backend not in ("auto", "cpu", "tpu"):
        raise ValueError(f"unknown crypto backend {backend!r}")
    _default_backend = backend
    if backend != "auto":
        _tpu_usable = None


_PROBE_TIMEOUT_S = float(os.environ.get("TMTPU_TPU_PROBE_TIMEOUT", "10"))


def _tpu_available() -> bool:
    """Probe for a usable jax device ONCE, with a hard timeout: a wedged
    PJRT plugin/tunnel can hang backend init indefinitely, and consensus
    must degrade to the CPU path rather than stall. Each probe attempt,
    timeout, and the resulting up/down verdict land in the crypto metric
    set (docs/OBSERVABILITY.md) — a node silently degraded to CPU shows
    as tendermint_crypto_tpu_backend_up 0."""
    global _tpu_usable
    if _tpu_usable is None:
        with _probe_lock:
            if _tpu_usable is None:
                from tmtpu.libs import metrics as _m

                result = {}

                def probe():
                    try:
                        import jax

                        result["ok"] = len(jax.devices()) > 0
                    except Exception:
                        result["ok"] = False

                _m.crypto_device_probe_attempts.inc()
                t = threading.Thread(target=probe, daemon=True)
                t.start()
                t.join(_PROBE_TIMEOUT_S)
                if "ok" not in result:
                    _m.crypto_device_probe_timeouts.inc()
                _tpu_usable = result.get("ok", False)
                _m.crypto_tpu_backend_up.set(1.0 if _tpu_usable else 0.0)
                if not _tpu_usable:
                    _m.crypto_cpu_fallback.inc(curve="any",
                                               reason="probe-failed")
    return _tpu_usable


class BatchVerifier(keys.BatchVerifier):
    """Accumulate (pubkey, msg, sig[, power]) items, then verify at once."""

    def __init__(self):
        self._items: List[Tuple[PubKey, bytes, bytes, int]] = []

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes,
            power: int = 0) -> None:
        self._items.append((pub_key, bytes(msg), bytes(sig), int(power)))

    def count(self) -> int:
        return len(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def verify(self) -> Tuple[bool, List[bool]]:
        raise NotImplementedError

    def verify_tally(self) -> Tuple[bool, List[bool], int]:
        all_ok, mask = self.verify()
        tallied = sum(
            it[3] for it, ok in zip(self._items, mask) if ok
        )
        return all_ok, mask, tallied


class CPUBatchVerifier(BatchVerifier):
    def verify(self) -> Tuple[bool, List[bool]]:
        """ed25519 lanes go through ONE native batched-libcrypto call
        (tmtpu/native ed25519_verify_batch — python-cryptography's
        per-call overhead roughly halves the serial rate); everything
        else, and any lane when the native library is unavailable,
        verifies per item in Python."""
        import time

        from tmtpu.libs import metrics as _m
        from tmtpu.libs import trace

        t0 = time.perf_counter()
        mask = [False] * len(self._items)
        ed_idx = [i for i, (pk, _, sig, _) in enumerate(self._items)
                  if pk.type_value() == ED25519 and len(sig) == 64]
        done = set()
        impl = "serial"
        with trace.span("crypto.cpu_batch_verify", lanes=len(self._items)):
            if len(ed_idx) >= 2:
                try:
                    from tmtpu import native

                    ok = native.ed25519_verify_batch(
                        [self._items[i][0].bytes() for i in ed_idx],
                        [self._items[i][1] for i in ed_idx],
                        [self._items[i][2] for i in ed_idx])
                except Exception:  # noqa: BLE001 — never break verification
                    ok = None
                if ok is not None:
                    impl = "native"
                    for i, v in zip(ed_idx, ok):
                        mask[i] = v
                    done = set(ed_idx)
            for i, (pk, msg, sig, _) in enumerate(self._items):
                if i not in done:
                    mask[i] = pk.verify_signature(msg, sig)
        dt = time.perf_counter() - t0
        by_curve: dict = {}
        for pk, _msg, _sig, _p in self._items:
            c = pk.type_value()
            by_curve[c] = by_curve.get(c, 0) + 1
        for c, n in by_curve.items():
            _m.observe_crypto_batch(c, "cpu",
                                    impl if c == ED25519 else "serial",
                                    n, 0, dt)
        from tmtpu.libs import timeline as _tl

        _tl.record_flush(backend="cpu", lanes=len(self._items),
                         ok=sum(mask), seconds=round(dt, 6))
        return all(mask), mask


class TPUBatchVerifier(BatchVerifier):
    def _split(self):
        """Partition items into per-curve device-eligible lanes and CPU
        lanes (mixed-curve valsets dispatch one device batch per curve)."""
        ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers = [], [], [], [], []
        sr_idx, k1_idx, cpu_idx = [], [], []
        for i, (pk, msg, sig, power) in enumerate(self._items):
            if pk.type_value() == ED25519 and len(sig) == 64:
                ed_idx.append(i)
                ed_pks.append(pk.bytes())
                ed_msgs.append(msg)
                ed_sigs.append(sig)
                ed_powers.append(power)
            elif pk.type_value() == SR25519 and len(sig) == 64:
                sr_idx.append(i)
            elif pk.type_value() == SECP256K1 and len(sig) == 64:
                k1_idx.append(i)
            else:
                cpu_idx.append(i)
        return (ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers,
                sr_idx, k1_idx, cpu_idx)

    def verify(self) -> Tuple[bool, List[bool]]:
        all_ok, mask, _ = self._run(tally=False)
        return all_ok, mask

    def verify_tally(self) -> Tuple[bool, List[bool], int]:
        """Fused verify + power tally: ed25519 lanes get ONE device dispatch
        that returns both the validity mask and the psum of valid lanes'
        powers (tmtpu.tpu.sharding.verify_tally_step_compact); sr25519 and
        secp256k1 lanes get their own device dispatches (mask only —
        powers summed on host); sub-threshold groups verify serially."""
        return self._run(tally=True)

    def _run(self, tally: bool) -> Tuple[bool, List[bool], int]:
        import time as _time

        from tmtpu.libs import metrics as _m

        t0 = _time.perf_counter()
        (ed_idx, ed_pks, ed_msgs, ed_sigs, ed_powers,
         sr_idx, k1_idx, cpu_idx) = self._split()
        if cpu_idx:
            _m.crypto_cpu_fallback.inc(len(cpu_idx), curve="other",
                                       reason="unsupported")
        if sr_idx and len(sr_idx) < _TPU_MIN_BATCH:
            cpu_idx += sr_idx  # below dispatch threshold: serial path
            _m.crypto_cpu_fallback.inc(len(sr_idx), curve=SR25519,
                                       reason="small-batch")
            sr_idx = []
        if k1_idx and len(k1_idx) < _TPU_MIN_BATCH:
            cpu_idx += k1_idx
            _m.crypto_cpu_fallback.inc(len(k1_idx), curve=SECP256K1,
                                       reason="small-batch")
            k1_idx = []
        mask: List[bool] = [False] * len(self._items)
        tallied = 0
        for i in cpu_idx:
            pk, msg, sig, power = self._items[i]
            mask[i] = pk.verify_signature(msg, sig)
            if mask[i]:
                tallied += power
        curve_batches = []
        if sr_idx:
            from tmtpu.tpu.sr_verify import batch_verify_sr

            curve_batches.append((sr_idx, batch_verify_sr))
        if k1_idx:
            from tmtpu.tpu.k1_verify import batch_verify_k1

            curve_batches.append((k1_idx, batch_verify_k1))
        for idx, fn in curve_batches:
            dev_mask = fn(
                [self._items[i][0].bytes() for i in idx],
                [self._items[i][1] for i in idx],
                [self._items[i][2] for i in idx],
            )
            for j, i in enumerate(idx):
                mask[i] = bool(dev_mask[j])
                if mask[i]:
                    tallied += self._items[i][3]
        if ed_idx:
            if len(ed_idx) < _TPU_MIN_BATCH:
                _m.crypto_cpu_fallback.inc(len(ed_idx), curve=ED25519,
                                           reason="small-batch")
                for j, i in enumerate(ed_idx):
                    mask[i] = self._items[i][0].verify_signature(
                        ed_msgs[j], ed_sigs[j]
                    )
                    if mask[i]:
                        tallied += ed_powers[j]
            elif tally:
                from tmtpu.tpu import sharding as sh

                dev_mask, dev_sum = sh.batch_verify_tally(
                    ed_pks, ed_msgs, ed_sigs, ed_powers
                )
                for j, i in enumerate(ed_idx):
                    mask[i] = bool(dev_mask[j])
                tallied += dev_sum
            else:
                from tmtpu.tpu import verify as tv

                dev_mask = tv.batch_verify(ed_pks, ed_msgs, ed_sigs)
                for j, i in enumerate(ed_idx):
                    mask[i] = bool(dev_mask[j])
        from tmtpu.libs import timeline as _tl

        _tl.record_flush(backend="tpu", lanes=len(self._items),
                         ok=sum(mask),
                         seconds=round(_time.perf_counter() - t0, 6))
        return all(mask), mask, tallied


def new_batch_verifier(backend: Optional[str] = None) -> BatchVerifier:
    b = backend or _default_backend
    if b == "auto":
        b = "tpu" if _tpu_available() else "cpu"
    if b == "tpu":
        return TPUBatchVerifier()
    return CPUBatchVerifier()


def batch_verify_items(items, backend: Optional[str] = None):
    bv = new_batch_verifier(backend)
    for pk, msg, sig in items:
        bv.add(pk, msg, sig)
    return bv.verify()
