"""Height-keyed session coalescing: many cold clients, one joint resolve.

The serving tier's second line of defense (the fact cache is the
first): when N clients concurrently ask about the SAME uncached target
height, exactly one bisection resolve runs — one set of provider
fetches, one set of device dispatches — and every waiting session gets
its own per-request slice of the outcome (the hop chain from ITS
trusted height, cut from the shared verified path).

Mirrors :mod:`tmtpu.sidecar.coalescer` deliberately: a private
:class:`~tmtpu.crypto.batch.AdaptiveFlushScheduler` fed by real session
arrivals and real resolve round-trips decides how long to linger for
more same-height arrivals; queues are FIFO across target heights so a
hot height cannot starve a cold one; ``submit`` applies admission
control (:class:`Overloaded` past ``max_queue_sessions``).

Whole-session granularity is trivial here — a session IS the unit — so
unlike the lane coalescer there is no dispatch cap: every queued
session for the chosen height rides the one resolve.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from tmtpu.crypto.batch import AdaptiveFlushScheduler

# resolve engine: (target_height, now_ns) -> resolution object
# (opaque to the coalescer; the slice function interprets it)
ResolveFn = Callable[[int, int], object]
# per-session outcome: (pending, resolution) -> None, fills the pending
# session's result fields from its own (trusted_height, trusted_hash)
SliceFn = Callable[["PendingSync", object], None]


class Overloaded(Exception):
    """Admission control rejected the session; queues are full."""


class PendingSync:
    """One client session riding toward a joint resolve."""

    __slots__ = ("client_id", "target_height", "trusted_height",
                 "trusted_hash", "now_ns", "deadline", "enqueued_at",
                 "done", "status", "hops", "dispatches", "cache_hit",
                 "error", "failure", "dispatch_id", "coalesced",
                 "on_done")

    def __init__(self, client_id: str, target_height: int,
                 trusted_height: int, trusted_hash: bytes, now_ns: int,
                 deadline: Optional[float],
                 on_done: Optional[Callable[["PendingSync"], None]]
                 = None):
        self.client_id = client_id
        self.target_height = target_height
        self.trusted_height = trusted_height
        self.trusted_hash = trusted_hash
        self.now_ns = now_ns
        self.deadline = deadline          # monotonic, None = no deadline
        self.enqueued_at = time.monotonic()
        self.done = threading.Event()
        self.status: Optional[int] = None
        self.hops: Optional[list] = None   # List[Fact], ascending
        self.dispatches = 0
        self.cache_hit = False
        self.error = ""
        self.failure = ""          # "" | "expired" | "engine" | "stopped"
        self.dispatch_id = 0
        self.coalesced = 0
        # completion hook: invoked exactly once, AFTER done is set, on
        # whichever coalescer thread finished the session. Keep it
        # cheap/non-blocking (the server hands off to a reply pool).
        self.on_done = on_done

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def finish(self) -> None:
        """Mark the session complete and fire its completion hook."""
        self.done.set()
        cb, self.on_done = self.on_done, None   # once, ever
        if cb is not None:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a reply-path bug must
                pass           # not wedge the coalescer thread


class SyncCoalescer:
    def __init__(self, resolve_fn: ResolveFn, slice_fn: SliceFn, *,
                 max_queue_sessions: int = 65536,
                 scheduler: Optional[AdaptiveFlushScheduler] = None):
        self._resolve_fn = resolve_fn
        self._slice_fn = slice_fn
        self._max_queue_sessions = max_queue_sessions
        # a PRIVATE scheduler: the daemon's session-arrival/resolve-RTT
        # profile, distinct from any crypto batch scheduler
        self.scheduler = scheduler or AdaptiveFlushScheduler()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[int, List[PendingSync]] = {}
        self._queued = 0
        self._inflight = 0            # resolves cut but not yet answered
        self._resolve_seq = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="lightserve-coalescer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            leftovers = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._queued = 0
        for req in leftovers:
            req.error = "coalescer stopped"
            req.failure = "stopped"
            req.finish()

    # --- client side ---

    def submit(self, client_id: str, target_height: int,
               trusted_height: int, trusted_hash: bytes, now_ns: int,
               deadline_s: Optional[float] = None,
               on_done: Optional[Callable[[PendingSync], None]] = None
               ) -> PendingSync:
        """Enqueue; returns a waitable :class:`PendingSync`. Raises
        :class:`Overloaded` when the session backlog is full. Every
        admitted session's ``on_done`` hook fires exactly once — on
        resolve, slice failure, deadline lapse, or coalescer stop."""
        from tmtpu.libs import metrics as _m

        req = PendingSync(
            client_id, target_height, trusted_height, trusted_hash,
            now_ns,
            None if deadline_s is None
            else time.monotonic() + deadline_s,
            on_done)
        with self._cond:
            if not self._running:
                raise Overloaded("coalescer not running")
            if self._queued + 1 > self._max_queue_sessions:
                _m.lightserve_server_overloads_total.inc()
                raise Overloaded(
                    f"session backlog full: {self._queued} queued, cap "
                    f"{self._max_queue_sessions}")
            self._queues.setdefault(target_height, []).append(req)
            self._queued += 1
            _m.lightserve_server_backlog.set(self._queued)
            self._cond.notify_all()
        self.scheduler.note_arrivals(1)
        return req

    def backlog(self) -> int:
        with self._lock:
            return self._queued + self._inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued session has resolved and answered,
        or the timeout passes (returns False)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._running and (self._queued > 0
                                     or self._inflight > 0):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.25))
            return self._queued == 0 and self._inflight == 0

    def snapshot(self) -> Dict:
        with self._lock:
            per_height = {h: len(q)
                          for h, q in self._queues.items() if q}
            return {"queued_sessions": self._queued,
                    "queued_by_height": per_height,
                    "inflight_resolves": self._inflight,
                    "resolves": self._resolve_seq,
                    "scheduler": self.scheduler.snapshot()}

    # --- dispatcher ---

    def _pick_height_locked(self) -> Optional[int]:
        """Height whose oldest session has waited longest (FIFO across
        heights so a hot target cannot starve a cold one)."""
        best, best_t = None, None
        for height, q in self._queues.items():
            if q and (best_t is None or q[0].enqueued_at < best_t):
                best, best_t = height, q[0].enqueued_at
        return best

    def _run(self) -> None:
        while True:
            batch: List[PendingSync] = []
            with self._cond:
                while self._running:
                    height = self._pick_height_locked()
                    if height is None:
                        self._cond.wait(timeout=0.5)
                        continue
                    q = self._queues[height]
                    # gather: linger only while the adaptive window says
                    # more same-height arrivals are worth the wait AND
                    # the oldest session has slack before its deadline
                    wait = self.scheduler.gather_wait_s(len(q))
                    now = time.monotonic()
                    elapsed = now - q[0].enqueued_at
                    remaining = wait - elapsed
                    if q[0].deadline is not None:
                        remaining = min(remaining, q[0].deadline - now)
                    if remaining > 1e-4:
                        self._cond.wait(timeout=remaining)
                        continue
                    batch = q
                    del self._queues[height]
                    self._queued -= len(batch)
                    self._inflight += 1
                    from tmtpu.libs import metrics as _m

                    _m.lightserve_server_backlog.set(self._queued)
                    break
                if not self._running:
                    return
            if batch:
                try:
                    self._resolve(batch[0].target_height, batch)
                finally:
                    with self._cond:
                        self._inflight -= 1
                        self._cond.notify_all()

    def _resolve(self, target_height: int,
                 batch: List[PendingSync]) -> None:
        from tmtpu.libs import metrics as _m

        # sessions whose deadline already passed are answered without
        # wasting a resolve slot on them
        now = time.monotonic()
        live: List[PendingSync] = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                req.error = "deadline expired before resolve"
                req.failure = "expired"
                req.finish()
            else:
                live.append(req)
        if not live:
            return
        with self._lock:
            self._resolve_seq += 1
            resolve_id = self._resolve_seq
        # the joint resolve judges expiry at the newest admission
        # stamp. Every now_ns is SERVER-stamped at admission (the
        # server never forwards a client clock here), so the max is
        # simply the most recent server-clock reading in the batch.
        now_ns = max(req.now_ns for req in live)
        t0 = time.perf_counter()
        try:
            resolution = self._resolve_fn(target_height, now_ns)
        except Exception as exc:  # noqa: BLE001 — engine bug must not
            # wedge sessions; they get an error verdict, never a chain
            for req in live:
                req.error = f"resolve engine failed: {exc}"
                req.failure = "engine"
                req.finish()
            return
        dt = time.perf_counter() - t0
        self.scheduler.note_dispatch(len(live), dt)
        _m.lightserve_server_resolves_total.inc()
        _m.lightserve_server_coalesced_sessions.observe(len(live))
        for req in live:
            req.dispatch_id = resolve_id
            req.coalesced = len(live)
            try:
                self._slice_fn(req, resolution)
            except Exception as exc:  # noqa: BLE001
                req.error = f"slice failed: {exc}"
                req.failure = "engine"
            req.finish()
