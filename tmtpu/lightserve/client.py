"""Lightserve client: one multiplexed connection, many in-flight sessions.

Mirrors :class:`tmtpu.sidecar.client.SidecarClient`: a background
reader thread demultiplexes replies to waiters by request id, so one
connection can carry thousands of pipelined sessions — the shape the
flood harness uses to hold 10k+ concurrent sessions with a handful of
sockets. Reconnects are lazy with a flat backoff window.

Failure kinds, for caller policy:

- :class:`LightserveUnavailable` — can't connect, connection died,
  deadline hit, daemon answered upstream_down/shutting_down. Retryable
  against another daemon.
- :class:`LightserveOverloaded` — explicit admission-control
  backpressure; the daemon is healthy. Back off and resubmit.
- :class:`LightserveRefused` — the daemon understood and said no:
  the trusting period lapsed (``expired``), the client's trusted hash
  conflicts with the verified spine (``untrusted`` — treat as possible
  fork evidence!), or the request was malformed. NOT retryable.

The blocking :meth:`sync` wraps the async pair
:meth:`sync_submit`/:meth:`SyncHandle.result`.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from tmtpu.lightserve import protocol as proto

ENV_ADDR = "TMTPU_LIGHTSERVE_ADDR"


def default_addr(home: str = "") -> str:
    """Explicit config addr wins (caller passes it through), then
    ``TMTPU_LIGHTSERVE_ADDR``, then the per-home unix socket."""
    env = os.environ.get(ENV_ADDR, "")
    if env:
        return env
    if home:
        return f"unix://{os.path.join(home, 'data', 'lightserve.sock')}"
    return ""


class LightserveError(Exception):
    pass


class LightserveUnavailable(LightserveError):
    """Daemon unreachable / dead connection / deadline / hard error."""


class LightserveOverloaded(LightserveError):
    """Explicit backpressure: daemon healthy but the session queue is
    full."""


class LightserveRefused(LightserveError):
    """A definitive no: expired trust, conflicting trusted hash, or a
    bad request. Resubmitting the same session cannot succeed."""

    def __init__(self, status: int, message: str):
        super().__init__(message or proto.STATUS_NAMES.get(status,
                                                           str(status)))
        self.status = status


class SyncResult:
    """One answered session."""

    __slots__ = ("target_height", "hops", "dispatches", "cache_hit",
                 "dispatch_id", "coalesced")

    def __init__(self, target_height: int,
                 hops: List[Tuple[int, bytes, int]], dispatches: int,
                 cache_hit: bool, dispatch_id: int, coalesced: int):
        self.target_height = target_height
        # ascending (height, header_hash, header_time), ending at target
        self.hops = hops
        self.dispatches = dispatches
        self.cache_hit = cache_hit
        self.dispatch_id = dispatch_id   # 0 = answered inline from cache
        self.coalesced = coalesced


class _Waiter:
    __slots__ = ("event", "reply", "error")

    def __init__(self):
        self.event = threading.Event()
        self.reply = None
        self.error: Optional[Exception] = None


class SyncHandle:
    """An in-flight session: ``wait`` then ``result`` (or just
    ``result``, which waits)."""

    __slots__ = ("_client", "_rid", "_waiter", "submitted_at")

    def __init__(self, client: "LightserveClient", rid: int,
                 waiter: _Waiter):
        self._client = client
        self._rid = rid
        self._waiter = waiter
        self.submitted_at = time.perf_counter()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._waiter.event.wait(timeout)

    def result(self, deadline_s: Optional[float] = None) -> SyncResult:
        return self._client._collect(self._rid, self._waiter,
                                     deadline_s, self.submitted_at)


class LightserveClient:
    def __init__(self, addr: str, *,
                 client_id: str = "",
                 chain_id: str = "",
                 connect_timeout_s: float = 2.0,
                 request_deadline_s: float = 15.0,
                 retry_backoff_s: float = 1.0,
                 max_frame_bytes: int = proto.DEFAULT_MAX_FRAME_BYTES):
        self.addr = addr
        self.client_id = client_id or f"pid-{os.getpid()}"
        self.chain_id = chain_id       # "" = adopt the server's chain
        self._connect_timeout_s = connect_timeout_s
        self._request_deadline_s = request_deadline_s
        self._retry_backoff_s = retry_backoff_s
        self._max_frame_bytes = max_frame_bytes
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._wlock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._waiters: Dict[int, _Waiter] = {}
        self._waiters_lock = threading.Lock()
        self._seq = itertools.count(1)
        self._last_connect_fail = 0.0
        self.hello_ack: Optional[proto.HelloAck] = None

    # --- connection management ---

    def connected(self) -> bool:
        return self._sock is not None

    def _ensure_connected(self) -> None:
        if self._sock is not None:
            return
        with self._conn_lock:
            if self._sock is not None:
                return
            now = time.monotonic()
            if now - self._last_connect_fail < self._retry_backoff_s:
                raise LightserveUnavailable(
                    f"lightserve {self.addr}: in connect backoff")
            try:
                self._connect_locked()
            except (OSError, proto.ProtocolError, EOFError,
                    ValueError) as exc:
                self._last_connect_fail = time.monotonic()
                raise LightserveUnavailable(
                    f"lightserve {self.addr}: {exc}") from exc

    def _connect_locked(self) -> None:
        from tmtpu.libs import metrics as _m

        _m.lightserve_client_reconnects.inc()
        kind, target = proto.parse_addr(self.addr)
        if kind == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self._connect_timeout_s)
        sock.connect(target)
        rfile = sock.makefile("rb")
        reader = proto.FrameReader(rfile, self._max_frame_bytes)
        sock.sendall(proto.encode_frame(proto.Hello(
            version=proto.PROTOCOL_VERSION, client_id=self.client_id,
            chain_id=self.chain_id)))
        ack = reader.read_msg()
        if isinstance(ack, proto.ErrorReply):
            raise LightserveUnavailable(
                f"lightserve rejected handshake (code {ack.code}): "
                f"{ack.message}")
        if not isinstance(ack, proto.HelloAck):
            raise proto.ProtocolError(
                f"expected HelloAck, got {type(ack).__name__}")
        sock.settimeout(None)  # reader thread blocks; waiters time out
        self.hello_ack = ack
        self._sock = sock
        self._rfile = rfile
        _m.lightserve_client_up.set(1.0)
        threading.Thread(target=self._read_loop, args=(reader, sock),
                         name="lightserve-client-read",
                         daemon=True).start()

    def close(self) -> None:
        with self._conn_lock:
            self._teardown(LightserveUnavailable("client closed"))

    def _teardown(self, err: Exception) -> None:
        from tmtpu.libs import metrics as _m

        sock, self._sock = self._sock, None
        self._rfile = None
        self.hello_ack = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            _m.lightserve_client_up.set(0.0)
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, {}
        for w in waiters.values():
            w.error = err
            w.event.set()

    def _read_loop(self, reader: proto.FrameReader,
                   sock: socket.socket) -> None:
        try:
            while True:
                msg = reader.read_msg()
                rid = getattr(msg, "request_id",
                              getattr(msg, "nonce", 0))
                if isinstance(msg, proto.ErrorReply) and rid == 0:
                    raise LightserveUnavailable(
                        f"lightserve connection error {msg.code}: "
                        f"{msg.message}")
                with self._waiters_lock:
                    w = self._waiters.pop(rid, None)
                if w is not None:
                    w.reply = msg
                    w.event.set()
                # unmatched reply: waiter already timed out — drop it
        except (EOFError, OSError, proto.ProtocolError,
                LightserveUnavailable) as exc:
            with self._conn_lock:
                if self._sock is sock:
                    self._teardown(LightserveUnavailable(
                        f"lightserve connection lost: {exc}"))

    # --- request primitives ---

    def _send(self, rid: int, msg) -> _Waiter:
        w = _Waiter()
        with self._waiters_lock:
            self._waiters[rid] = w
        sock = None
        try:
            data = proto.encode_frame(msg)
            sock = self._sock
            if sock is None:
                raise LightserveUnavailable("lightserve not connected")
            with self._wlock:
                sock.sendall(data)
            return w
        except BaseException as exc:
            # EVERY failure path must unregister the waiter, including
            # the sock-is-None raise above (a connect race would
            # otherwise leak the entry until teardown)
            with self._waiters_lock:
                self._waiters.pop(rid, None)
            if isinstance(exc, OSError):
                with self._conn_lock:
                    if self._sock is sock:
                        self._teardown(LightserveUnavailable(str(exc)))
                raise LightserveUnavailable(
                    f"lightserve send failed: {exc}") from exc
            raise

    def _await(self, rid: int, w: _Waiter, deadline_s: float):
        if not w.event.wait(deadline_s):
            with self._waiters_lock:
                self._waiters.pop(rid, None)
            raise LightserveUnavailable(
                f"lightserve request deadline ({deadline_s:.3f}s) "
                f"exceeded")
        if w.error is not None:
            raise LightserveUnavailable(str(w.error)) from w.error
        return w.reply

    def _roundtrip(self, rid: int, msg, deadline_s: float):
        return self._await(rid, self._send(rid, msg), deadline_s)

    # --- public API ---

    def sync_submit(self, trusted_height: int, trusted_hash: bytes,
                    target_height: int = 0,
                    now_ns: int = 0) -> SyncHandle:
        """Fire one session without blocking; collect it later via the
        handle. Many handles can ride one connection concurrently —
        same-target sessions coalesce server-side."""
        self._ensure_connected()
        rid = next(self._seq)
        w = self._send(rid, proto.SyncRequest(
            request_id=rid, trusted_height=trusted_height,
            trusted_hash=trusted_hash, target_height=target_height,
            now_ns=now_ns))
        return SyncHandle(self, rid, w)

    def _collect(self, rid: int, w: _Waiter,
                 deadline_s: Optional[float],
                 submitted_at: float) -> SyncResult:
        from tmtpu.libs import metrics as _m

        try:
            reply = self._await(rid, w,
                                deadline_s or self._request_deadline_s)
        except LightserveUnavailable:
            _m.lightserve_client_requests.inc(status="error")
            raise
        _m.lightserve_client_request_latency.observe(
            time.perf_counter() - submitted_at)
        if not isinstance(reply, proto.SyncResponse):
            _m.lightserve_client_requests.inc(status="error")
            raise LightserveUnavailable(
                f"unexpected reply {type(reply).__name__}")
        status = proto.STATUS_NAMES.get(reply.status,
                                        str(reply.status))
        _m.lightserve_client_requests.inc(status=status)
        if reply.status == proto.STATUS_OVERLOADED:
            raise LightserveOverloaded(reply.error or "overloaded")
        if reply.status in (proto.STATUS_EXPIRED,
                            proto.STATUS_UNTRUSTED,
                            proto.STATUS_BAD_REQUEST):
            raise LightserveRefused(reply.status, reply.error)
        if reply.status != proto.STATUS_OK:
            raise LightserveUnavailable(
                f"lightserve status {status}: {reply.error}")
        hops = [(h.height, bytes(h.header_hash), h.header_time)
                for h in reply.hops]
        if not hops:
            raise LightserveUnavailable("ok response carried no hops")
        return SyncResult(hops[-1][0], hops, reply.dispatches,
                          reply.cache_hit, reply.dispatch_id,
                          reply.coalesced)

    def sync(self, trusted_height: int, trusted_hash: bytes,
             target_height: int = 0, now_ns: int = 0,
             deadline_s: Optional[float] = None) -> SyncResult:
        """One blocking session: prove ``target_height`` (0 = server's
        latest) from ``(trusted_height, trusted_hash)``."""
        handle = self.sync_submit(trusted_height, trusted_hash,
                                  target_height, now_ns)
        return handle.result(deadline_s)

    def ping(self, deadline_s: Optional[float] = None) -> proto.Pong:
        self._ensure_connected()
        nonce = next(self._seq)
        reply = self._roundtrip(nonce, proto.Ping(nonce=nonce),
                                deadline_s or self._request_deadline_s)
        if not isinstance(reply, proto.Pong):
            raise LightserveUnavailable(
                f"unexpected reply {type(reply).__name__}")
        return reply

    def stats(self, deadline_s: Optional[float] = None) -> Dict:
        """Daemon snapshot; serializes on request id 0 like the sidecar
        stats call — fine for a debug endpoint."""
        self._ensure_connected()
        reply = self._roundtrip(0, proto.StatsRequest(),
                                deadline_s or self._request_deadline_s)
        if not isinstance(reply, proto.StatsResponse):
            raise LightserveUnavailable(
                f"unexpected reply {type(reply).__name__}")
        return json.loads(reply.stats_json.decode())
