"""Lightserve wire protocol: commit-proof sessions over framed messages.

Same framing as the sidecar (``uvarint(len(body)) || type_byte ||
payload``) — the codec itself is imported from
:mod:`tmtpu.sidecar.protocol` with this module's own message registry,
so the two daemons share one tested frame reader without sharing a wire
namespace.

A session is one :class:`SyncRequest`: "I trust ``(trusted_height,
trusted_hash)``; prove ``target_height`` to me." The daemon answers
with the chain of verified-header hops (bisection pivots per
arXiv:2010.07031) from at-or-below the client's trusted height up to
the target, plus accounting: how many device dispatches the answer
actually cost (0 = pure cache hit) and how many concurrent sessions
shared the joint resolve. Frames are small — hops are (height, hash,
time) facts, never validator sets — so the default frame cap is 1 MiB,
not the sidecar's 8.

Handshake: client sends :class:`Hello` first (with the chain id it
expects); server answers :class:`HelloAck` carrying its chain id,
trust anchor, and latest verified height — a cold client with no
social-consensus anchor of its own can adopt the server's. Version
negotiation mirrors the sidecar: min(client, server), ``ERR_VERSION``
on unsupported.
"""

from __future__ import annotations

from typing import Dict, Type

from tmtpu.libs.protoio import ProtoMessage
from tmtpu.sidecar.protocol import (  # noqa: F401 — re-exported codec
    ProtocolError,
    encode_uvarint,
    parse_addr,
)
from tmtpu.sidecar import protocol as _sidecar_proto

PROTOCOL_VERSION = 1
SUPPORTED_VERSIONS = (1,)

# Proof frames carry (height, hash, time) hops, not lanes: 1 MiB covers
# a ~17k-hop chain with room, far past any O(log N) bisection path.
DEFAULT_MAX_FRAME_BYTES = 1 * 1024 * 1024

# --- SyncResponse.status ---
STATUS_OK = 0
STATUS_OVERLOADED = 1      # admission control rejected; retry later
STATUS_UPSTREAM_DOWN = 2   # provider unreachable / verification engine failed
STATUS_BAD_REQUEST = 3     # zero target, malformed hash
STATUS_SHUTTING_DOWN = 4   # daemon draining; do not resubmit
STATUS_EXPIRED = 5         # no trusted state fresh enough to prove the target
STATUS_UNTRUSTED = 6       # client's trusted hash conflicts with the spine

STATUS_NAMES = {
    STATUS_OK: "ok",
    STATUS_OVERLOADED: "overloaded",
    STATUS_UPSTREAM_DOWN: "upstream_down",
    STATUS_BAD_REQUEST: "bad_request",
    STATUS_SHUTTING_DOWN: "shutting_down",
    STATUS_EXPIRED: "expired",
    STATUS_UNTRUSTED: "untrusted",
}

# --- ErrorReply.code --- (numbering shared with the sidecar protocol)
ERR_VERSION = 1
ERR_PROTOCOL = 2
ERR_INTERNAL = 3


class Hello(ProtoMessage):
    FIELDS = [
        (1, "version", "uint32"),
        (2, "client_id", "string"),
        (3, "chain_id", "string"),           # "" = accept server's chain
    ]


class HelloAck(ProtoMessage):
    FIELDS = [
        (1, "version", "uint32"),
        (2, "server_id", "string"),
        (3, "chain_id", "string"),
        (4, "anchor_height", "uint64"),      # the daemon's trust anchor…
        (5, "anchor_hash", "bytes"),         # …a cold client can adopt it
        (6, "latest_height", "uint64"),      # top of the verified spine
        (7, "max_frame_bytes", "uint64"),
    ]


class SyncRequest(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),
        (2, "trusted_height", "uint64"),
        (3, "trusted_hash", "bytes"),
        (4, "target_height", "uint64"),      # 0 = server's latest
        # the client's wall clock, ONLY a skew check: the server
        # refuses bad_request when it strays past max_client_skew_ns,
        # but trust expiry is always judged on the SERVER clock (a
        # client value must never evict shared cache facts). 0 = skip.
        (5, "now_ns", "uint64"),
    ]


class Hop(ProtoMessage):
    """One verified-header fact on the server's bisection path."""

    FIELDS = [
        (1, "height", "uint64"),
        (2, "header_hash", "bytes"),
        (3, "header_time", "int64"),
    ]


class SyncResponse(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),
        (2, "status", "uint32"),
        (3, "hops", ("rep", ("msg", Hop))),  # ascending, ends at target
        (4, "dispatches", "uint32"),         # device dispatches this answer cost
        (5, "cache_hit", "bool"),            # target served straight from cache
        (6, "dispatch_id", "uint64"),        # joint-resolve identity (0 = inline)
        (7, "coalesced", "uint32"),          # sessions sharing the resolve
        (8, "error", "string"),
    ]


class Ping(ProtoMessage):
    FIELDS = [(1, "nonce", "uint64")]


class Pong(ProtoMessage):
    FIELDS = [
        (1, "nonce", "uint64"),
        (2, "latest_height", "uint64"),
        (3, "uptime_ms", "uint64"),
    ]


class StatsRequest(ProtoMessage):
    FIELDS = []


class StatsResponse(ProtoMessage):
    """Introspection snapshot; JSON so the payload can grow without
    protocol bumps (advisory, not consensus-critical)."""

    FIELDS = [(1, "stats_json", "bytes")]


class ErrorReply(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),         # 0 when not tied to a request
        (2, "code", "uint32"),
        (3, "message", "string"),
    ]


# type_byte → message class. Wire-visible; never reuse a number.
MESSAGE_TYPES: Dict[int, Type[ProtoMessage]] = {
    1: Hello,
    2: HelloAck,
    3: SyncRequest,
    4: SyncResponse,
    5: Ping,
    6: Pong,
    7: StatsRequest,
    8: StatsResponse,
    9: ErrorReply,
    10: Hop,
}

TYPE_BYTES: Dict[Type[ProtoMessage], int] = {
    cls: tb for tb, cls in MESSAGE_TYPES.items()
}


def encode_frame(msg: ProtoMessage) -> bytes:
    return _sidecar_proto.encode_frame(msg, TYPE_BYTES)


def decode_frame(body: bytes) -> ProtoMessage:
    return _sidecar_proto.decode_frame(body, MESSAGE_TYPES)


def write_frame(stream, msg: ProtoMessage) -> None:
    _sidecar_proto.write_frame(stream, msg, TYPE_BYTES)


class FrameReader(_sidecar_proto.FrameReader):
    """Sidecar frame reader bound to the lightserve message registry."""

    def __init__(self, stream,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        super().__init__(stream, max_frame_bytes,
                         message_types=MESSAGE_TYPES)
