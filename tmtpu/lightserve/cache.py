"""Bounded verified-at-height fact cache, trust-period aware.

A :class:`Fact` is the distilled outcome of one light-client
verification: "header ``header_hash`` at ``height`` (time
``header_time``) is verified, reached from ``parent_height``". Facts
are tiny (no validator sets, no commits), so the cache holds orders of
magnitude more heights than the LightStore spine can afford to keep as
full blocks.

Two queries matter at serving time:

- :meth:`get` — is this exact height verified and still inside the
  trusting period? Expiry is checked at READ time with the verifier's
  own :func:`~tmtpu.light.verifier.header_expired` boundary
  (``header_time + trusting_period_ns <= now_ns``): a fact that was
  fresh when cached is refused — and evicted — the instant the trust
  period lapses, never served stale.
- :meth:`hop_chain` — the precomputed bisection path. Every fact
  remembers the height it was verified FROM, so the path from any
  lower trusted height to a cached target is a parent-pointer walk:
  O(log N) hops handed out with zero dispatches, zero provider calls.

Keys: one cache serves one chain (``chain_id`` is pinned at
construction and part of every fact's identity triple ``(chain_id,
height, header_hash)``); capacity is bounded LRU over lookups and
inserts.

The sorted height index (``_heights``, backing the ``nearest_*``
range queries) uses lazy deletion: evictions only drop the fact and
bump a stale counter, and the index is rebuilt in one O(N log N) pass
once stale entries outnumber live ones. A miss-heavy workload at
``max_facts`` therefore costs O(log N) amortized per insert/evict
under the serving lock, never an O(N) list scan per eviction.
"""

from __future__ import annotations

import threading
from bisect import bisect_left, bisect_right
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


class Fact:
    """One verified-height fact (identity: (chain_id, height, hash))."""

    __slots__ = ("height", "header_hash", "header_time", "parent_height")

    def __init__(self, height: int, header_hash: bytes, header_time: int,
                 parent_height: int):
        self.height = int(height)
        self.header_hash = bytes(header_hash)
        self.header_time = int(header_time)
        # the verified height this fact's verification hopped from;
        # 0 for the trust anchor itself
        self.parent_height = int(parent_height)

    def expired(self, trusting_period_ns: int, now_ns: int) -> bool:
        """Same boundary as verifier.header_expired: expired AT exactly
        ``header_time + trusting_period_ns``."""
        return self.header_time + trusting_period_ns <= now_ns

    def __repr__(self) -> str:  # debugging / test failure readability
        return (f"Fact(h={self.height}, hash={self.header_hash.hex()[:8]}, "
                f"parent={self.parent_height})")


class VerifiedFactCache:
    def __init__(self, chain_id: str, trusting_period_ns: int,
                 max_facts: int = 200_000):
        if max_facts < 1:
            raise ValueError("max_facts must be >= 1")
        self.chain_id = chain_id
        self.trusting_period_ns = int(trusting_period_ns)
        self.max_facts = max_facts
        self._lock = threading.Lock()
        self._facts: "OrderedDict[int, Fact]" = OrderedDict()
        # sorted height index; may lag _facts by lazily-deleted entries
        # (heights whose fact was evicted), compacted once _stale wins
        self._heights: List[int] = []
        self._stale = 0
        self.hits = 0
        self.misses = 0
        self.expired = 0

    def key(self, fact: Fact) -> Tuple[str, int, bytes]:
        return (self.chain_id, fact.height, fact.header_hash)

    # -- writes --------------------------------------------------------------

    def put(self, fact: Fact, now_ns: int) -> bool:
        """Cache a fact unless its trust already lapsed (a re-verified
        expired height is served but NOT re-cached — it would only be
        refused again on the next read). Returns True when stored."""
        if fact.expired(self.trusting_period_ns, now_ns):
            return False
        with self._lock:
            if fact.height not in self._facts:
                i = bisect_left(self._heights, fact.height)
                if i < len(self._heights) and \
                        self._heights[i] == fact.height:
                    self._stale -= 1   # resurrected a lazy-deleted slot
                else:
                    self._heights.insert(i, fact.height)
            self._facts[fact.height] = fact
            self._facts.move_to_end(fact.height)
            while len(self._facts) > self.max_facts:
                self._facts.popitem(last=False)
                self._stale += 1
            self._maybe_compact_locked()
            return True

    def _evict_locked(self, height: int) -> None:
        if height in self._facts:
            del self._facts[height]
            self._stale += 1
            self._maybe_compact_locked()

    def _maybe_compact_locked(self) -> None:
        """Rebuild the height index once lazily-deleted entries
        outnumber live ones (amortized O(log N) per eviction)."""
        if self._stale > 64 and self._stale * 2 > len(self._heights):
            self._heights = sorted(self._facts)
            self._stale = 0

    # -- reads ---------------------------------------------------------------

    def get(self, height: int, now_ns: int) -> Optional[Fact]:
        """The fresh fact at exactly ``height``, or None. An expired fact
        is refused AND evicted (counted in ``expired``, not ``misses``)."""
        from tmtpu.libs import metrics as _m

        with self._lock:
            fact = self._facts.get(height)
            if fact is None:
                self.misses += 1
                _m.lightserve_server_cache_misses.inc()
                return None
            if fact.expired(self.trusting_period_ns, now_ns):
                self._evict_locked(height)
                self.expired += 1
                _m.lightserve_server_cache_expired.inc()
                return None
            self._facts.move_to_end(height)
            self.hits += 1
            _m.lightserve_server_cache_hits.inc()
            return fact

    def peek(self, height: int) -> Optional[Fact]:
        """Lookup without expiry check, LRU touch, or counters (used for
        trusted-hash validation, where an expired fact still proves a
        client is on a fork)."""
        with self._lock:
            return self._facts.get(height)

    def nearest_at_or_below(self, height: int, now_ns: int
                            ) -> Optional[Fact]:
        """Highest fresh fact at or below ``height`` — the bisection
        anchor candidate. Expired candidates encountered on the way down
        are evicted (older headers only ever get MORE expired)."""
        from tmtpu.libs import metrics as _m

        with self._lock:
            i = bisect_right(self._heights, height)
            found = None
            while i > 0:
                i -= 1
                fact = self._facts.get(self._heights[i])
                if fact is None:
                    continue   # lazily-deleted index entry
                if not fact.expired(self.trusting_period_ns, now_ns):
                    found = fact
                    break
                del self._facts[fact.height]
                self._stale += 1
                self.expired += 1
                _m.lightserve_server_cache_expired.inc()
            self._maybe_compact_locked()
            return found

    def nearest_above(self, height: int, now_ns: int) -> Optional[Fact]:
        """Lowest fresh fact strictly above ``height`` — the hash-link
        re-verification anchor once everything at-or-below expired."""
        with self._lock:
            i = bisect_right(self._heights, height)
            while i < len(self._heights):
                fact = self._facts.get(self._heights[i])
                if fact is not None and \
                        not fact.expired(self.trusting_period_ns, now_ns):
                    return fact
                i += 1   # don't evict: higher fresh facts may follow
            return None

    def hop_chain(self, from_height: int, to_height: int
                  ) -> Optional[List[Fact]]:
        """The cached bisection path: facts from just above
        ``from_height`` up to ``to_height`` inclusive, ascending, linked
        by parent pointers. None when the walk hits a missing fact
        (evicted mid-chain) — the caller re-resolves."""
        with self._lock:
            chain: List[Fact] = []
            h = to_height
            while h > from_height:
                fact = self._facts.get(h)
                if fact is None:
                    return None
                chain.append(fact)
                if fact.parent_height >= h:   # corrupt pointer guard
                    return None
                h = fact.parent_height
            chain.reverse()
            return chain

    # -- introspection -------------------------------------------------------

    def size(self) -> int:
        with self._lock:
            return len(self._facts)

    def lookups(self) -> int:
        with self._lock:
            return self.hits + self.misses + self.expired

    def _bound_locked(self, highest: bool) -> int:
        it = reversed(self._heights) if highest else iter(self._heights)
        for h in it:
            if h in self._facts:   # skip lazily-deleted index entries
                return h
        return 0

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "chain_id": self.chain_id,
                "facts": len(self._facts),
                "max_facts": self.max_facts,
                "lowest": self._bound_locked(False),
                "highest": self._bound_locked(True),
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
            }
