"""Light-client commit-proof serving tier.

One daemon process terminates many concurrent light-client sessions
against a single chain: it keeps its own verified spine (a LightStore
anchored at social-consensus TrustOptions), folds concurrent sync
requests for the same target height into ONE joint verification via a
height-keyed coalescer, and answers repeat queries from a bounded
trust-period-aware verified-height fact cache — so the Nth client
asking about a height costs zero device dispatches.

Deployment shape mirrors :mod:`tmtpu.sidecar`: a socket daemon
(``python -m tmtpu.cmd lightserve``) speaking a length-prefixed frame
protocol, plus an optional HTTP listener for ``/healthz`` and
``/metrics``.
"""

from tmtpu.lightserve.cache import Fact, VerifiedFactCache  # noqa: F401
from tmtpu.lightserve.client import (  # noqa: F401
    LightserveClient,
    LightserveError,
    LightserveOverloaded,
    LightserveRefused,
    LightserveUnavailable,
)
from tmtpu.lightserve.server import LightserveServer  # noqa: F401
