"""The lightserve daemon: commit-proof serving for many light clients.

One daemon process terminates light-client sessions for one chain. It
maintains its own verified spine — a :class:`~tmtpu.light.store.LightStore`
anchored at social-consensus :class:`~tmtpu.light.client.TrustOptions`,
fed by a :class:`~tmtpu.light.provider.Provider` (a full node's RPC in
production) — plus the :class:`~tmtpu.lightserve.cache.VerifiedFactCache`
of everything it has ever proven.

Request path, cheapest first:

1. **Cache hit** — the target height's fact is cached and inside the
   trusting period: answered INLINE on the connection thread (no
   coalescer, no reply pool), hop chain cut from parent pointers.
   This is the path that must hold at 10k+ concurrent sessions.
2. **Joint resolve** — cold target: the session queues in the
   height-keyed :class:`~tmtpu.lightserve.coalescer.SyncCoalescer`;
   one bisection resolve (the verifier's skipping algorithm, every hop
   a batched commit verify) serves every session waiting on that
   height, and each verified pivot becomes a cached fact.
3. **Expired target** — the fact's trusting period lapsed: the cache
   refuses it, and the resolve re-verifies the height by hash-linking
   backwards from the nearest still-fresh header
   (:func:`~tmtpu.light.verifier.verify_backwards`). The re-verified
   fact is NOT re-cached — it is expired by definition and would only
   be refused again — so each request for a lapsed height pays its own
   re-verification.

Trust expiry is judged on the SERVER clock, always. A client's
``SyncRequest.now_ns`` is only checked against the server clock
(rejected ``bad_request`` past ``max_client_skew_ns``) — it is never
used for cache reads/evictions or joint resolves, because the shared
cache and every coalesced peer would otherwise be at the mercy of one
unauthenticated client's clock. Cold sessions are answered by a small
fixed reply pool fed by coalescer completion hooks, not by
per-session threads.

Introspection mirrors the sidecar daemon: ``Ping``/``StatsRequest`` on
the protocol socket, optional HTTP ``/healthz`` (verdict from
``libs.watchdog.lightserve_check``: cache hit-rate floor + session
backlog ceiling) and ``/metrics``.

Run it: ``python -m tmtpu.cmd lightserve --addr tcp://127.0.0.1:26680
--upstream http://127.0.0.1:26657 --chain-id ... --trust-height 1
--trust-hash <hex>``.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tmtpu.light import provider as prov
from tmtpu.light import verifier
from tmtpu.light.client import DEFAULT_MAX_CLOCK_DRIFT_NS, TrustOptions
from tmtpu.light.store import LightStore
from tmtpu.light.verifier import ErrNewValSetCantBeTrusted
from tmtpu.lightserve import protocol as proto
from tmtpu.lightserve.cache import Fact, VerifiedFactCache
from tmtpu.lightserve.coalescer import (
    Overloaded,
    PendingSync,
    SyncCoalescer,
)
from tmtpu.types.light_block import LightBlock

_FAILURE_STATUS = {
    "expired": proto.STATUS_OVERLOADED,
    "engine": proto.STATUS_UPSTREAM_DOWN,
    "stopped": proto.STATUS_SHUTTING_DOWN,
}

# client.go:40 verifySkipping pivot — mirrored from light/client.py
_PIVOT_NUM, _PIVOT_DEN = 1, 2


class Resolution:
    """Outcome of one joint target-height resolve."""

    __slots__ = ("status", "error", "dispatches", "fact", "now_ns",
                 "cache_hit", "hops_override")

    def __init__(self, status: int, dispatches: int = 0,
                 fact: Optional[Fact] = None, now_ns: int = 0,
                 cache_hit: bool = False, error: str = "",
                 hops_override: Optional[List[Fact]] = None):
        self.status = status
        self.dispatches = dispatches
        self.fact = fact
        self.now_ns = now_ns
        self.cache_hit = cache_hit
        self.error = error
        # backwards re-verification builds its chain outside the fact
        # cache (expired facts are never re-cached)
        self.hops_override = hops_override


class _ReplyPool:
    """Bounded pool of reply senders for cold sessions.

    Cold sessions complete on the coalescer thread, which must never
    block on a slow client socket; per-session reply threads (the old
    shape) explode at high cold-session volume and die in
    ``Thread.start``. Instead the coalescer's ``on_done`` hook enqueues
    the finished session here and a FIXED set of workers drains the
    queue. The queue itself needs no cap: each admitted session
    enqueues at most one job, and admission is already bounded by the
    coalescer's ``max_queue_sessions``."""

    def __init__(self, workers: int):
        self.workers = workers
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        if self._threads:
            return
        for i in range(self.workers):
            t = threading.Thread(target=self._run,
                                 name=f"lightserve-reply-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        # sentinels queue BEHIND any leftover failure replies the
        # coalescer enqueued during its own stop, so those still drain
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    def put(self, job) -> None:
        self._q.put(job)

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                job()
            except Exception:  # noqa: BLE001 — one bad reply must not
                pass           # kill the worker


class LightserveServer:
    def __init__(self, addr: str, provider: prov.Provider,
                 trust_options: TrustOptions, chain_id: str, *,
                 backend: Optional[str] = None,
                 trust_level: Tuple[int, int] = verifier.DEFAULT_TRUST_LEVEL,
                 max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
                 cache_max_facts: int = 200_000,
                 store_max_blocks: int = 10_000,
                 max_queue_sessions: int = 65536,
                 max_frame_bytes: int = proto.DEFAULT_MAX_FRAME_BYTES,
                 request_deadline_s: float = 10.0,
                 backwards_limit: int = 1024,
                 health_laddr: str = "",
                 server_id: str = "",
                 hit_rate_floor: float = 0.5,
                 hit_rate_min_lookups: int = 64,
                 backlog_ceiling: int = 4096,
                 max_client_skew_ns: int = 10_000_000_000,
                 reply_workers: int = 8,
                 clock: Callable[[], int] = time.time_ns):
        from tmtpu.libs.db import MemDB

        trust_options.validate_basic()
        verifier.validate_trust_level(*trust_level)
        self.addr = addr
        self._kind, self._target = proto.parse_addr(addr)
        self.provider = provider
        self.trust_options = trust_options
        self.chain_id = chain_id
        self.backend = backend
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self._store = LightStore(MemDB())
        self._store_max_blocks = store_max_blocks
        self.cache = VerifiedFactCache(
            chain_id, trust_options.period_ns, max_facts=cache_max_facts)
        self._max_queue_sessions = max_queue_sessions
        self._max_frame_bytes = max_frame_bytes
        self._default_deadline_s = request_deadline_s
        self._backwards_limit = backwards_limit
        self._health_laddr = health_laddr
        self.server_id = server_id or f"lightserve-{os.getpid()}"
        self._hit_rate_floor = hit_rate_floor
        self._hit_rate_min_lookups = hit_rate_min_lookups
        self._backlog_ceiling = backlog_ceiling
        # the SERVER clock is the only expiry clock: it drives every
        # cache read/eviction and joint-resolve decision. A client's
        # now_ns is only a skew CHECK (see _handle_sync), never an
        # input — an unauthenticated far-future clock must not be able
        # to evict shared facts or poison coalesced peers. Injectable
        # for tests (the only supported way to pin time).
        self._clock = clock
        self._max_client_skew_ns = max_client_skew_ns
        self._reply_pool = _ReplyPool(max(1, reply_workers))
        self.coalescer = SyncCoalescer(
            self._resolve, self._slice,
            max_queue_sessions=max_queue_sessions)
        self.provider_calls = 0
        self.sessions_served = 0
        self._resolve_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._health_httpd = None
        self._health_thread: Optional[threading.Thread] = None
        self._health_check = None     # wired in start()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._running = False
        self._draining = False
        self._started_at = 0.0
        self._anchor_fact: Optional[Fact] = None

    # --- the verified spine -------------------------------------------------

    def _fetch(self, height: Optional[int]) -> LightBlock:
        self.provider_calls += 1
        lb = self.provider.light_block(height)
        if height is not None and lb.height() != height:
            raise prov.ErrBadLightBlock(
                f"expected height {height}, got {lb.height()}")
        lb.validate_basic(self.chain_id)
        return lb

    def _save(self, lb: LightBlock, fact: Fact, now_ns: int) -> None:
        self._store.save_light_block(lb)
        if self._store.size() > self._store_max_blocks:
            self._store.prune(self._store_max_blocks)
        self.cache.put(fact, now_ns)

    def init_anchor(self, now_ns: Optional[int] = None) -> Fact:
        """Fetch and verify the trust anchor (client.go:362
        initializeWithTrustOptions, server-side)."""
        from tmtpu.libs import metrics as _m
        from tmtpu.types import commit_verify

        now_ns = now_ns if now_ns is not None else self._clock()
        lb = self._fetch(self.trust_options.height)
        if lb.header.hash() != self.trust_options.hash:
            raise verifier.LightError(
                f"anchor hash mismatch at height "
                f"{self.trust_options.height}: expected "
                f"{self.trust_options.hash.hex().upper()}, got "
                f"{lb.header.hash().hex().upper()}")
        commit_verify.verify_commit_light_trusting(
            lb.validator_set, self.chain_id, lb.commit,
            self.trust_level[0], self.trust_level[1],
            backend=self.backend)
        _m.lightserve_server_dispatches_total.inc()
        fact = Fact(lb.height(), lb.header.hash(), lb.header.time,
                    parent_height=0)
        self._save(lb, fact, now_ns)
        self._anchor_fact = fact
        return fact

    def latest_height(self) -> int:
        return self._store.last_light_block_height()

    def update_to_latest(self, now_ns: Optional[int] = None) -> int:
        """Advance the spine to the provider's tip (one joint-style
        resolve, same dispatch accounting). Returns the new tip height."""
        now_ns = now_ns if now_ns is not None else self._clock()
        tip = self._fetch(None)
        if tip.height() > self.latest_height():
            res = self._resolve(tip.height(), now_ns)
            if res.status != proto.STATUS_OK:
                raise verifier.LightError(
                    f"update to {tip.height()} failed: "
                    f"{proto.STATUS_NAMES.get(res.status)} {res.error}")
        return self.latest_height()

    # --- resolve engine (runs on the coalescer thread) ----------------------

    def _anchor_below(self, target: int, now_ns: int
                      ) -> Tuple[Optional[Fact], Optional[LightBlock]]:
        """Highest spine block at-or-below ``target`` whose trust is
        still fresh. A stored block with an evicted fact is still a
        verified anchor — its fact is synthesized (parent unknown)."""
        h = target + 1
        while True:
            lb = self._store.light_block_before(h)
            if lb is None:
                return None, None
            fact = self.cache.peek(lb.height())
            if fact is None:
                fact = Fact(lb.height(), lb.header.hash(),
                            lb.header.time, parent_height=0)
            if not fact.expired(self.cache.trusting_period_ns, now_ns):
                return fact, lb
            h = lb.height()

    def _verify_hop(self, verified: LightBlock, untrusted: LightBlock,
                    now_ns: int) -> int:
        """One skipping hop; returns the device dispatches it cost
        (adjacent = 1 commit verify, non-adjacent = 2, a failed trust
        check = 1). Raises exactly like verifier.verify."""
        from tmtpu.libs import metrics as _m

        period = self.trust_options.period_ns
        if untrusted.height() == verified.height() + 1:
            _m.lightserve_server_dispatches_total.inc()
            verifier.verify_adjacent(
                verified.signed_header, untrusted.signed_header,
                untrusted.validator_set, period, now_ns,
                self.max_clock_drift_ns, backend=self.backend)
            return 1
        try:
            _m.lightserve_server_dispatches_total.inc(2)
            verifier.verify_non_adjacent(
                verified.signed_header, verified.validator_set,
                untrusted.signed_header, untrusted.validator_set,
                period, now_ns, self.max_clock_drift_ns,
                self.trust_level, backend=self.backend)
            return 2
        except ErrNewValSetCantBeTrusted:
            # the second (new-valset) dispatch never ran
            _m.lightserve_server_dispatches_total.inc(-1)
            raise

    def _resolve(self, target: int, now_ns: int) -> Resolution:
        """Joint resolve for one target height. Serialized: concurrent
        resolves would race on the spine (single coalescer thread plus
        update_to_latest callers)."""
        with self._resolve_lock:
            return self._resolve_locked(target, now_ns)

    def _resolve_locked(self, target: int, now_ns: int) -> Resolution:
        fact = self.cache.get(target, now_ns)
        if fact is not None:
            return Resolution(proto.STATUS_OK, 0, fact, now_ns,
                              cache_hit=True)
        anchor_fact, anchor_lb = self._anchor_below(target, now_ns)
        if anchor_fact is None:
            return self._resolve_backwards(target, now_ns)
        if anchor_fact.height == target:
            # stored and fresh, only the fact was evicted: re-cache it
            self.cache.put(anchor_fact, now_ns)
            return Resolution(proto.STATUS_OK, 0, anchor_fact, now_ns,
                              cache_hit=True)
        dispatches = 0
        try:
            target_lb = self._fetch(target)
            # verifier's skipping algorithm (light/client.py
            # _verify_skipping), with dispatch accounting and every
            # verified pivot persisted as a fact
            block_cache = [target_lb]
            depth = 0
            verified = anchor_lb
            while True:
                try:
                    dispatches += self._verify_hop(
                        verified, block_cache[depth], now_ns)
                except ErrNewValSetCantBeTrusted:
                    dispatches += 1
                    if depth == len(block_cache) - 1:
                        pivot = verified.height() + \
                            (block_cache[depth].height() -
                             verified.height()) * _PIVOT_NUM // _PIVOT_DEN
                        block_cache.append(self._fetch(pivot))
                    depth += 1
                    continue
                newly = block_cache[depth]
                new_fact = Fact(newly.height(), newly.header.hash(),
                                newly.header.time, verified.height())
                self._save(newly, new_fact, now_ns)
                if depth == 0:
                    return Resolution(proto.STATUS_OK, dispatches,
                                      new_fact, now_ns)
                verified = newly
                block_cache = block_cache[:depth]
                depth = 0
        except verifier.ErrOldHeaderExpired as exc:
            return Resolution(proto.STATUS_EXPIRED, dispatches,
                              error=str(exc), now_ns=now_ns)
        except (verifier.LightError, prov.ProviderError,
                ValueError) as exc:
            return Resolution(proto.STATUS_UPSTREAM_DOWN, dispatches,
                              error=str(exc), now_ns=now_ns)

    def _resolve_backwards(self, target: int, now_ns: int) -> Resolution:
        """Everything at-or-below the target has lapsed: re-verify via
        the hash-link walk from the nearest still-fresh header above
        (verifier.verify_backwards — zero signature dispatches). The
        result is served but never re-cached."""
        above = self.cache.nearest_above(target, now_ns)
        if above is None:
            return Resolution(
                proto.STATUS_EXPIRED, 0, now_ns=now_ns,
                error=f"no trusted state fresh enough to prove height "
                      f"{target} (trusting period lapsed)")
        if above.height - target > self._backwards_limit:
            return Resolution(
                proto.STATUS_EXPIRED, 0, now_ns=now_ns,
                error=f"height {target} is {above.height - target} below "
                      f"the freshest trusted header (backwards limit "
                      f"{self._backwards_limit})")
        cur = self._store.light_block(above.height)
        if cur is None:
            return Resolution(
                proto.STATUS_UPSTREAM_DOWN, 0, now_ns=now_ns,
                error=f"fresh fact at {above.height} has no spine block")
        try:
            target_lb: Optional[LightBlock] = None
            for h in range(above.height - 1, target - 1, -1):
                interim = self._fetch(h)
                verifier.verify_backwards(interim.signed_header,
                                          cur.signed_header)
                cur = interim
                target_lb = interim
        except (verifier.LightError, prov.ProviderError,
                ValueError) as exc:
            return Resolution(proto.STATUS_UPSTREAM_DOWN, 0,
                              error=str(exc), now_ns=now_ns)
        fact = Fact(target_lb.height(), target_lb.header.hash(),
                    target_lb.header.time, parent_height=0)
        return Resolution(proto.STATUS_OK, 0, fact, now_ns,
                          hops_override=[fact])

    # --- per-session slicing (coalescer + inline fast path) -----------------

    def _slice(self, req: PendingSync, res: Resolution) -> None:
        """Fill one session's result from the joint resolution: ITS hop
        chain, cut from the fact cache's parent pointers at ITS trusted
        height."""
        if res.status != proto.STATUS_OK:
            req.status = res.status
            req.error = res.error
            return
        known = self.cache.peek(req.trusted_height)
        if known is not None and req.trusted_hash and \
                known.header_hash != req.trusted_hash:
            req.status = proto.STATUS_UNTRUSTED
            req.error = (f"trusted hash at height {req.trusted_height} "
                         f"conflicts with the verified spine")
            return
        target = res.fact.height
        if res.hops_override is not None:
            hops = [f for f in res.hops_override
                    if f.height > req.trusted_height
                    or f.height == target]
        elif target <= req.trusted_height:
            hops = [res.fact]
        else:
            hops = self.cache.hop_chain(req.trusted_height, target)
            if hops is None:   # chain broken by LRU eviction mid-walk
                hops = [res.fact]
        req.status = proto.STATUS_OK
        req.hops = hops
        req.dispatches = res.dispatches
        req.cache_hit = res.cache_hit

    # --- lifecycle ----------------------------------------------------------

    def start(self, init_anchor: bool = True) -> None:
        if self._running:
            return
        if init_anchor and self._anchor_fact is None:
            self.init_anchor()
        if self._kind == "unix":
            path = self._target
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.bind(path)
        else:
            host, port = self._target
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            if port == 0:
                port = sock.getsockname()[1]
                self._target = (host, port)
                self.addr = f"tcp://{host}:{port}"
        sock.listen(128)
        self._listener = sock
        self._running = True
        self._started_at = time.monotonic()
        self._reply_pool.start()
        self.coalescer.start()
        from tmtpu.libs import watchdog as _wd

        self._health_check = _wd.lightserve_check(
            self.health_snapshot,
            hit_rate_floor=self._hit_rate_floor,
            min_lookups=self._hit_rate_min_lookups,
            backlog_ceiling=self._backlog_ceiling)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="lightserve-accept",
            daemon=True)
        self._accept_thread.start()
        if self._health_laddr:
            self._start_health_http()

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop taking new sessions (subsequent SyncRequests answer
        STATUS_OVERLOADED), finish what's queued. Ping/Stats keep
        working. Call stop() afterwards."""
        self._draining = True
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        return self.coalescer.drain(timeout)

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # coalescer first: its stop() finishes leftover sessions, whose
        # on_done hooks enqueue failure replies the pool then drains
        # ahead of its shutdown sentinels
        self.coalescer.stop()
        self._reply_pool.stop()
        if self._health_httpd is not None:
            try:
                self._health_httpd.shutdown()
                self._health_httpd.server_close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            self._health_httpd = None
        ht = self._health_thread
        if ht is not None and ht is not threading.current_thread():
            ht.join(timeout=2.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        if self._kind == "unix":
            try:
                os.unlink(self._target)
            except OSError:
                pass

    # --- introspection ------------------------------------------------------

    def health_snapshot(self) -> Dict:
        """The compact shape libs.watchdog.lightserve_check judges."""
        cache = self.cache.snapshot()
        return {
            "cache_hits": cache["hits"],
            "cache_misses": cache["misses"],
            "cache_expired": cache["expired"],
            "backlog": self.coalescer.backlog(),
        }

    def snapshot(self) -> Dict:
        with self._conns_lock:
            n_conns = len(self._conns)
        return {
            "server_id": self.server_id,
            "addr": self.addr,
            "chain_id": self.chain_id,
            "draining": self._draining,
            "uptime_s": round(max(0.0, time.monotonic() -
                                  self._started_at), 3),
            "connections": n_conns,
            "anchor_height": self.trust_options.height,
            "latest_height": self.latest_height(),
            "spine_blocks": self._store.size(),
            "provider_calls": self.provider_calls,
            "sessions_served": self.sessions_served,
            "cache": self.cache.snapshot(),
            "coalescer": self.coalescer.snapshot(),
        }

    # --- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        from tmtpu.libs import metrics as _m

        while self._running:
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _peer = listener.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
                _m.lightserve_server_connections.set(len(self._conns))
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="lightserve-conn", daemon=True).start()

    def _drop_conn(self, conn) -> None:
        from tmtpu.libs import metrics as _m

        with self._conns_lock:
            self._conns.discard(conn)
            _m.lightserve_server_connections.set(len(self._conns))
        try:
            conn.close()
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        from tmtpu.libs import metrics as _m

        rfile = conn.makefile("rb")
        wlock = threading.Lock()

        def send(msg) -> None:
            data = proto.encode_frame(msg)
            with wlock:
                conn.sendall(data)

        reader = proto.FrameReader(rfile, self._max_frame_bytes)
        try:
            try:
                first = reader.read_msg()
            except proto.ProtocolError as exc:
                _m.lightserve_server_protocol_errors.inc(kind="bad-frame")
                try:
                    send(proto.ErrorReply(code=proto.ERR_PROTOCOL,
                                          message=str(exc)))
                except OSError:
                    pass
                return
            if not isinstance(first, proto.Hello):
                _m.lightserve_server_protocol_errors.inc(kind="no-hello")
                send(proto.ErrorReply(
                    code=proto.ERR_PROTOCOL,
                    message=f"expected Hello, got "
                            f"{type(first).__name__}"))
                return
            if first.version not in proto.SUPPORTED_VERSIONS:
                _m.lightserve_server_protocol_errors.inc(
                    kind="version-mismatch")
                send(proto.ErrorReply(
                    code=proto.ERR_VERSION,
                    message=f"protocol version {first.version} not in "
                            f"server-supported "
                            f"{list(proto.SUPPORTED_VERSIONS)}"))
                return
            if first.chain_id and first.chain_id != self.chain_id:
                _m.lightserve_server_protocol_errors.inc(
                    kind="chain-mismatch")
                send(proto.ErrorReply(
                    code=proto.ERR_PROTOCOL,
                    message=f"daemon serves chain {self.chain_id!r}, "
                            f"not {first.chain_id!r}"))
                return
            client_id = first.client_id or "anon"
            _m.lightserve_server_requests.inc(type="hello")
            anchor = self._anchor_fact
            send(proto.HelloAck(
                version=min(first.version, proto.PROTOCOL_VERSION),
                server_id=self.server_id,
                chain_id=self.chain_id,
                anchor_height=self.trust_options.height,
                anchor_hash=anchor.header_hash if anchor
                else self.trust_options.hash,
                latest_height=max(0, self.latest_height()),
                max_frame_bytes=self._max_frame_bytes))
            while self._running:
                try:
                    msg = reader.read_msg()
                except proto.ProtocolError as exc:
                    _m.lightserve_server_protocol_errors.inc(
                        kind="bad-frame")
                    try:
                        send(proto.ErrorReply(code=proto.ERR_PROTOCOL,
                                              message=str(exc)))
                    except OSError:
                        pass
                    return  # framing is lost; the stream cannot recover
                if isinstance(msg, proto.SyncRequest):
                    _m.lightserve_server_requests.inc(type="sync")
                    self._handle_sync(client_id, msg, send)
                elif isinstance(msg, proto.Ping):
                    _m.lightserve_server_requests.inc(type="ping")
                    send(proto.Pong(
                        nonce=msg.nonce,
                        latest_height=max(0, self.latest_height()),
                        uptime_ms=int((time.monotonic() -
                                       self._started_at) * 1000)))
                elif isinstance(msg, proto.StatsRequest):
                    _m.lightserve_server_requests.inc(type="stats")
                    send(proto.StatsResponse(stats_json=json.dumps(
                        self.snapshot()).encode()))
                else:
                    _m.lightserve_server_protocol_errors.inc(
                        kind="unexpected-type")
                    send(proto.ErrorReply(
                        code=proto.ERR_PROTOCOL,
                        message=f"unexpected {type(msg).__name__}"))
        except (EOFError, OSError, BrokenPipeError):
            pass  # peer went away
        finally:
            self._drop_conn(conn)

    def _reply_sync(self, send, request_id: int, ps: PendingSync,
                    t0: float) -> None:
        from tmtpu.libs import metrics as _m

        status = ps.status if ps.status is not None else \
            _FAILURE_STATUS.get(ps.failure, proto.STATUS_UPSTREAM_DOWN)
        hops = [proto.Hop(height=f.height, header_hash=f.header_hash,
                          header_time=f.header_time)
                for f in (ps.hops or [])]
        self.sessions_served += 1
        if status == proto.STATUS_OK and ps.dispatches == 0:
            _m.lightserve_server_dispatches_avoided.inc()
        _m.lightserve_server_proof_latency.observe(
            time.perf_counter() - t0)
        try:
            send(proto.SyncResponse(
                request_id=request_id, status=status, hops=hops,
                dispatches=ps.dispatches, cache_hit=ps.cache_hit,
                dispatch_id=ps.dispatch_id, coalesced=ps.coalesced,
                error=ps.error))
        except OSError:
            pass  # client gone; the resolve already happened

    def _handle_sync(self, client_id: str, req: proto.SyncRequest,
                     send) -> None:
        t0 = time.perf_counter()

        def reject(status: int, error: str) -> None:
            send(proto.SyncResponse(
                request_id=req.request_id, status=status, error=error))

        if self._draining:
            reject(proto.STATUS_OVERLOADED, "daemon draining for shutdown")
            return
        target = req.target_height
        if target == 0:
            target = self.latest_height()
        if target <= 0:
            reject(proto.STATUS_BAD_REQUEST,
                   "no target height (spine empty and none requested)")
            return
        # THE expiry clock is the server's. The client's now_ns is a
        # skew CHECK only: a clock too far from ours would judge our
        # proofs under a different trusting-period window, so refuse
        # loudly — but never let an unauthenticated value drive cache
        # eviction or the joint resolve (a far-future clock would evict
        # fresh shared facts and expire every coalesced peer; a
        # far-past one would bypass trusting-period safety).
        now_ns = self._clock()
        if req.now_ns:
            skew = req.now_ns - now_ns
            if abs(skew) > self._max_client_skew_ns:
                reject(proto.STATUS_BAD_REQUEST,
                       f"client clock skew {skew}ns exceeds "
                       f"±{self._max_client_skew_ns}ns; fix the client "
                       f"clock (the server clock judges trust expiry)")
                return
        ps = PendingSync(client_id, target, req.trusted_height,
                         bytes(req.trusted_hash), now_ns, None)
        # fast path: fresh cached fact — answered inline on the
        # connection thread, no coalescer, no reply pool. This is the
        # only path that can hold 10k+ concurrent sessions.
        fact = self.cache.get(target, now_ns)
        if fact is not None:
            ps.coalesced = 1
            self._slice(ps, Resolution(proto.STATUS_OK, 0, fact, now_ns,
                                       cache_hit=True))
            self._reply_sync(send, req.request_id, ps, t0)
            return

        # cold path: ride the height-keyed coalescer. The reply is sent
        # by the bounded reply pool when the coalescer finishes the
        # session (its on_done hook fires exactly once for every
        # admitted session — resolve, failure, deadline, or stop — so
        # no per-session thread and no unanswered session). A wedged
        # upstream is bounded by the provider's own timeouts plus the
        # client-side request deadline.
        request_id = req.request_id

        def on_done(pending: PendingSync) -> None:
            self._reply_pool.put(
                lambda: self._reply_sync(send, request_id, pending, t0))

        try:
            self.coalescer.submit(
                client_id, target, req.trusted_height,
                bytes(req.trusted_hash), now_ns,
                deadline_s=self._default_deadline_s,
                on_done=on_done)
        except Overloaded as exc:
            reject(proto.STATUS_OVERLOADED, str(exc))
            return

    # --- health HTTP --------------------------------------------------------

    def _start_health_http(self) -> None:
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    healthy, reason, details = server._health_check()
                    body = json.dumps(
                        {"healthy": healthy, "reason": reason,
                         "check": details, **server.snapshot()}).encode()
                    self.send_response(200 if healthy else 503)
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    from tmtpu.libs import metrics as _m

                    body = _m.render_prometheus().encode()
                    self.send_response(200)
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    ctype = "text/plain"
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        host, _sep, port = self._health_laddr.rpartition(":")
        httpd = http.server.ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)), Handler)
        self._health_httpd = httpd
        self._health_thread = threading.Thread(
            target=httpd.serve_forever, name="lightserve-health",
            daemon=True)
        self._health_thread.start()
