"""BlockStore (reference: store/store.go) — persists blocks (as parts),
commits and block metas per height on a libs.db KV."""

from __future__ import annotations

import struct
import threading
from typing import Optional

from tmtpu.libs.db import DB
from tmtpu.types import pb
from tmtpu.types.block import Block, BlockID, Commit, Header
from tmtpu.types.part_set import Part, PartSet


class BlockMeta:
    """types/block_meta.go."""

    def __init__(self, block_id: BlockID, block_size: int, header: Header,
                 num_txs: int):
        self.block_id = block_id
        self.block_size = block_size
        self.header = header
        self.num_txs = num_txs

    def encode(self) -> bytes:
        return _BlockMetaPB(
            block_id=self.block_id.to_proto(),
            block_size=self.block_size,
            header=self.header.to_proto(),
            num_txs=self.num_txs,
        ).encode()

    @classmethod
    def decode(cls, buf: bytes) -> "BlockMeta":
        m = _BlockMetaPB.decode(buf)
        return cls(BlockID.from_proto(m.block_id), m.block_size,
                   Header.from_proto(m.header), m.num_txs)


class _BlockMetaPB(pb.ProtoMessage):
    FIELDS = [
        (1, "block_id", ("msg!", pb.BlockID)),
        (2, "block_size", "int64"),
        (3, "header", ("msg!", pb.Header)),
        (4, "num_txs", "int64"),
    ]


def _k_meta(h: int) -> bytes:
    return b"H:%d" % h


def _k_part(h: int, i: int) -> bytes:
    return b"P:%d:%d" % (h, i)


def _k_commit(h: int) -> bytes:
    return b"C:%d" % h


def _k_seen_commit(h: int) -> bytes:
    return b"SC:%d" % h


def _k_hash(block_hash: bytes) -> bytes:
    return b"BH:" + block_hash


class BlockStore:
    def __init__(self, db: DB):
        self.db = db
        self._lock = threading.RLock()
        raw = self.db.get(b"blockStore")
        if raw:
            self._base, self._height = struct.unpack(">qq", raw)
        else:
            self._base, self._height = 0, 0

    def base(self) -> int:
        with self._lock:
            return self._base

    def height(self) -> int:
        with self._lock:
            return self._height

    def size(self) -> int:
        with self._lock:
            return self._height - self._base + 1 if self._height else 0

    def _save_height(self) -> None:
        self.db.set(b"blockStore", struct.pack(">qq", self._base, self._height))

    def save_block(self, block: Block, part_set: PartSet,
                   seen_commit: Commit) -> None:
        """store.go:332 SaveBlock."""
        height = block.header.height
        with self._lock:
            if self._height and height != self._height + 1:
                raise ValueError(
                    f"cannot save block at height {height}, expected "
                    f"{self._height + 1}"
                )
            bid = BlockID(block.hash(), part_set.total, part_set.hash)
            meta = BlockMeta(bid, part_set.byte_size(), block.header,
                             len(block.txs))
            sets = [(_k_meta(height), meta.encode()),
                    (_k_hash(block.hash()), b"%d" % height)]
            for i in range(part_set.total):
                sets.append((_k_part(height, i),
                             part_set.get_part(i).to_proto().encode()))
            if block.last_commit is not None:
                sets.append((_k_commit(height - 1),
                             block.last_commit.to_proto().encode()))
            sets.append((_k_seen_commit(height),
                         seen_commit.to_proto().encode()))
            self.db.write_batch(sets)
            if self._base == 0:
                self._base = height
            self._height = height
            self._save_height()

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        """store.go SaveSeenCommit — used by statesync to plant the
        light-verified commit at the snapshot height."""
        with self._lock:
            self.db.set(_k_seen_commit(height), commit.to_proto().encode())

    def bootstrap(self, height: int) -> None:
        """Plant the store height after statesync (no block data exists —
        queries below base get no_block_response, like a pruned node).
        Without this, a crash before blocksync persists its first block
        leaves state at H vs store at 0 and the node can never restart."""
        with self._lock:
            if self._height:
                raise ValueError("cannot bootstrap a non-empty block store")
            self._base = height
            self._height = height
            self._save_height()

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self.db.get(_k_meta(height))
        return BlockMeta.decode(raw) if raw else None

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.parts_total):
            raw = self.db.get(_k_part(height, i))
            if raw is None:
                return None
            parts.append(Part.from_proto(pb.Part.decode(raw)))
        data = b"".join(p.bytes for p in parts)
        return Block.decode(data)

    def load_block_by_hash(self, block_hash: bytes) -> Optional[Block]:
        raw = self.db.get(_k_hash(block_hash))
        if raw is None:
            return None
        return self.load_block(int(raw))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self.db.get(_k_part(height, index))
        return Part.from_proto(pb.Part.decode(raw)) if raw else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The canonical commit for height (stored with block height+1)."""
        raw = self.db.get(_k_commit(height))
        return Commit.from_proto(pb.Commit.decode(raw)) if raw else None

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        raw = self.db.get(_k_seen_commit(height))
        return Commit.from_proto(pb.Commit.decode(raw)) if raw else None

    def prune_blocks(self, retain_height: int) -> int:
        """store.go:248 PruneBlocks — drop everything below retain_height."""
        with self._lock:
            if retain_height <= self._base:
                return 0
            if retain_height > self._height:
                raise ValueError("cannot prune beyond latest height")
            pruned = 0
            deletes = []
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is not None:
                    deletes.append(_k_hash(meta.block_id.hash))
                    for i in range(meta.block_id.parts_total):
                        deletes.append(_k_part(h, i))
                deletes += [_k_meta(h), _k_commit(h - 1), _k_seen_commit(h)]
                pruned += 1
            self.db.write_batch([], deletes)
            self._base = retain_height
            self._save_height()
            return pruned
