"""Remote-signer conformance harness (reference:
tools/tm-signer-harness/main.go + internal/test_harness.go).

An operator points an EXTERNAL remote signer (anything speaking the
privval protocol — our SignerServer, tmkms, ...) at the harness; the
harness plays the node side (listener endpoint) and runs the reference's
acceptance checks:

1. connectivity — the signer dials in before the accept deadline;
2. public key — the signer serves its pubkey (optionally matched against
   an expected key, e.g. from genesis);
3. sign proposal — a height-1 proposal signature that verifies;
4. sign vote — prevote + precommit signatures that verify;
5. double-sign defence — re-signing the same HRS with a DIFFERENT block
   id must be refused (the reference's TestSignProposal/TestSignVote
   failure cases; a signer without last-sign-state tracking fails here).

Each check prints PASS/FAIL; the run exits non-zero on the first failure
so CI can gate on it. Used by ``tmtpu signer-harness`` (cmd/__main__.py)
and tests/test_privval_harness.py.
"""

from __future__ import annotations

import time

from tmtpu.privval.signer import (
    RemoteSignerError, SignerClient, SignerListenerEndpoint,
)
from tmtpu.types.block import BlockID
from tmtpu.types.vote import PRECOMMIT, PREVOTE, Proposal, Vote


class HarnessFailure(Exception):
    def __init__(self, check: str, detail: str):
        super().__init__(f"{check}: {detail}")
        self.check = check


def _bid(tag: bytes) -> BlockID:
    return BlockID((tag * 32)[:32], 1, (b"\xaa" * 32)[:32])


def run_harness(laddr: str, chain_id: str, *, accept_deadline_s: float = 30.0,
                expect_pubkey: bytes | None = None, log=print) -> int:
    """Run every check against the signer dialing ``laddr``. Returns 0 on
    full conformance; raises HarnessFailure on the first failed check."""
    ep = SignerListenerEndpoint(laddr)
    try:
        log(f"signer-harness: listening on {laddr}, waiting up to "
            f"{accept_deadline_s:.0f}s for the signer to dial in...")
        try:
            ep.accept(timeout=accept_deadline_s)
        except Exception as e:  # noqa: BLE001
            raise HarnessFailure("connect", f"signer never dialed in: {e!r}")
        log("PASS connect")

        client = SignerClient(ep, chain_id)
        try:
            pk = client.get_pub_key()
        except RemoteSignerError as e:
            raise HarnessFailure("pubkey", str(e))
        if expect_pubkey is not None and pk.bytes() != expect_pubkey:
            raise HarnessFailure(
                "pubkey", f"got {pk.bytes().hex()}, "
                f"expected {expect_pubkey.hex()}")
        log(f"PASS pubkey ({pk.type_value()} {pk.bytes().hex()[:16]}...)")

        now = time.time_ns()
        prop = Proposal(height=1, round=0, pol_round=-1,
                        block_id=_bid(b"\x01"), timestamp=now)
        try:
            client.sign_proposal(chain_id, prop)
        except RemoteSignerError as e:
            raise HarnessFailure("sign-proposal", str(e))
        if not pk.verify_signature(prop.sign_bytes(chain_id),
                                   prop.signature):
            raise HarnessFailure("sign-proposal",
                                 "signature does not verify")
        log("PASS sign-proposal")

        for vtype, name in ((PREVOTE, "prevote"), (PRECOMMIT, "precommit")):
            v = Vote(type=vtype, height=1, round=0, block_id=_bid(b"\x02"),
                     timestamp=now, validator_address=pk.address(),
                     validator_index=0)
            try:
                client.sign_vote(chain_id, v)
            except RemoteSignerError as e:
                raise HarnessFailure(f"sign-{name}", str(e))
            if not pk.verify_signature(v.sign_bytes(chain_id), v.signature):
                raise HarnessFailure(f"sign-{name}",
                                     "signature does not verify")
            log(f"PASS sign-{name}")

        # double-sign defence: same H/R/S, conflicting block id
        evil = Vote(type=PRECOMMIT, height=1, round=0, block_id=_bid(b"\x03"),
                    timestamp=now + 1, validator_address=pk.address(),
                    validator_index=0)
        try:
            client.sign_vote(chain_id, evil)
        except RemoteSignerError:
            log("PASS double-sign-defence (conflicting precommit refused)")
        else:
            raise HarnessFailure(
                "double-sign-defence",
                "signer signed a conflicting precommit at the same HRS")

        log("signer-harness: ALL CHECKS PASSED")
        return 0
    finally:
        ep.close()
