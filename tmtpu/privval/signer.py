"""Remote signer protocol (reference: privval/signer_client.go,
privval/signer_listener_endpoint.go, privval/signer_server.go,
proto/tendermint/privval/types.proto).

Topology matches the reference: the NODE listens on
``priv_validator_laddr`` (tcp:// or unix://); the SIGNER process — which
holds the key — dials in and then serves sign requests over the
connection. Messages are length-delimited protos; tcp connections are
upgraded with SecretConnection, unix sockets run in the clear.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional, Tuple

from tmtpu.crypto.encoding import pubkey_from_proto, pubkey_to_proto
from tmtpu.libs import protoio
from tmtpu.libs.protoio import ProtoMessage
from tmtpu.types import pb
from tmtpu.types.priv_validator import PrivValidator
from tmtpu.types.vote import Proposal, Vote


class RemoteSignerErrorPB(ProtoMessage):
    FIELDS = [(1, "code", "int32"), (2, "description", "string")]


class PubKeyRequestPB(ProtoMessage):
    FIELDS = [(1, "chain_id", "string")]


class PubKeyResponsePB(ProtoMessage):
    FIELDS = [(1, "pub_key", ("msg", pb.PublicKey)),
              (2, "error", ("msg", RemoteSignerErrorPB))]


class SignVoteRequestPB(ProtoMessage):
    FIELDS = [(1, "vote", ("msg", pb.Vote)), (2, "chain_id", "string")]


class SignedVoteResponsePB(ProtoMessage):
    FIELDS = [(1, "vote", ("msg", pb.Vote)),
              (2, "error", ("msg", RemoteSignerErrorPB))]


class SignProposalRequestPB(ProtoMessage):
    FIELDS = [(1, "proposal", ("msg", pb.Proposal)),
              (2, "chain_id", "string")]


class SignedProposalResponsePB(ProtoMessage):
    FIELDS = [(1, "proposal", ("msg", pb.Proposal)),
              (2, "error", ("msg", RemoteSignerErrorPB))]


class PingRequestPB(ProtoMessage):
    FIELDS = []


class PingResponsePB(ProtoMessage):
    FIELDS = []


class SignerMessagePB(ProtoMessage):
    """privval Message oneof sum."""

    FIELDS = [
        (1, "pub_key_request", ("msg", PubKeyRequestPB)),
        (2, "pub_key_response", ("msg", PubKeyResponsePB)),
        (3, "sign_vote_request", ("msg", SignVoteRequestPB)),
        (4, "signed_vote_response", ("msg", SignedVoteResponsePB)),
        (5, "sign_proposal_request", ("msg", SignProposalRequestPB)),
        (6, "signed_proposal_response", ("msg", SignedProposalResponsePB)),
        (7, "ping_request", ("msg", PingRequestPB)),
        (8, "ping_response", ("msg", PingResponsePB)),
    ]


class RemoteSignerError(Exception):
    pass


def _parse_addr(addr: str) -> Tuple[str, object]:
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://"):]
    if addr.startswith("tcp://"):
        hp = addr[len("tcp://"):]
        host, _, port = hp.rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    raise ValueError(f"unsupported privval address {addr!r}")


class _Conn:
    """Length-delimited proto messages over a socket or SecretConnection."""

    def __init__(self, sock, secret=None):
        self.sock = sock
        self.secret = secret
        self._lock = threading.Lock()

    def send_msg(self, m: SignerMessagePB) -> None:
        data = protoio.marshal_delimited(m.encode())
        with self._lock:
            if self.secret is not None:
                self.secret.write(data)
            else:
                self.sock.sendall(data)

    def recv_msg(self) -> SignerMessagePB:
        # uvarint length prefix, then the message
        shift = 0
        n = 0
        while True:
            b = self._read_exact(1)[0]
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise ValueError("varint overflow")
        if n > 16 * 1024 * 1024:
            raise ValueError("signer message too large")
        return SignerMessagePB.decode(self._read_exact(n))

    def _read_exact(self, n: int) -> bytes:
        if self.secret is not None:
            return self.secret.read_exact(n)
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("signer connection closed")
            buf += chunk
        return buf

    def close(self) -> None:
        try:
            if self.secret is not None:
                self.secret.close()
            else:
                self.sock.close()
        except OSError:
            pass


class SignerListenerEndpoint:
    """Node side (privval/signer_listener_endpoint.go): listen, accept ONE
    signer connection at a time, issue requests over it."""

    def __init__(self, addr: str, node_priv_key=None,
                 timeout_read_s: float = 30.0):
        self.addr = addr
        self.node_priv_key = node_priv_key
        self.timeout_read_s = timeout_read_s
        self._conn: Optional[_Conn] = None
        self._lock = threading.Lock()
        self._req_lock = threading.Lock()  # serializes send+recv exchanges
        kind, target = _parse_addr(addr)
        if kind == "unix":
            if os.path.exists(target):
                os.unlink(target)
            self._listener = socket.socket(socket.AF_UNIX)
            self._listener.bind(target)
        else:
            self._listener = socket.socket(socket.AF_INET)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind(target)
        self._listener.listen(1)
        self._kind = kind

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1] if self._kind == "tcp" else 0

    def accept(self, timeout: Optional[float] = None) -> None:
        """Block until a signer dials in."""
        self._listener.settimeout(timeout)
        sock, _ = self._listener.accept()
        sock.settimeout(self.timeout_read_s)
        secret = None
        if self._kind == "tcp":
            from tmtpu.crypto import ed25519
            # via transport's gate: plaintext dev fallback when
            # `cryptography` is absent (see plain_connection.py)
            from tmtpu.p2p.transport import SecretConnection

            secret = SecretConnection(
                sock, self.node_priv_key or ed25519.gen_priv_key())
        with self._lock:
            if self._conn is not None:
                self._conn.close()
            self._conn = _Conn(sock, secret)

    def start_accept_loop(self) -> None:
        """Keep re-accepting so a restarted signer can reconnect (the
        reference's listener endpoint does the same); the freshest
        connection replaces the old one."""
        def loop():
            while True:
                try:
                    self.accept(timeout=None)
                except Exception:  # noqa: BLE001
                    # a failed handshake (e.g. the signer gave up mid-way,
                    # or a stray connection) must NOT kill the accept loop
                    # — only a closed listener ends it; otherwise the
                    # signer could never reconnect and the validator would
                    # stop signing forever
                    try:
                        self._listener.fileno()
                    except OSError:
                        return  # listener closed
                    time.sleep(0.1)  # bound a persistently failing accept
                    continue

        threading.Thread(target=loop, daemon=True,
                         name="signer-accept").start()

    def start_ping_loop(self, interval_s: float = 5.0) -> None:
        """Periodic pings keep an idle signer connection alive
        (signer_listener_endpoint.go pingLoop) — without them the signer's
        read timeout tears down perfectly good connections whenever
        consensus goes quiet."""
        def loop():
            while True:
                time.sleep(interval_s)
                try:
                    self._listener.fileno()
                except OSError:
                    return  # endpoint closed
                try:
                    self.request(SignerMessagePB(
                        ping_request=PingRequestPB()))
                except Exception:  # noqa: BLE001
                    pass  # no conn right now; accept loop will fix it

        threading.Thread(target=loop, daemon=True,
                         name="signer-ping").start()

    def request(self, m: SignerMessagePB) -> SignerMessagePB:
        # one exchange at a time: a concurrent caller (ping loop vs the
        # consensus sign path) would otherwise recv the OTHER caller's
        # response or interleave reads mid-frame
        with self._req_lock:
            return self._request_locked(m)

    def _request_locked(self, m: SignerMessagePB) -> SignerMessagePB:
        with self._lock:
            conn = self._conn
        if conn is None:
            raise RemoteSignerError("no signer connected")
        try:
            conn.send_msg(m)
            return conn.recv_msg()
        except (ConnectionError, OSError) as e:
            with self._lock:
                if self._conn is conn:
                    self._conn = None
            conn.close()
            raise RemoteSignerError(f"signer connection lost: {e}") from e

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
        try:
            self._listener.close()
        except OSError:
            pass


class SignerClient(PrivValidator):
    """privval/signer_client.go — PrivValidator over the endpoint."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._pub_key = None

    def ping(self) -> bool:
        res = self.endpoint.request(
            SignerMessagePB(ping_request=PingRequestPB()))
        return res.ping_response is not None

    def get_pub_key(self):
        if self._pub_key is None:
            res = self.endpoint.request(SignerMessagePB(
                pub_key_request=PubKeyRequestPB(chain_id=self.chain_id)))
            r = res.pub_key_response
            if r is None or r.error is not None:
                raise RemoteSignerError(
                    r.error.description if r and r.error else "bad response")
            self._pub_key = pubkey_from_proto(r.pub_key)
        return self._pub_key

    def address(self) -> bytes:
        return self.get_pub_key().address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        res = self.endpoint.request(SignerMessagePB(
            sign_vote_request=SignVoteRequestPB(
                vote=vote.to_proto(), chain_id=chain_id)))
        r = res.signed_vote_response
        if r is None:
            raise RemoteSignerError("bad sign vote response")
        if r.error is not None:
            raise RemoteSignerError(r.error.description)
        if r.vote is None:
            raise RemoteSignerError("signer returned neither vote nor error")
        vote.signature = bytes(r.vote.signature)
        # remote may also have adjusted the timestamp (cached HRS re-sign)
        if r.vote.timestamp is not None:
            vote.timestamp = r.vote.timestamp.to_unix_nanos()

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        res = self.endpoint.request(SignerMessagePB(
            sign_proposal_request=SignProposalRequestPB(
                proposal=proposal.to_proto(), chain_id=chain_id)))
        r = res.signed_proposal_response
        if r is None:
            raise RemoteSignerError("bad sign proposal response")
        if r.error is not None:
            raise RemoteSignerError(r.error.description)
        if r.proposal is None:
            raise RemoteSignerError(
                "signer returned neither proposal nor error")
        proposal.signature = bytes(r.proposal.signature)
        if r.proposal.timestamp is not None:
            proposal.timestamp = r.proposal.timestamp.to_unix_nanos()


class SignerServer:
    """Signer side (privval/signer_server.go + signer_dialer_endpoint.go):
    dial the node and serve sign requests from the wrapped PrivValidator
    (usually a FilePV with its double-sign protection intact)."""

    def __init__(self, addr: str, chain_id: str, priv_validator,
                 dial_priv_key=None, retries: int = 10,
                 retry_wait_s: float = 0.5):
        self.addr = addr
        self.chain_id = chain_id
        self.priv_validator = priv_validator
        self.dial_priv_key = dial_priv_key
        self.retries = retries
        self.retry_wait_s = retry_wait_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve_loop,
                                        daemon=True, name="signer-server")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _dial(self) -> _Conn:
        kind, target = _parse_addr(self.addr)
        last_err = None
        for _ in range(self.retries):
            if self._stopped.is_set():
                raise ConnectionError("signer stopped")
            try:
                if kind == "unix":
                    sock = socket.socket(socket.AF_UNIX)
                    sock.connect(target)
                    return _Conn(sock)
                sock = socket.create_connection(target, timeout=10)
                from tmtpu.crypto import ed25519
                # via transport's gate: plaintext dev fallback when
                # `cryptography` is absent (see plain_connection.py)
                from tmtpu.p2p.transport import SecretConnection

                secret = SecretConnection(
                    sock, self.dial_priv_key or ed25519.gen_priv_key())
                return _Conn(sock, secret)
            except OSError as e:
                last_err = e
                time.sleep(self.retry_wait_s)
        raise ConnectionError(f"cannot reach node: {last_err}")

    def _serve_loop(self) -> None:
        from tmtpu.libs.log import default_logger

        log = default_logger().with_fields(module="privval-signer")
        while not self._stopped.is_set():
            try:
                conn = self._dial()
            except ConnectionError as e:
                if self._stopped.is_set():
                    return
                # keep dialing until stopped (signer_dialer_endpoint.go's
                # retry loop) — a node outage must never permanently kill
                # the signer; _dial's `retries` bounds one burst only
                log.error("cannot reach node, will keep retrying", err=e)
                self._stopped.wait(self.retry_wait_s * 2)
                continue
            try:
                while not self._stopped.is_set():
                    req = conn.recv_msg()
                    conn.send_msg(self._handle(req))
            except Exception as e:  # noqa: BLE001
                # ANY failure (node restarting mid-frame, decode error,
                # socket teardown) = disconnect: log it, close, re-dial
                if not self._stopped.is_set():
                    log.error("serve error, reconnecting", err=repr(e))
                conn.close()
                time.sleep(self.retry_wait_s)

    def _handle(self, req: SignerMessagePB) -> SignerMessagePB:
        if req.ping_request is not None:
            return SignerMessagePB(ping_response=PingResponsePB())
        if req.pub_key_request is not None:
            return SignerMessagePB(pub_key_response=PubKeyResponsePB(
                pub_key=pubkey_to_proto(self.priv_validator.get_pub_key())))
        if req.sign_vote_request is not None:
            vote = Vote.from_proto(req.sign_vote_request.vote)
            try:
                self.priv_validator.sign_vote(
                    req.sign_vote_request.chain_id or self.chain_id, vote)
                return SignerMessagePB(
                    signed_vote_response=SignedVoteResponsePB(
                        vote=vote.to_proto()))
            except Exception as e:  # noqa: BLE001 — double sign etc.
                return SignerMessagePB(
                    signed_vote_response=SignedVoteResponsePB(
                        error=RemoteSignerErrorPB(code=1,
                                                  description=str(e))))
        if req.sign_proposal_request is not None:
            prop = Proposal.from_proto(req.sign_proposal_request.proposal)
            try:
                self.priv_validator.sign_proposal(
                    req.sign_proposal_request.chain_id or self.chain_id,
                    prop)
                return SignerMessagePB(
                    signed_proposal_response=SignedProposalResponsePB(
                        proposal=prop.to_proto()))
            except Exception as e:  # noqa: BLE001
                return SignerMessagePB(
                    signed_proposal_response=SignedProposalResponsePB(
                        error=RemoteSignerErrorPB(code=1,
                                                  description=str(e))))
        raise ValueError("unknown signer request")
