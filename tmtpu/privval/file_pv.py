"""FilePV — file-backed private validator with double-sign protection
(reference: privval/file.go:148).

Two files: the immutable key file and the last-sign-state file. Before
signing, the height/round/step (HRS) is compared against the persisted
state (file.go:92 CheckHRS): signing an older HRS is refused; re-signing
the exact same HRS returns the cached signature iff the sign bytes match
(modulo timestamp), which is what makes crash-restart safe.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional, Tuple

from tmtpu.crypto import ed25519
from tmtpu.libs import protoio
from tmtpu.types import pb
from tmtpu.types.priv_validator import PrivValidator


def _gen_priv_key(key_type: str):
    if key_type == "ed25519":
        return ed25519.gen_priv_key()
    if key_type == "sr25519":
        from tmtpu.crypto import sr25519

        return sr25519.gen_priv_key()
    if key_type == "secp256k1":
        from tmtpu.crypto import secp256k1

        return secp256k1.gen_priv_key()
    raise ValueError(f"unknown key type {key_type!r} "
                     f"(want ed25519|sr25519|secp256k1)")

STEP_PROPOSAL = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {pb.SIGNED_MSG_TYPE_PREVOTE: STEP_PREVOTE,
              pb.SIGNED_MSG_TYPE_PRECOMMIT: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: str) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class FilePV(PrivValidator):
    def __init__(self, priv_key, key_file: str, state_file: str):
        self.priv_key = priv_key
        self.key_file = key_file
        self.state_file = state_file
        # last sign state
        self.height = 0
        self.round = 0
        self.step = 0
        self.signature: Optional[bytes] = None
        self.sign_bytes: Optional[bytes] = None

    # -- construction -------------------------------------------------------

    @classmethod
    def generate(cls, key_file: str, state_file: str,
                 key_type: str = "ed25519") -> "FilePV":
        """New validator key on any registered curve (cmd/tendermint init
        --key analogue; the reference's codec.go:14 handles only
        ed25519/secp256k1 — sr25519 works here too)."""
        pv = cls(_gen_priv_key(key_type), key_file, state_file)
        pv.save()
        return pv

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        from tmtpu.libs import amino_json

        with open(key_file) as f:
            kd = json.load(f)
        pv = cls(amino_json.unmarshal_priv_key(kd["priv_key"]),
                 key_file, state_file)
        # file.go LoadFilePV fails loudly when the state file is unreadable:
        # a silently-fresh sign state would disable double-sign protection.
        if not os.path.exists(state_file):
            raise FileNotFoundError(
                f"privval state file {state_file!r} missing; refusing to "
                f"start with empty sign state (double-sign risk)")
        with open(state_file) as f:
            sd = json.load(f)
        # reference state form (privval/file.go:76-80): height is an
        # int64 -> string, signature base64, signbytes hex; legacy tmtpu
        # files had int height + hex signature — accept both
        pv.height = int(sd.get("height", 0))
        pv.round = int(sd.get("round", 0))
        pv.step = int(sd.get("step", 0))
        pv.signature = amino_json.bytes_from_b64(sd.get("signature"))
        sb = sd.get("signbytes")
        pv.sign_bytes = bytes.fromhex(sb) if sb else None
        return pv

    @classmethod
    def load_or_generate(cls, key_file: str, state_file: str,
                         key_type: str = "ed25519") -> "FilePV":
        if os.path.exists(key_file):
            return cls.load(key_file, state_file)
        os.makedirs(os.path.dirname(key_file) or ".", exist_ok=True)
        os.makedirs(os.path.dirname(state_file) or ".", exist_ok=True)
        return cls.generate(key_file, state_file, key_type)

    def save(self) -> None:
        """Write the key file in the reference's amino JSON form
        (privval/file.go FilePVKey through libs/json): base64 values
        under tendermint/PrivKey* type tags — loadable by the reference
        and round-trippable here."""
        from tmtpu.libs import amino_json

        pub = self.priv_key.pub_key()
        _atomic_write(self.key_file, json.dumps({
            "address": pub.address().hex().upper(),
            "pub_key": amino_json.marshal_pub_key(pub),
            "priv_key": amino_json.marshal_priv_key(self.priv_key),
        }, indent=2))
        self._save_state()

    def _save_state(self) -> None:
        """Reference FilePVLastSignState shape (privval/file.go:76-80):
        height as string (amino int64), round/step numeric, signature
        base64, signbytes uppercase hex."""
        from tmtpu.libs import amino_json

        d = {"height": str(self.height), "round": self.round,
             "step": self.step}
        if self.signature:
            d["signature"] = amino_json.b64_or_none(self.signature)
        if self.sign_bytes:
            d["signbytes"] = self.sign_bytes.hex().upper()
        _atomic_write(self.state_file, json.dumps(d, indent=2))

    # -- PrivValidator ------------------------------------------------------

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def address(self) -> bytes:
        return self.get_pub_key().address()

    def sign_vote(self, chain_id: str, vote) -> None:
        step = _VOTE_STEP.get(vote.type)
        if step is None:
            raise ValueError(f"unknown vote type {vote.type}")
        sb = vote.sign_bytes(chain_id)
        same, cached = self._check_hrs(vote.height, vote.round, step, sb)
        if same and cached is not None:
            vote.signature = cached
            return
        vote.signature = self.priv_key.sign(sb)
        self._update_state(vote.height, vote.round, step, sb, vote.signature)

    def sign_proposal(self, chain_id: str, proposal) -> None:
        sb = proposal.sign_bytes(chain_id)
        same, cached = self._check_hrs(proposal.height, proposal.round,
                                       STEP_PROPOSAL, sb)
        if same and cached is not None:
            proposal.signature = cached
            return
        proposal.signature = self.priv_key.sign(sb)
        self._update_state(proposal.height, proposal.round, STEP_PROPOSAL,
                          sb, proposal.signature)

    # -- double-sign protection (file.go:92 CheckHRS) -----------------------

    def _check_hrs(self, height: int, round: int, step: int,
                   sign_bytes: bytes) -> Tuple[bool, Optional[bytes]]:
        if (self.height, self.round, self.step) > (height, round, step):
            raise DoubleSignError(
                f"sign state is ahead: {self.height}/{self.round}/{self.step}"
                f" > {height}/{round}/{step}"
            )
        if (self.height, self.round, self.step) == (height, round, step):
            if self.sign_bytes is None:
                raise DoubleSignError("no sign bytes cached for same HRS")
            if self.sign_bytes == sign_bytes:
                return True, self.signature
            if _only_timestamp_differs(self.sign_bytes, sign_bytes, step):
                return True, self.signature
            raise DoubleSignError(
                "conflicting data: same HRS, different sign bytes")
        return False, None

    def _update_state(self, height: int, round: int, step: int,
                      sign_bytes: bytes, sig: bytes) -> None:
        self.height, self.round, self.step = height, round, step
        self.signature = sig
        self.sign_bytes = sign_bytes
        self._save_state()


def _only_timestamp_differs(old: bytes, new: bytes, step: int) -> bool:
    """file.go checkVotesOnlyDifferByTimestamp — strip the timestamp field
    from both canonical encodings and compare."""
    try:
        if step == STEP_PROPOSAL:
            a = pb.CanonicalProposal.decode(protoio.unmarshal_delimited(old))
            b = pb.CanonicalProposal.decode(protoio.unmarshal_delimited(new))
        else:
            a = pb.CanonicalVote.decode(protoio.unmarshal_delimited(old))
            b = pb.CanonicalVote.decode(protoio.unmarshal_delimited(new))
    except Exception:
        return False
    a.timestamp = None
    b.timestamp = None
    return a.encode() == b.encode()
