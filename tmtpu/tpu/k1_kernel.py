"""Fused Pallas TPU kernel for batched secp256k1 ECDSA verification.

Same per-lane semantics as ``tmtpu.tpu.k1_verify.verify_core_compact`` (the
btcec low-S verify; reference crypto/secp256k1/secp256k1.go:195-197, serial
oracle tmtpu.crypto.secp256k1.PubKeySecp256k1.verify_signature), but the
whole device half — big-endian byte unpack, SEC1 decompression (one
(p+1)/4 square-root chain), the 64-window Straus/Shamir ladder
R = [u1]G + [u2]Q and the projective x(R) ≡ r check — runs inside ONE
Pallas kernel per lane tile, keeping the ~4000 field multiplies per
signature in VMEM/vector registers instead of round-tripping [20, B] limb
arrays through HBM after every op. That HBM round-trip is what bounds the
plain-XLA graph (tmtpu.tpu.k1_verify): it loses to serial OpenSSL on CPU
(VERDICT r2 weak #2); the same fusion took ed25519 from 22k to 260k sig/s
(tmtpu.tpu.kernel).

Layout matches tmtpu.tpu.kernel: limb arrays are [NLIMBS, T] int32 with T
lanes on the TPU vector lanes, so the fe_k1/k1_verify field and point
routines run verbatim inside the kernel (their constants arrive through
fe.const_context planes — Pallas rejects closed-over arrays). Kernel-only
code is what touches refs or needs [1, T] masks: the big-endian unpack,
digit extraction, decompression, select-chain window lookups and the final
compare.

Grid: one program per ``tile`` lanes; programs are data-parallel over
signatures, so the kernel composes with shard_map lane-sharding unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tmtpu.tpu import fe_k1 as fe
from tmtpu.tpu import k1_verify as kv

NLIMBS = fe.NLIMBS
RADIX = fe.RADIX
WINDOW = kv.WINDOW
NDIGITS = kv.NDIGITS
NTAB = 1 << WINDOW

# Constants plane: [NLIMBS, CONST_COLS] int32. Columns 3*d + c hold
# coordinate c (X, Y, Z) of the fixed-base table entry d*G (projective,
# identity at d = 0) — 48 columns total.
CONST_COLS = 48

# fe-level constants at full tile width (narrow [20, 1] constants die in
# Mosaic's layout pass — see tmtpu.tpu.kernel._verify_kernel): KSUB (sub),
# P_LIMBS (freeze), SEVEN (decompress).
_FC_N = 3

DEFAULT_TILE = 256

_CONSTS_PLANE = None
_FCOLS = None


def _consts_plane() -> np.ndarray:
    global _CONSTS_PLANE
    if _CONSTS_PLANE is None:
        plane = np.zeros((NLIMBS, CONST_COLS), dtype=np.int32)
        tab = kv.fixed_base_table()  # [16, 3, 20]
        for d in range(NTAB):
            for c in range(3):
                plane[:, 3 * d + c] = tab[d, c]
        _CONSTS_PLANE = plane
    return _CONSTS_PLANE


def _fcols() -> np.ndarray:
    global _FCOLS
    if _FCOLS is None:
        _FCOLS = np.concatenate(
            [fe.KSUB, fe.P_LIMBS, fe.limbs_of_int(7)]).astype(np.int32)
    return _FCOLS


def _unpack_limbs_be(b):
    """[32, T] int32 BIG-endian bytes -> [20, T] radix-2^13 limbs of the
    full 256-bit value (callers guarantee value < p < 2^256). Byte k of the
    little-endian order is row 31-k of the big-endian input."""
    rows = []
    for limb in range(NLIMBS):
        lo_bit = RADIX * limb
        if lo_bit >= 256:
            rows.append(jnp.zeros_like(b[0:1]))
            continue
        hi_bit = min(lo_bit + RADIX, 256)
        nbits = hi_bit - lo_bit
        off = lo_bit & 7
        k = lo_bit >> 3
        acc = b[31 - k : 32 - k] >> off
        shift = 8 - off
        k += 1
        while shift < nbits:
            acc = acc | (b[31 - k : 32 - k] << shift)
            shift += 8
            k += 1
        rows.append(acc & ((1 << nbits) - 1))
    return jnp.concatenate(rows, axis=0)


def _row0_one(x):
    """[20, T] limb vector of the field element 1 (concat form — .at[].set
    lowers to scatter, unsupported in Mosaic)."""
    return jnp.concatenate(
        [jnp.ones((1, x.shape[1]), jnp.int32),
         jnp.zeros((NLIMBS - 1, x.shape[1]), jnp.int32)], axis=0)


def _eq_all(a, b):
    """[20, T] x2 canonical limbs -> bool [1, T] rowwise equality."""
    return jnp.sum(jnp.abs(a - b), axis=0, keepdims=True) == 0


def _decompress_k(x, parity):
    """Kernel twin of k1_verify.decompress with [1, T] masks. x: [20, T]
    canonical limbs (host-checked < p); parity: [1, T] in {0, 1}."""
    seven = fe.const_col("K1_SEVEN", fe.limbs_of_int(7))
    y2 = fe.add(fe.mul(fe.sq(x), x), seven)
    y = fe.sqrt_candidate(y2)
    yf = fe.freeze(y)
    valid = _eq_all(fe.freeze(fe.sq(y)), fe.freeze(y2))
    flip = (yf[0:1] & 1) != parity
    y = jnp.where(flip, fe.neg(yf), yf)
    return (x, y, _row0_one(x)), valid


def _digit_rows_msb_be(b):
    """[32, T] int32 BIG-endian scalar bytes -> 64 [1, T] 4-bit windows,
    most-significant first (row 2i = hi nibble of byte i)."""
    rows = []
    for w in range(NDIGITS):
        byte = b[w // 2 : w // 2 + 1]
        rows.append((byte >> 4) if (w % 2 == 0) else (byte & 0x0F))
    return rows


def _k1_ladder(consts, q, tab_refs, d1_ref, d2_ref, T):
    """Build the per-lane window table d*Q (d in 0..15) in scratch — 14
    sequential complete adds, unrolled — then run the 64-window
    Straus/Shamir ladder [u1]G + [u2]Q with select-chain lookups (the
    fixed-base projective rows from the constants plane; the per-lane rows
    from scratch). Returns the projective result."""
    tx_ref, ty_ref, tz_ref = tab_refs
    ident = kv.identity((T,))
    for ref_, val in zip(tab_refs, ident):
        ref_[0:NLIMBS] = val
    for ref_, val in zip(tab_refs, q):
        ref_[NLIMBS : 2 * NLIMBS] = val
    acc = q
    for d in range(2, NTAB):
        acc = kv.add(acc, q)
        for ref_, val in zip(tab_refs, acc):
            ref_[d * NLIMBS : (d + 1) * NLIMBS] = val

    def lookup_base(dig):
        sel = [None, None, None]
        for d in range(NTAB):
            m = dig == d
            for c in range(3):
                col = 3 * d + c
                const = consts[:, col : col + 1]  # [20, 1]
                sel[c] = (jnp.where(m, const, sel[c])
                          if sel[c] is not None
                          else jnp.broadcast_to(const, (NLIMBS, T)))
        return tuple(sel)

    def lookup_lane(dig):
        outs = []
        for ref_ in tab_refs:
            acc_c = ref_[0:NLIMBS]
            for d in range(1, NTAB):
                acc_c = jnp.where(dig == d,
                                  ref_[d * NLIMBS : (d + 1) * NLIMBS], acc_c)
            outs.append(acc_c)
        return tuple(outs)

    def body(w, p):
        for _ in range(WINDOW):
            p = kv.double(p)
        d1 = d1_ref[pl.ds(w, 1)]
        d2 = d2_ref[pl.ds(w, 1)]
        p = kv.add(p, lookup_base(d1))
        p = kv.add(p, lookup_lane(d2))
        return p

    return jax.lax.fori_loop(0, NDIGITS, body, ident)


def _k1_verify_kernel(consts_ref, fc_ref, pkx_ref, par_ref, u1_ref, u2_ref,
                      r_ref, rpn_ref, out_ref, tx_ref, ty_ref, tz_ref,
                      d1_ref, d2_ref, use_dus: bool = True):
    consts = consts_ref[:]
    ctx = {
        "K1_KSUB": fc_ref[0 * NLIMBS : 1 * NLIMBS],
        "K1_P": fc_ref[1 * NLIMBS : 2 * NLIMBS],
        "K1_SEVEN": fc_ref[2 * NLIMBS : 3 * NLIMBS],
        "_dus": use_dus,
    }
    from tmtpu.tpu.fe import const_context

    with const_context(ctx):
        _k1_verify_body(consts, pkx_ref, par_ref, u1_ref, u2_ref, r_ref,
                        rpn_ref, out_ref, (tx_ref, ty_ref, tz_ref),
                        d1_ref, d2_ref)


def _k1_verify_body(consts, pkx_ref, par_ref, u1_ref, u2_ref, r_ref,
                    rpn_ref, out_ref, tab_refs, d1_ref, d2_ref):
    T = pkx_ref.shape[1]

    x_limbs = _unpack_limbs_be(pkx_ref[:].astype(jnp.int32))
    parity = par_ref[0:1]

    for w, row in enumerate(_digit_rows_msb_be(u1_ref[:].astype(jnp.int32))):
        d1_ref[w : w + 1] = row
    for w, row in enumerate(_digit_rows_msb_be(u2_ref[:].astype(jnp.int32))):
        d2_ref[w : w + 1] = row

    q, q_ok = _decompress_k(x_limbs, parity)
    rp = _k1_ladder(consts, q, tab_refs, d1_ref, d2_ref, T)

    X, _, Z = rp
    zf = fe.freeze(Z)
    finite = jnp.sum(zf, axis=0, keepdims=True) != 0
    xf = fe.freeze(X)
    r_l = _unpack_limbs_be(r_ref[:].astype(jnp.int32))
    rpn_l = _unpack_limbs_be(rpn_ref[:].astype(jnp.int32))
    m1 = _eq_all(xf, fe.freeze(fe.mul(r_l, Z)))
    m2 = _eq_all(xf, fe.freeze(fe.mul(rpn_l, Z)))
    ok = q_ok & finite & (m1 | m2)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, T))


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _k1_verify_pallas_jit(pkx_b, parity, u1_b, u2_b, r_b, rpn_b,
                          tile: int, interpret: bool):
    B = pkx_b.shape[1]
    grid = (B // tile,)
    spec_in = pl.BlockSpec((32, tile), lambda i: (0, i),
                           memory_space=pltpu.VMEM)
    spec_par = pl.BlockSpec((8, tile), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    spec_consts = pl.BlockSpec((NLIMBS, CONST_COLS), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    fc = jnp.asarray(np.repeat(_fcols()[:, None], tile, axis=1))
    spec_fc = pl.BlockSpec((_FC_N * NLIMBS, tile), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    par8 = jnp.broadcast_to(parity[None, :].astype(jnp.int32), (8, B))
    out = pl.pallas_call(
        functools.partial(_k1_verify_kernel, use_dus=not interpret),
        grid=grid,
        in_specs=[spec_consts, spec_fc, spec_in, spec_par] + [spec_in] * 4,
        out_specs=pl.BlockSpec((8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, B), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # table X
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # table Y
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # table Z
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # u1 digits
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # u2 digits
        ],
        interpret=interpret,
    )(jnp.asarray(_consts_plane()), fc, pkx_b.astype(jnp.int32), par8,
      u1_b.astype(jnp.int32), u2_b.astype(jnp.int32),
      r_b.astype(jnp.int32), rpn_b.astype(jnp.int32))
    return out[0]


def _default_interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def k1_verify_compact_kernel(pkx_b, parity, u1_b, u2_b, r_b, rpn_b, *,
                             tile: int = 256,
                             interpret: bool | None = None):
    """Fused-kernel twin of k1_verify.verify_core_compact. pkx_b/u1_b/
    u2_b/r_b/rpn_b: [32, B] uint8 big-endian device arrays (B a multiple
    of ``tile``); parity: [B] int32. Returns bool [B]."""
    if interpret is None:
        interpret = _default_interpret()
    return _k1_verify_pallas_jit(
        pkx_b, parity, u1_b, u2_b, r_b, rpn_b, tile, interpret) != 0
