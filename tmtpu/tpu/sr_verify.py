"""Batched sr25519 (schnorrkel) signature verification on TPU.

The reference verifies sr25519 one-at-a-time on CPU through go-schnorrkel
(crypto/sr25519/pubkey.go:50); the spec oracle here is
tmtpu.crypto.sr25519.PubKeySr25519.verify_signature. BASELINE.md lists
sr25519 batches and mixed-curve sets as a north-star config — this module
gives sr25519 the same device pipeline ed25519 has (tmtpu.tpu.verify).

ristretto255 is a quotient group over the same edwards25519 curve, so the
entire field/curve stack (tmtpu.tpu.fe radix-2^13 limbs, tmtpu.tpu.curve
complete point ops and the Straus/Shamir ladder, the fixed-base window
table for B) is reused verbatim. What is new here is batched *ristretto*
decoding (SQRT_RATIO_M1 decompression) and coset equality, per
draft-irtf-cfrg-ristretto255 (host oracle: tmtpu.crypto.ristretto).

Split of labor:
- **host**: length/marker checks, ``s < L``, canonical-encoding byte checks
  (value < p, even), and the merlin transcript absorption producing the
  challenge scalar k (STROBE/Keccak is byte-serial, data-dependent work —
  exactly what SURVEY §7 assigns to the host side);
- **device**: ristretto decode of A and R (one inverse-sqrt each), the
  shared-doubling ladder R' = [s]B + [k](-A), and projective coset
  equality R' == R — all elementwise over the trailing batch dim, sharding
  over lanes like the ed25519 graph.

Verification predicate (exactly the CPU path's): sig parses, A and R are
canonical ristretto encodings, s canonical, and encode(R') == sig.R —
which over canonical encodings is equivalent to the on-device coset
equality decode(sig.R) ≅ R' (encode/decode are inverse bijections between
canonical encodings and cosets, so no byte re-encoding is needed).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tmtpu.crypto import ed25519_ref as ref
from tmtpu.libs import faultinject, trace
from tmtpu.crypto import ristretto
from tmtpu.crypto.merlin import Transcript
from tmtpu.tpu import curve, fe
from tmtpu.tpu.verify import (
    base_table_f32,
    digits_msb_device,
    lt_le,
)

L = ref.L
P = ref.P

D_LIMBS = fe.limbs_of_int(ref.D)
SQRT_M1_LIMBS = fe.limbs_of_int(ref.SQRT_M1)
NEG_SQRT_M1_LIMBS = fe.limbs_of_int(P - ref.SQRT_M1)
ONE_LIMBS = fe.limbs_of_int(1)
NEG_ONE_LIMBS = fe.limbs_of_int(P - 1)


def _const(limbs):
    return jnp.asarray(limbs)[:, None]


def _one_like(s):
    return jnp.zeros_like(s).at[0].add(1)


def _parity(x_frozen):
    """IS_NEGATIVE per ristretto spec: low bit of the canonical form."""
    return x_frozen[0] & 1


def _abs_fe(x):
    """CT_ABS: negate iff the canonical form is odd. Returns loose limbs."""
    xf = fe.freeze(x)
    return jnp.where((_parity(xf) == 1)[None], fe.neg(xf), xf)


def _invsqrt(w):
    """SQRT_RATIO_M1(1, w): (was_square [B], r [20, B]) with r = 1/sqrt(w)
    when w is a nonzero square (mirrors ristretto._sqrt_ratio_m1 with u=1).
    """
    w3 = fe.mul(fe.sq(w), w)
    w7 = fe.mul(fe.sq(w3), w)
    r = fe.mul(w3, fe.pow_p58(w7))
    check = fe.freeze(fe.mul(w, fe.sq(r)))
    correct = jnp.all(check == _const(ONE_LIMBS), axis=0)
    flipped = jnp.all(check == _const(NEG_ONE_LIMBS), axis=0)
    flipped_i = jnp.all(check == _const(NEG_SQRT_M1_LIMBS), axis=0)
    r = jnp.where(
        (flipped | flipped_i)[None], fe.mul(r, _const(SQRT_M1_LIMBS)), r
    )
    return correct | flipped, _abs_fe(r)


def ristretto_decompress(s):
    """Batched ristretto255 DECODE: s [20, B] canonical limbs (host has
    already rejected values >= p and odd values). Returns (extended point,
    valid mask [B]); invalid lanes hold a garbage-but-finite point that the
    complete formulas never fault on — callers mask."""
    one = _one_like(s)
    ss = fe.sq(s)
    u1 = fe.sub(one, ss)
    u2 = fe.add(one, ss)
    u2_sqr = fe.sq(u2)
    # v = -(d*u1^2) - u2^2
    v = fe.sub(fe.neg(fe.mul(_const(D_LIMBS), fe.sq(u1))), u2_sqr)
    ok, invsqrt = _invsqrt(fe.mul(v, u2_sqr))
    den_x = fe.mul(invsqrt, u2)
    den_y = fe.mul(fe.mul(invsqrt, den_x), v)
    x = _abs_fe(fe.mul(fe.add(s, s), den_x))
    y = fe.mul(u1, den_y)
    t = fe.mul(x, y)
    yf = fe.freeze(y)
    valid = ok & (_parity(fe.freeze(t)) == 0) & ~jnp.all(yf == 0, axis=0)
    return (x, y, one, t), valid


def ristretto_equal(p, q):
    """Coset equality X1*Y2 == Y1*X2 or X1*X2 == Y1*Y2 — projective-safe
    (Z factors scale both products identically), so the ladder's extended
    result compares directly against a decoded (Z=1) point."""
    x1, y1 = p[0], p[1]
    x2, y2 = q[0], q[1]
    a = fe.freeze(fe.sub(fe.mul(x1, y2), fe.mul(y1, x2)))
    b = fe.freeze(fe.sub(fe.mul(x1, x2), fe.mul(y1, y2)))
    return jnp.all(a == 0, axis=0) | jnp.all(b == 0, axis=0)


def sr_verify_core_compact(pk_b, r_b, s_b, k_b, base_table):
    """The jittable device graph: raw 32-byte columns in, mask out.

    pk_b, r_b: [32, B] uint8 ristretto encodings of A and R (host has
    checked canonical: value < p and even); s_b, k_b: [32, B] uint8 LE
    scalars (s from the signature with the schnorrkel marker bit cleared,
    k = merlin challenge, both < L). Returns bool [B]."""
    a_pt, a_ok = ristretto_decompress(fe.pack_bytes_device(pk_b))
    r_pt, r_ok = ristretto_decompress(fe.pack_bytes_device(r_b))
    r_prime = curve.shamir_double_scalar(
        digits_msb_device(s_b), digits_msb_device(k_b),
        curve.negate(a_pt), base_table,
    )
    return a_ok & r_ok & ristretto_equal(r_prime, r_pt)


# ---------------------------------------------------------------------------
# Host-side preparation.

_P_LE = np.frombuffer(int.to_bytes(P, 32, "little"), dtype=np.uint8)
_L_LE = np.frombuffer(int.to_bytes(L, 32, "little"), dtype=np.uint8)
_ZERO32 = bytes(32)
_ZERO64 = bytes(64)


def _native_challenges(pk_arr, r_arr, msgs):
    """Batched merlin challenges via the C hostprep library; None when no
    toolchain is available (callers fall back to the pure-Python walk).
    Disable with TMTPU_NO_NATIVE=1."""
    import os

    if os.environ.get("TMTPU_NO_NATIVE"):
        return None
    try:
        from tmtpu import native
    except Exception:
        return None
    return native.sr_challenges(pk_arr, r_arr, msgs)


def _challenge_k(pk: bytes, msg: bytes, r_bytes: bytes) -> bytes:
    """The merlin transcript walk of sr25519.PubKeySr25519.verify_signature,
    producing the 32-byte LE challenge scalar k (already reduced mod L)."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pk)
    t.append_message(b"sign:R", r_bytes)
    k = int.from_bytes(t.challenge_bytes(b"sign:c", 64), "little") % L
    return k.to_bytes(32, "little")


def prepare_sr_batch_packed(pks, msgs, sigs):
    """Host prep, packed form: (numpy [128, B] uint8 — pk/r/s/k stacked,
    host_ok). Callers device_put the single plane.

    Host-rejected lanes (wrong length, missing schnorrkel marker bit,
    s >= L, non-canonical A or R encoding) get well-formed dummy inputs and
    are masked out via host_ok."""
    B = len(sigs)
    pks_b = [bytes(p) for p in pks]
    sigs_b = [bytes(s) for s in sigs]
    len_ok = np.fromiter(
        (len(pks_b[i]) == 32 and len(sigs_b[i]) == 64 for i in range(B)),
        dtype=bool, count=B,
    )
    if not len_ok.all():
        pks_b = [p if ok else _ZERO32 for p, ok in zip(pks_b, len_ok)]
        sigs_b = [s if ok else _ZERO64 for s, ok in zip(sigs_b, len_ok)]
    sig_arr = np.frombuffer(b"".join(sigs_b), dtype=np.uint8).reshape(B, 64)
    pk_arr = np.frombuffer(
        b"".join(pks_b), dtype=np.uint8
    ).reshape(B, 32).copy()  # frombuffer views are read-only; lanes get zeroed
    r_arr = sig_arr[:, :32].copy()
    s_arr = sig_arr[:, 32:].copy()
    marker_ok = (s_arr[:, 31] & 0x80) != 0
    s_arr[:, 31] &= 0x7F
    host_ok = (
        len_ok & marker_ok & lt_le(s_arr, _L_LE)
        # canonical ristretto encodings: value < p AND even (IS_NEGATIVE
        # inputs are rejected by DECODE before any field math)
        & lt_le(pk_arr, _P_LE) & ((pk_arr[:, 0] & 1) == 0)
        & lt_le(r_arr, _P_LE) & ((r_arr[:, 0] & 1) == 0)
    )
    if not host_ok.all():
        bad = ~host_ok
        s_arr[bad] = 0
        pk_arr[bad] = 0
        r_arr[bad] = 0
    # merlin challenge per lane (STROBE/Keccak on host; see module doc).
    # The C library (tmtpu/native/hostprep.c tmtpu_sr_challenges) walks the
    # transcripts ~300x faster than the pure-Python merlin — 42 ms vs 12.6 s
    # per 10k lanes; the Python path remains as the no-toolchain fallback
    # and differential oracle (tests/test_tpu_sr25519.py).
    k_arr = _native_challenges(pk_arr, r_arr, msgs)
    if k_arr is None:
        k_arr = np.frombuffer(
            b"".join(
                _challenge_k(p.tobytes(), bytes(m), r.tobytes())
                for p, m, r in zip(pk_arr, msgs, r_arr)
            ),
            dtype=np.uint8,
        ).reshape(B, 32)
    # ONE [128, B] host plane (pk/r/s/k stacked): callers device_put it as
    # a single transfer — per-RPC latency dominates bandwidth on the
    # tunnel-attached TPU, same reason the ed25519 path packs
    # (verify.prepare_batch_packed)
    packed = np.concatenate([
        np.ascontiguousarray(pk_arr.T), np.ascontiguousarray(r_arr.T),
        np.ascontiguousarray(s_arr.T), np.ascontiguousarray(k_arr.T),
    ], axis=0)
    return packed, host_ok


def prepare_sr_batch(pks, msgs, sigs):
    """Per-plane form of prepare_sr_batch_packed: ([32, B] jnp x4
    (pk, r, s, k), host_ok) — tests and the sharded per-plane path."""
    packed, host_ok = prepare_sr_batch_packed(pks, msgs, sigs)
    from tmtpu.tpu.verify import split_packed

    return tuple(jnp.asarray(p) for p in split_packed(packed)), host_ok


@jax.jit
def _sr_verify_compact_jit(pk_b, r_b, s_b, k_b, table):
    return sr_verify_core_compact(pk_b, r_b, s_b, k_b, table)


@jax.jit
def _sr_verify_packed_jit(packed, table):
    """Packed-input twin: ONE [128, B] uint8 H2D transfer, split device-
    side (slices are free under jit)."""
    from tmtpu.tpu.verify import split_packed

    return sr_verify_core_compact(*split_packed(packed), table)


@jax.jit
def _sr_kernel_packed_jit(packed):
    from tmtpu.tpu import kernel as tk
    from tmtpu.tpu.verify import split_packed

    return tk.sr_verify_compact_kernel(*split_packed(packed))


# chaos site on the device dispatch boundary (docs/RESILIENCE.md)
_FAULT_SR_BATCH = faultinject.register("tpu.sr25519.batch")


def batch_verify_sr(pks, msgs, sigs) -> np.ndarray:
    """sr25519 batch verification: bool [B] per-signature validity, exactly
    matching serial PubKeySr25519.verify_signature per lane. On real TPUs
    the fused Pallas kernel (tmtpu.tpu.kernel.sr_verify_compact_kernel)
    runs the whole pipeline in VMEM like the ed25519 path; the plain-XLA
    graph remains the CPU/virtual-mesh path and the fallback should Mosaic
    reject the kernel."""
    B = len(sigs)
    if B == 0:
        return np.zeros(0, dtype=bool)
    faultinject.fire(_FAULT_SR_BATCH)
    from tmtpu.libs import metrics as _m
    from tmtpu.tpu import verify as tv
    from tmtpu.tpu.verify import pad_packed

    t0 = time.perf_counter()
    with trace.span("sr25519.prepare", lanes=B):
        packed, host_ok = prepare_sr_batch_packed(pks, msgs, sigs)
    # breaker replaces the old module _kernel_broken latch: compile
    # rejections trip permanently, transient faults re-probe after
    # backoff (policy in tmtpu.tpu.verify.note_pallas_failure)
    pbr = tv.pallas_breaker("sr25519")
    if tv.use_pallas_kernel() and pbr.allow():
        from tmtpu.tpu import kernel as tk

        padded = max(tk.DEFAULT_TILE, tv._pad_to_bucket(B))
        try:
            with trace.span("sr25519.execute", impl="pallas",
                            lanes=B, padded=padded):
                mask = np.asarray(_sr_kernel_packed_jit(
                    jnp.asarray(pad_packed(packed, padded))))[:B]
            pbr.record_success()
            _m.observe_crypto_batch("sr25519", tv.backend_label(), "pallas",
                                    B, padded, time.perf_counter() - t0)
            return mask & host_ok
        except Exception as e:  # noqa: BLE001
            tv.note_pallas_failure(pbr, e)
            import sys

            print(
                "sr_verify: Pallas kernel "
                f"{'disabled' if pbr.state != 'closed' else 'failed'}"
                f" (breaker {pbr.state}): {e!r}",
                file=sys.stderr)
    # attribute lookup (not an import-time binding) so tests can pin one
    # bucket via monkeypatch, same as the ed25519/secp256k1 paths
    padded = tv._pad_to_bucket(B)
    with trace.span("sr25519.execute", impl="xla", lanes=B, padded=padded):
        packed = pad_packed(packed, padded)
        mask = np.asarray(
            _sr_verify_packed_jit(jnp.asarray(packed), base_table_f32()))[:B]
    _m.observe_crypto_batch("sr25519", tv.backend_label(), "xla",
                            B, padded, time.perf_counter() - t0)
    return mask & host_ok
