"""Multi-chip sharding for the batch verifier + on-device vote tally.

The framework's scale axis is validator-set size (SURVEY.md §5: per-round
work is O(V) signature verifies + O(V) bitarray/power bookkeeping, V ≤ 10000
— types/vote_set.go:18). The TPU mapping is data parallelism over signature
*lanes*: every per-lane array (limbs [20, B], digits [64, B], masks [B]) is
sharded on its trailing batch dimension over a 1-D device mesh (axis
``"sig"``), the fixed-base table is replicated, and the only cross-device
traffic is the tally reduction (psum of power-limb sums — a few hundred
bytes) riding ICI. Scaling to multi-host meshes changes nothing in this
file: the same NamedSharding specs lay lanes out over DCN-connected hosts
and XLA inserts the hierarchical reduction.

Voting powers are int64 in the reference (types/validator.go). TPUs have no
64-bit integer ALU, so powers ride as 5×13-bit limbs ([5, B] int32, same
radix as the field arithmetic); per-limb lane sums stay < 2^31 for any
B ≤ 2^17 and are recombined into a Python int on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tmtpu.tpu import verify as tv

POWER_RADIX = 13
POWER_LIMBS = 5  # 5 * 13 = 65 bits >= int64


def powers_to_limbs(powers) -> np.ndarray:
    """int64-ish array/list [B] -> [5, B] int32 radix-2^13 limbs."""
    out = np.zeros((POWER_LIMBS, len(powers)), dtype=np.int32)
    for i, p in enumerate(powers):
        v = int(p)
        for j in range(POWER_LIMBS):
            out[j, i] = v & ((1 << POWER_RADIX) - 1)
            v >>= POWER_RADIX
        assert v == 0, "voting power exceeds 65 bits"
    return out


def limb_sums_to_int(sums) -> int:
    s = np.asarray(sums, dtype=np.int64)
    return int(sum(int(s[j]) << (POWER_RADIX * j) for j in range(POWER_LIMBS)))


def pack_bitarray(mask):
    """bool [B] -> uint32 words [ceil(B/32)] (zero-padded high bits).
    The on-device equivalent of libs/bits.BitArray for vote bookkeeping."""
    b = mask.shape[0]
    if b % 32:
        mask = jnp.concatenate(
            [mask, jnp.zeros(32 - b % 32, dtype=mask.dtype)]
        )
        b = mask.shape[0]
    w = mask.reshape(b // 32, 32).astype(jnp.uint32)
    return (w << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
        axis=1, dtype=jnp.uint32
    )


def verify_tally_step_compact(pk_b, r_b, s_b, h_b, power_limbs, table):
    """The flagship device step: batch-verify all lanes, then reduce the
    valid lanes' voting power and pack the validity bitarray — the fused
    VoteSet.addVote hot path (types/vote_set.go:233-304) for a whole
    round's votes at once. Inputs are raw [32, B] byte columns (128 B/lane
    over the host->device link), unpacked on device
    (tv.verify_core_compact). Returns (mask [B] bool, power_sums [5]
    int32, bit_words [B/32] uint32)."""
    mask = tv.verify_core_compact(pk_b, r_b, s_b, h_b, table)
    power_sums = jnp.sum(power_limbs * mask[None].astype(jnp.int32), axis=1)
    return mask, power_sums, pack_bitarray(mask)


def verify_tally_step_kernel(pk_b, r_b, s_b, h_b, power_limbs):
    """verify_tally_step_compact with the verification running as the
    fused Pallas kernel (tmtpu.tpu.kernel) — the production TPU path; the
    tally stays a handful of XLA reduction ops on the kernel's mask."""
    from tmtpu.tpu import kernel as tk

    mask = tk.verify_compact_kernel(pk_b, r_b, s_b, h_b)
    power_sums = jnp.sum(power_limbs * mask[None].astype(jnp.int32), axis=1)
    return mask, power_sums, pack_bitarray(mask)


def verify_tally_packed_kernel(packed, power_limbs):
    """Packed-input twin of verify_tally_step_kernel: ONE [128, B] uint8
    plane (pk | r | s | h) so the host->device hop is a single transfer —
    the tunnel link's per-RPC latency dominates bandwidth (see
    tv.prepare_batch_packed)."""
    return verify_tally_step_kernel(*tv.split_packed(packed), power_limbs)


def verify_tally_packed_compact(packed, power_limbs, table):
    """Packed-input twin of verify_tally_step_compact (XLA-graph path)."""
    return verify_tally_step_compact(
        *tv.split_packed(packed), power_limbs, table)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), ("sig",))


def sharded_verify_tally_compact(mesh: Mesh):
    """Build the pjit'd multi-chip step for ``mesh``: every [32, B] byte
    column shards on its lane ("sig") dimension, unpack happens
    shard-locally, and only the power reduction crosses devices as an XLA
    psum riding ICI."""
    lane = NamedSharding(mesh, P(None, "sig"))
    flat = NamedSharding(mesh, P("sig"))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        verify_tally_step_compact,
        in_shardings=(lane, lane, lane, lane, lane, repl),
        out_shardings=(flat, repl, flat),
    )


def sharded_verify_tally_kernel(mesh: Mesh, *, tile: int | None = None,
                                interpret: bool | None = None):
    """Multi-chip fused-kernel step: shard_map over the "sig" lane axis
    with the Pallas kernel running shard-locally on each chip and the
    power tally reduced across the mesh with one psum riding ICI. Each
    shard's lane count must be a multiple of the kernel tile.

    This is the production pod-scale path; the XLA-graph twin
    (sharded_verify_tally_compact) remains for CPU meshes and the driver
    dryrun, where Mosaic isn't available."""
    try:
        from jax import shard_map

        # jax >= 0.8 renamed check_rep -> check_vma
        rep_kw = {"check_vma": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        rep_kw = {"check_rep": False}

    from tmtpu.tpu import kernel as tk

    kw = {}
    if tile is not None:
        kw["tile"] = tile
    if interpret is not None:
        kw["interpret"] = interpret

    def local_step(pk_b, r_b, s_b, h_b, power_limbs):
        mask = tk.verify_compact_kernel(pk_b, r_b, s_b, h_b, **kw)
        local = jnp.sum(power_limbs * mask[None].astype(jnp.int32), axis=1)
        power_sums = jax.lax.psum(local, "sig")
        return mask, power_sums, pack_bitarray(mask)

    return jax.jit(shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(None, "sig"),) * 5,
        out_specs=(P("sig"), P(), P("sig")),
        **rep_kw,
    ))


def sharded_verify_tally_packed(mesh: Mesh):
    """Packed-input twin of :func:`sharded_verify_tally_compact` — the
    production mesh-dispatch entry (tpu/mesh_dispatch.py). ONE [128, B]
    uint8 plane rides host->device, shards on its lane dimension, and is
    split shard-locally; the power tally crosses devices as the only
    collective. B must be a multiple of 32 x n_devices (the packed
    bitarray output shards one uint32 word per 32 lanes)."""
    lane = NamedSharding(mesh, P(None, "sig"))
    flat = NamedSharding(mesh, P("sig"))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        verify_tally_packed_compact,
        in_shardings=(lane, lane, repl),
        out_shardings=(flat, repl, flat),
    )


def sharded_verify_tally_packed_kernel(mesh: Mesh, *,
                                       tile: int | None = None,
                                       interpret: bool | None = None):
    """Packed-input twin of :func:`sharded_verify_tally_kernel`: the
    fused Pallas kernel under shard_map with a single [128, B] transfer.
    Each shard's lane count must be a multiple of the kernel tile."""
    try:
        from jax import shard_map

        rep_kw = {"check_vma": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        rep_kw = {"check_rep": False}

    from tmtpu.tpu import kernel as tk

    kw = {}
    if tile is not None:
        kw["tile"] = tile
    if interpret is not None:
        kw["interpret"] = interpret

    def local_step(packed, power_limbs):
        mask = tk.verify_compact_kernel(*tv.split_packed(packed), **kw)
        local = jnp.sum(power_limbs * mask[None].astype(jnp.int32), axis=1)
        power_sums = jax.lax.psum(local, "sig")
        return mask, power_sums, pack_bitarray(mask)

    return jax.jit(shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(None, "sig"), P(None, "sig")),
        out_specs=(P("sig"), P(), P("sig")),
        **rep_kw,
    ))


def sharded_verify_sr(mesh: Mesh):
    """Lane-sharded sr25519 batch verify over ``mesh``: the [128, B]
    packed plane (pk|r|s|k — sr_verify.prepare_sr_batch_packed) shards on
    lanes, the fixed-base table replicates, and ristretto decode + the
    shared-doubling ladder run shard-locally. Verification is
    embarrassingly parallel — no collective at all; the sharded mask
    feeds whatever reduction the caller wants."""
    from tmtpu.tpu import sr_verify as srv

    lane = NamedSharding(mesh, P(None, "sig"))
    flat = NamedSharding(mesh, P("sig"))
    repl = NamedSharding(mesh, P())

    def step(packed, table):
        return srv.sr_verify_core_compact(*tv.split_packed(packed), table)

    return jax.jit(step, in_shardings=(lane, repl), out_shardings=flat)


def sharded_verify_k1(mesh: Mesh):
    """Lane-sharded secp256k1 batch verify over ``mesh``: the [168, B]
    packed plane (k1_verify.prepare_k1_batch_packed) shards on lanes, the
    fixed-base table replicates; decompression, the Straus ladder and the
    projective x(R) ≡ r check run shard-locally with no collectives."""
    from tmtpu.tpu import k1_verify as kv

    lane = NamedSharding(mesh, P(None, "sig"))
    flat = NamedSharding(mesh, P("sig"))
    repl = NamedSharding(mesh, P())

    def step(packed, table):
        planes, parity = kv.split_packed_k1(packed)
        return kv.verify_core_compact(planes[0], parity, *planes[1:],
                                      table)

    return jax.jit(step, in_shardings=(lane, repl), out_shardings=flat)


_fused_jit = None
_fused_kernel_jit = None


def _fused_step():
    global _fused_jit
    if _fused_jit is None:
        _fused_jit = jax.jit(verify_tally_packed_compact)
    return _fused_jit


def _fused_kernel_step():
    global _fused_kernel_jit
    if _fused_kernel_jit is None:
        _fused_kernel_jit = jax.jit(verify_tally_packed_kernel)
    return _fused_kernel_jit


def batch_verify_tally(pks, msgs, sigs, powers):
    """Host-facing fused entry: bytes -> (validity mask [B] bool ndarray,
    summed voting power of valid lanes as a Python int). One device dispatch
    runs verify + power-psum + bitarray pack (verify_tally_step_compact);
    this is
    what crypto.batch.TPUBatchVerifier.verify_tally calls.

    Lanes failing the host-side checks (bad lengths, s >= L, non-canonical
    A.y) are masked out AND their power is zeroed before the device sum.
    """
    import time

    from tmtpu.libs import metrics as _m
    from tmtpu.libs import trace

    B = len(sigs)
    if B == 0:
        return np.zeros(0, dtype=bool), 0
    t0 = time.perf_counter()
    with trace.span("crypto.batch_verify_tally", curve="ed25519",
                    lanes=B) as sp:
        with trace.span("ed25519.prepare", lanes=B):
            packed, host_ok = tv.prepare_batch_packed(pks, msgs, sigs)
        p = np.asarray(powers, dtype=np.int64).copy()
        assert p.shape == (B,)
        p[~host_ok] = 0
        use_kernel = tv.use_pallas_kernel()
        impl = "pallas" if use_kernel else "xla"
        padded = tv._pad_to_bucket(B)
        if use_kernel:
            from tmtpu.tpu import kernel as tk

            padded = max(tk.DEFAULT_TILE, padded)
        sp.set(impl=impl, padded=padded)
        with trace.span("ed25519.pad", padded=padded):
            power_limbs = np.zeros((POWER_LIMBS, padded), dtype=np.int32)
            power_limbs[:, :B] = powers_to_limbs(p)
            packed_h = tv.pad_packed(packed, padded)
        with trace.span("ed25519.device_put"):
            packed = jnp.asarray(packed_h)  # ONE transfer
        with trace.span("ed25519.execute", impl=impl):
            if use_kernel:
                mask, power_sums, _bits = _fused_kernel_step()(
                    packed, jnp.asarray(power_limbs))
            else:
                mask, power_sums, _bits = _fused_step()(
                    packed, jnp.asarray(power_limbs), tv.base_table_f32()
                )
            mask = jax.block_until_ready(mask)
        with trace.span("ed25519.readback"):
            mask = np.asarray(mask)[:B] & host_ok
            tallied = limb_sums_to_int(power_sums)
    _m.observe_crypto_batch("ed25519", tv.backend_label(), impl, B, padded,
                            time.perf_counter() - t0)
    return mask, tallied


def _tile(a, reps):
    return jnp.repeat(a, reps, axis=-1)


def example_batch(lanes: int):
    """Deterministic well-formed device args with ``lanes`` lanes (one real
    signature tiled), for compile checks and benchmarks (compact form)."""
    from tmtpu.crypto import ed25519_ref as ref

    seed = bytes(range(32))
    msg = b"tmtpu-example-vote-sign-bytes" * 4
    pk = ref.public_key(seed)
    sig = ref.sign(seed, msg)
    args, host_ok = tv.prepare_batch_compact([pk], [msg], [sig])
    assert host_ok.all()
    return tuple(_tile(a, lanes) for a in args)
