"""Batched edwards25519 point arithmetic on TPU.

Points are extended twisted-Edwards coordinates (X, Y, Z, T) with
x = X/Z, y = Y/Z, T = XY/Z — a 4-tuple of fe limb arrays [20, B].

The unified addition (add-2008-hwcd-3) is complete on ed25519 for *all*
curve points (a = -1 is square mod p since p ≡ 1 mod 4, d non-square), so
identity/doubling/mixed-order inputs need no special-casing on device —
crucial for SIMD batches where each lane may hold a different case.
Reference semantics being reproduced: cofactorless verify per Go stdlib
(crypto/ed25519/ed25519.go:148), oracle in tmtpu.crypto.ed25519_ref.

Two cached operand forms avoid per-add constant multiplies:
- ``niels(P)`` for affine/extended *constants*: (Y-X, Y+X, 2d*T) with Z=1
  (7-mul mixed add);
- ``cached(P)`` for projective operands: (Y-X, Y+X, 2Z, 2d*T) (8-mul add).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmtpu.crypto import ed25519_ref as ref
from tmtpu.tpu import fe

# 2d mod p as canonical limbs (host constant).
D2_INT = (2 * ref.D) % ref.P
D2_LIMBS = fe.limbs_of_int(D2_INT)


def identity(batch_shape):
    z = jnp.zeros((fe.NLIMBS,) + tuple(batch_shape), dtype=jnp.int32)
    # concat instead of .at[0:1].set: .at lowers to scatter, which Mosaic
    # (the Pallas TPU kernel reuses this) has no lowering for
    one = jnp.concatenate(
        [jnp.ones((1,) + tuple(batch_shape), dtype=jnp.int32), z[1:]], axis=0
    )
    return (z, one, one, z)


def double(p):
    """dbl-2008-hwcd — valid for all points. 4 squarings + 4 muls."""
    X, Y, Z, _ = p
    A = fe.sq(X)
    B = fe.sq(Y)
    C = fe.add(fe.sq(Z), fe.sq(Z))
    H = fe.add(A, B)
    E = fe.sub(H, fe.sq(fe.add(X, Y)))
    G = fe.sub(A, B)
    F = fe.add(C, G)
    return (fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def add_niels(p, n):
    """p (extended) + n (niels: Ym=Y-X, Yp=Y+X, T2d=2dT, implicit Z=1)."""
    X1, Y1, Z1, T1 = p
    Ym, Yp, T2d = n
    A = fe.mul(fe.sub(Y1, X1), Ym)
    B = fe.mul(fe.add(Y1, X1), Yp)
    C = fe.mul(T1, T2d)
    D = fe.add(Z1, Z1)
    E = fe.sub(B, A)
    F = fe.sub(D, C)
    G = fe.add(D, C)
    H = fe.add(B, A)
    return (fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def add_cached(p, c):
    """p (extended) + c (cached: Ym=Y-X, Yp=Y+X, Z2=2Z, T2d=2dT)."""
    X1, Y1, Z1, T1 = p
    Ym, Yp, Z2, T2d = c
    A = fe.mul(fe.sub(Y1, X1), Ym)
    B = fe.mul(fe.add(Y1, X1), Yp)
    C = fe.mul(T1, T2d)
    D = fe.mul(Z1, Z2)
    E = fe.sub(B, A)
    F = fe.sub(D, C)
    G = fe.add(D, C)
    H = fe.add(B, A)
    return (fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def to_cached(p):
    X, Y, Z, T = p
    d2 = fe.const_col("D2", D2_LIMBS)
    return (fe.sub(Y, X), fe.add(Y, X), fe.add(Z, Z), fe.mul(T, d2))


def negate(p):
    X, Y, Z, T = p
    return (fe.neg(X), Y, Z, fe.neg(T))


def on_curve_mask(p):
    """-x^2 + y^2 == z^2 + d*x^2*y^2/z^2 check in projective form:
    Z^2(Y^2 - X^2) == Z^4 + d X^2 Y^2 — returns bool [B]. (Host-side
    decompression already guarantees this for A; used in tests.)"""
    X, Y, Z, _ = p
    x2, y2, z2 = fe.sq(X), fe.sq(Y), fe.sq(Z)
    lhs = fe.freeze(fe.mul(z2, fe.sub(y2, x2)))
    d = jnp.asarray(fe.limbs_of_int(ref.D))[:, None]
    rhs = fe.freeze(fe.add(fe.sq(z2), fe.mul(d, fe.mul(x2, y2))))
    return jnp.all(lhs == rhs, axis=0)


# ---------------------------------------------------------------------------
# Window tables.

WINDOW = 4
NDIGITS = 64  # ceil(256 / WINDOW)


def fixed_base_niels_table() -> np.ndarray:
    """[16, 3, 20] int32: niels form of d*B for d in 0..15 (identity at 0).
    Host-computed once from the reference oracle."""
    rows = []
    for d in range(1 << WINDOW):
        pt = ref.scalar_mult(d, ref.BASE)
        x, y = ref.affine(pt)
        t = x * y % ref.P
        rows.append(
            np.stack(
                [
                    fe.limbs_of_int((y - x) % ref.P),
                    fe.limbs_of_int((y + x) % ref.P),
                    fe.limbs_of_int(t * D2_INT % ref.P),
                ]
            )
        )
    return np.stack(rows)  # [16, 3, 20]


def lookup_niels_const(table_f32, digits):
    """table_f32 [16, 3, 20] float32, digits [B] int32 -> niels ([20,B] x3).

    One-hot matmul instead of gather: limbs < 2^13 are exact in f32, and the
    [B,16]x[16,60] contraction rides the MXU. Precision HIGHEST is required:
    the TPU MXU's default f32 matmul truncates inputs to bf16 (8-bit
    mantissa), which corrupts 13-bit limbs."""
    oh = jax.nn.one_hot(digits, 1 << WINDOW, dtype=jnp.float32)  # [B, 16]
    flat = table_f32.reshape(1 << WINDOW, -1)  # [16, 60]
    sel = jnp.matmul(oh, flat, precision=jax.lax.Precision.HIGHEST)  # [B, 60]
    sel = sel.astype(jnp.int32).T.reshape(3, fe.NLIMBS, -1)
    return (sel[0], sel[1], sel[2])


def build_cached_table(p):
    """Per-lane window table: cached form of d*p for d in 0..15.
    Returns [16, 4, 20, B] int32 (d=0 is the cached identity).

    The 14 repeated adds run as a ``lax.scan`` rather than a Python unroll:
    each add is ~8 field muls, and unrolling all of them dominated trace and
    XLA compile time (the dryrun/driver budget), while the scanned form
    compiles the body once with identical arithmetic."""
    B = p[0].shape[1:]
    ident = identity(B)
    c1 = to_cached(p)

    def step(acc, _):
        nxt = add_cached(acc, c1)
        return nxt, jnp.stack(to_cached(nxt))  # [4, 20, B]

    _, rest = jax.lax.scan(step, p, None, length=(1 << WINDOW) - 2)
    head = jnp.stack([jnp.stack(to_cached(ident)), jnp.stack(c1)])
    return jnp.concatenate([head, rest])  # [16, 4, 20, B]


def lookup_cached_batched(table_f32, digits):
    """table_f32 [16, 4, 20, B] float32, digits [B] -> cached ([20,B] x4)."""
    oh = jax.nn.one_hot(digits, 1 << WINDOW, dtype=jnp.float32, axis=0)  # [16, B]
    sel = jnp.einsum(
        "tclb,tb->clb", table_f32, oh, precision=jax.lax.Precision.HIGHEST
    ).astype(jnp.int32)
    return (sel[0], sel[1], sel[2], sel[3])


def shamir_double_scalar(s_digits, h_digits, a_point, base_table_f32):
    """[s]B + [h]A per lane, MSB-first 4-bit windows (Straus/Shamir).

    s_digits, h_digits: [64, B] int32 in [0, 16), most-significant first.
    a_point: extended (4x [20, B]).
    Returns the extended result. ~256 doublings + 128 table adds shared
    across both scalars; each op is vectorized over the whole batch.
    """
    a_table = build_cached_table(a_point).astype(jnp.float32)
    batch = a_point[0].shape[1:]

    def body(w, p):
        for _ in range(WINDOW):
            p = double(p)
        ds = jax.lax.dynamic_index_in_dim(s_digits, w, 0, keepdims=False)
        dh = jax.lax.dynamic_index_in_dim(h_digits, w, 0, keepdims=False)
        p = add_niels(p, lookup_niels_const(base_table_f32, ds))
        p = add_cached(p, lookup_cached_batched(a_table, dh))
        return p

    return jax.lax.fori_loop(0, NDIGITS, body, identity(batch))


def compress_check(p, y_claim, sign_claim):
    """Byte-exact encode-and-compare (the ed25519_ref.verify final step,
    without materializing bytes): freeze x = X/Z, y = Y/Z and compare y's
    255 bits and x's parity against the claimed encoding.

    y_claim: [20, B] limbs of the claimed encoding's low 255 bits;
    sign_claim: [B] int32 in {0,1} (bit 255). Returns bool [B]."""
    X, Y, Z, _ = p
    zinv = fe.invert(Z)
    y = fe.freeze(fe.mul(Y, zinv))
    x = fe.freeze(fe.mul(X, zinv))
    y_ok = jnp.all(y == y_claim, axis=0)
    sign_ok = (x[0] & 1) == sign_claim
    return y_ok & sign_ok
