"""Batched ed25519 signature verification on TPU.

The device graph reproduces, lane-for-lane, the cofactorless Go-stdlib verify
semantics (reference: crypto/ed25519/ed25519.go:148-155; spec oracle:
tmtpu.crypto.ed25519_ref.verify):

    decode A; reject s >= L; h = SHA-512(R || A || msg) mod L;
    R' = [s]B + [h](-A); byte-compare encode(R') against the signature's R.

Split of labor:
- **host** (cheap, data-dependent byte work): length checks, ``s < L``,
  canonical-``y`` check on A, SHA-512 (messages are short and distinct),
  reduction mod L — vectorized numpy / C-backed hashlib;
- **device**: byte->limb unpacking and 4-bit window extraction (raw
  32-byte columns ship over the host link — 128 B/lane), then all the
  field/curve arithmetic (~99% of the FLOPs): point decompression (sqrt
  in GF(p)), the shared-doubling Straus/Shamir ladder [s]B + [h](-A), and
  the byte-exact compressed comparison.

Every device op is elementwise over the trailing batch dimension, so the
whole pipeline shards over a device mesh by splitting lanes (data parallel
over signatures); see tmtpu.tpu.sharding.
"""

from __future__ import annotations

import hashlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from tmtpu.crypto import ed25519_ref as ref
from tmtpu.libs import faultinject, trace
from tmtpu.tpu import curve, fe

# chaos site on the device dispatch boundary (docs/RESILIENCE.md): an
# injected error/latency here models a failing/hung TPU batch and must
# surface as breaker accounting + CPU fallback in crypto/batch.py
_FAULT_ED_BATCH = faultinject.register("tpu.ed25519.batch")

L = ref.L
WINDOW = curve.WINDOW
NDIGITS = curve.NDIGITS

D_LIMBS = fe.limbs_of_int(ref.D)
SQRT_M1_LIMBS = fe.limbs_of_int(ref.SQRT_M1)


def _const(limbs):
    return jnp.asarray(limbs)[:, None]


def decompress(y, sign):
    """Batched point decompression: y limbs [20, B] (canonical, < p —
    guaranteed by the host-side check), sign [B] in {0,1}.

    Returns (extended point, valid mask [B]). Invalid lanes hold a garbage
    point (the complete add formulas never fault on it); callers mask.
    Mirrors ed25519_ref._recover_x.
    """
    one = jnp.zeros_like(y).at[0].add(1)
    y2 = fe.sq(y)
    u = fe.sub(y2, one)  # y^2 - 1
    v = fe.add(fe.mul(_const(D_LIMBS), y2), one)  # d y^2 + 1 (never 0: d non-square)
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.freeze(fe.mul(v, fe.sq(x)))
    u_f = fe.freeze(u)
    nu_f = fe.freeze(fe.neg(u))
    ok_direct = jnp.all(vxx == u_f, axis=0)
    ok_twist = jnp.all(vxx == nu_f, axis=0)
    x = jnp.where(ok_twist[None], fe.mul(x, _const(SQRT_M1_LIMBS)), x)
    valid = ok_direct | ok_twist
    xf = fe.freeze(x)
    x_is_zero = jnp.all(xf == 0, axis=0)
    # x == 0 with sign bit set is not a valid encoding (_recover_x: None).
    valid &= ~(x_is_zero & (sign == 1))
    x = jnp.where(((xf[0] & 1) != sign)[None], fe.neg(x), x)
    z = jnp.zeros_like(y).at[0].add(1)
    return (x, y, z, fe.mul(x, y)), valid


def verify_core(pk_y, pk_sign, r_y, r_sign, s_digits, h_digits, base_table):
    """The jittable device graph: all-curve-arithmetic part of batch verify.

    pk_y, r_y: [20, B] canonical limbs of A's / R's claimed y;
    pk_sign, r_sign: [B] int32 sign bits;
    s_digits, h_digits: [64, B] MSB-first 4-bit windows of s and h;
    base_table: [16, 3, 20] float32 niels table of small multiples of B.

    Returns bool [B]: lanes where A decodes AND encode([s]B + [h](-A)) == R.
    """
    a_point, a_ok = decompress(pk_y, pk_sign)
    r_prime = curve.shamir_double_scalar(
        s_digits, h_digits, curve.negate(a_point), base_table
    )
    return a_ok & curve.compress_check(r_prime, r_y, r_sign)


def digits_msb_device(s_bytes):
    """DEVICE [32, B] scalar bytes (LE) -> [64, B] int32 4-bit windows,
    most-significant first (MSB-first because the Straus ladder consumes
    windows high-to-low)."""
    s = s_bytes.astype(jnp.int32)
    lo = s & 0x0F
    hi = s >> 4
    # interleave LSB-first: window 2i = lo[i], 2i+1 = hi[i]
    inter = jnp.stack([lo, hi], axis=1).reshape((64,) + s.shape[1:])
    return inter[::-1]


def verify_core_compact(pk_b, r_b, s_b, h_b, base_table):
    """Compact-transfer device graph: raw 32-byte columns in, mask out.

    pk_b, r_b, s_b, h_b: [32, B] uint8 — the A and R encodings and the
    s / h scalars exactly as on the wire (128 B/lane vs 848 B/lane for
    pre-unpacked limbs+digits; unpacking is a handful of elementwise ops).
    Host guarantees: s < L, A.y canonical (host_ok covers violators).
    """
    pk_sign = (pk_b[31] >> 7).astype(jnp.int32)
    r_sign = (r_b[31] >> 7).astype(jnp.int32)
    mask_hi = jnp.asarray(0x7F, dtype=pk_b.dtype)
    pk_y = fe.pack_bytes_device(pk_b.at[31].set(pk_b[31] & mask_hi))
    r_y = fe.pack_bytes_device(r_b.at[31].set(r_b[31] & mask_hi))
    return verify_core(pk_y, pk_sign, r_y, r_sign,
                       digits_msb_device(s_b), digits_msb_device(h_b),
                       base_table)


# ---------------------------------------------------------------------------
# Host-side preparation.


_L_LE = np.frombuffer(int.to_bytes(L, 32, "little"), dtype=np.uint8)
_ZERO32 = bytes(32)
_ZERO64 = bytes(64)


def _native_prep(pk_arr, r_arr, s_arr, msgs):
    """Batched SHA-512 + mod-L + s<L via the C hostprep library
    (tmtpu/native); None when no toolchain is available (callers fall back
    to the numpy/hashlib path below). Disable with TMTPU_NO_NATIVE=1."""
    import os

    if os.environ.get("TMTPU_NO_NATIVE"):
        return None
    try:
        from tmtpu import native
    except Exception:
        return None
    return native.prep_ed25519(pk_arr, r_arr, s_arr, msgs)


def lt_le(arr: np.ndarray, bound_le: np.ndarray) -> np.ndarray:
    """Vectorized lexicographic ``arr < bound`` over little-endian [B, 32]
    byte rows (the most significant differing byte decides). Used for the
    canonical-scalar (s < L, Go scMinimal) and canonical-field-element
    (value < p) checks here and in sr_verify."""
    B = arr.shape[0]
    diff = arr != bound_le[None, :]
    idx = 31 - np.argmax(diff[:, ::-1], axis=1)
    rows = np.arange(B)
    return diff.any(axis=1) & (arr[rows, idx] < bound_le[idx])


def _s_below_l(s_arr: np.ndarray) -> np.ndarray:
    return lt_le(s_arr, _L_LE)


def prepare_batch_packed(pks, msgs, sigs):
    """Host prep, packed form: returns (numpy [128, B] uint8, host_ok).

    The four 32-byte planes (pk, r, s, h) are stacked into ONE array so
    the host->device hop is a single transfer — on the tunnel-attached
    TPU in this deployment, per-transfer latency dominates bandwidth
    (~70 ms/RPC vs ~30 MB/s), so 1 transfer of 128 B/lane beats 4 of
    32 B/lane by ~3x wall-clock. Output is pure numpy: callers decide
    when the device_put happens (and can overlap it with compute).

    Host-side checks (the ones the device never sees): wrong lengths,
    non-canonical s (>= L), non-canonical A.y (>= p); violating lanes get
    dummy-but-wellformed inputs and are masked via host_ok. No limb/digit
    expansion here — that runs on device (verify_core_compact) — so the
    host does only byte shuffling plus SHA-512 challenge hashing and the
    mod-L reduction."""
    B = len(sigs)
    pks_b = [bytes(p) for p in pks]
    sigs_b = [bytes(s) for s in sigs]
    len_ok = np.fromiter(
        (len(pks_b[i]) == 32 and len(sigs_b[i]) == 64 for i in range(B)),
        dtype=bool, count=B,
    )
    if not len_ok.all():
        pks_b = [p if ok else _ZERO32 for p, ok in zip(pks_b, len_ok)]
        sigs_b = [s if ok else _ZERO64 for s, ok in zip(sigs_b, len_ok)]
    sig_arr = np.frombuffer(b"".join(sigs_b), dtype=np.uint8).reshape(B, 64)
    pk_arr = np.frombuffer(b"".join(pks_b), dtype=np.uint8).reshape(B, 32)
    r_arr = sig_arr[:, :32].copy()
    s_arr = sig_arr[:, 32:].copy()
    native = _native_prep(pk_arr, r_arr, s_arr, msgs)
    if native is not None:
        h_arr, s_ok = native
        host_ok = len_ok & s_ok
    else:
        host_ok = len_ok & _s_below_l(s_arr)
        h_arr = np.frombuffer(
            b"".join(
                int.to_bytes(
                    int.from_bytes(
                        hashlib.sha512(s[:32] + p + bytes(m)).digest(),
                        "little",
                    ) % L,
                    32, "little",
                )
                for s, p, m in zip(sigs_b, pks_b, msgs)
            ),
            dtype=np.uint8,
        ).reshape(B, 32)
    if not host_ok.all():
        s_arr[~host_ok] = 0
    # canonicality of A.y (device packs the masked bytes; the check is host's)
    masked = pk_arr.copy()
    masked[:, 31] &= 0x7F
    host_ok &= ~(
        (masked[:, 0] >= 0xED)
        & np.all(masked[:, 1:31] == 0xFF, axis=1)
        & (masked[:, 31] == 0x7F)
    )
    packed = np.empty((128, B), dtype=np.uint8)
    packed[0:32] = pk_arr.T
    packed[32:64] = r_arr.T
    packed[64:96] = s_arr.T
    packed[96:128] = h_arr.T
    return packed, host_ok


def split_packed(packed):
    """Device-side: one [128, B] plane -> the four [32, B] byte columns."""
    return packed[0:32], packed[32:64], packed[64:96], packed[96:128]


def pad_packed(packed: np.ndarray, padded: int) -> np.ndarray:
    """numpy [rows, B] -> [rows, padded], replicating lane 0 (well-formed;
    pad results are discarded). Row-count agnostic: ed25519/sr25519 pack
    128 rows, secp256k1 packs 168 (k1_verify.prepare_k1_batch_packed)."""
    B = packed.shape[1]
    if padded == B:
        return packed
    return np.concatenate(
        [packed, np.repeat(packed[:, :1], padded - B, axis=1)], axis=1
    )


def prepare_batch_compact(pks, msgs, sigs):
    """Compact host prep: returns ([32, B] uint8 x4 (pk, r, s, h) as jnp
    arrays, host_ok). Thin split over prepare_batch_packed for callers
    that want per-plane arrays (tests, the sharded pjit path whose
    in_shardings are per-plane); the production single-transfer paths use
    the packed form directly."""
    packed, host_ok = prepare_batch_packed(pks, msgs, sigs)
    return tuple(jnp.asarray(p) for p in split_packed(packed)), host_ok


_BASE_TABLE_F32 = None


def base_table_f32():
    global _BASE_TABLE_F32
    if _BASE_TABLE_F32 is None:
        _BASE_TABLE_F32 = jnp.asarray(
            curve.fixed_base_niels_table(), dtype=jnp.float32
        )
    return _BASE_TABLE_F32


def use_pallas_kernel() -> bool:
    """Device-graph implementation choice. The fused Pallas kernel
    (tmtpu.tpu.kernel) is the production path on real TPUs; the plain-XLA
    graph remains for CPU/virtual-mesh runs (tests, multichip dryrun),
    where Mosaic isn't in play and XLA:CPU compiles the scatter form much
    faster. Override with TMTPU_TPU_IMPL=pallas|xla."""
    import os

    impl = os.environ.get("TMTPU_TPU_IMPL", "")
    if impl == "pallas":
        return True
    if impl == "xla":
        return False
    import jax

    # the device platform, not default_backend(): under the axon PJRT
    # plugin the backend is named "axon" but the devices are real TPUs
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# Shared Pallas-fallback latch policy (sr25519 + secp256k1 batch paths):
# substrings identifying a deterministic compile/lowering rejection —
# retrying those pays full trace+lowering cost per batch for nothing,
# while transient runtime faults (device OOM, tunnel RPC hiccup) deserve
# one retry before the per-module latch trips.
_COMPILE_ERR_MARKERS = ("mosaic", "lowering", "unsupported", "unimplemented",
                        "cannot lower", "pallas")


def is_compile_error(e: Exception) -> bool:
    if isinstance(e, NotImplementedError):
        return True
    s = f"{type(e).__name__}: {e}".lower()
    return any(m in s for m in _COMPILE_ERR_MARKERS)


# Pallas-fallback breakers (one per kernel family, replacing the old
# module-level _kernel_broken latches): a compile/lowering rejection is
# deterministic → trip permanently; transient runtime faults open after
# 2 consecutive failures and RE-PROBE after backoff — the old latch
# never un-latched, so one bad minute degraded the process to XLA until
# restart. half_open_probes=1: one good batch re-trusts the kernel.
PALLAS_BREAKER_DEFAULTS = dict(failure_threshold=2, backoff_base_s=30.0,
                               backoff_max_s=600.0, half_open_probes=1)


def pallas_breaker(curve_name: str):
    from tmtpu.libs import breaker as _bk

    return _bk.get(f"pallas.{curve_name}", **PALLAS_BREAKER_DEFAULTS)


def note_pallas_failure(br, e: Exception) -> None:
    """Shared failure policy for a Pallas kernel dispatch exception."""
    if is_compile_error(e):
        br.trip_permanent(f"{type(e).__name__}: {e}")
    else:
        br.record_failure(e)


@jax.jit
def _verify_compact_jit(pk_b, r_b, s_b, h_b, table):
    return verify_core_compact(pk_b, r_b, s_b, h_b, table)


@jax.jit
def _verify_packed_jit(packed, table):
    return verify_core_compact(*split_packed(packed), table)


@jax.jit
def _verify_packed_kernel_jit(packed):
    from tmtpu.tpu import kernel as tk

    return tk.verify_compact_kernel(*split_packed(packed))


def _pad_to_bucket(n: int) -> int:
    """Round the batch up to a small set of sizes so jit caches stay warm
    (recompiling per odd batch size would dwarf the verify itself).
    The floor is 64: every consensus-sized flush (a vote burst, a commit
    slice) shares ONE compiled shape instead of churning 8/16/32 variants
    — the pad lanes are microseconds of device time while each extra
    shape is a fresh multi-second XLA compile. Above that, powers of two
    up to 4096, then multiples of 2048 (a 10k VoteSet pads to 10240
    instead of 16384 — padding waste matters more than cache entries at
    commit-verify scale)."""
    if n > 4096:
        return (n + 2047) // 2048 * 2048
    b = 64
    while b < n:
        b *= 2
    return b


def pad_args_to_bucket(args, B: int, padded: int):
    """Tile each lane array out to the bucket size by replicating lane 0
    (a known-wellformed lane; pad results are discarded)."""
    if padded == B:
        return args
    return tuple(
        jnp.concatenate(
            [a, jnp.repeat(a[..., :1], padded - B, axis=-1)], axis=-1
        )
        for a in args
    )


def backend_label() -> str:
    """The jax device platform for metric labels ('cpu', 'tpu', ...) —
    only consulted after a dispatch, so the backend is already up."""
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        return "unknown"


def batch_verify(pks, msgs, sigs) -> np.ndarray:
    """ed25519 batch verification: returns bool [B] per-signature validity.

    Semantics are exactly per-signature Go-stdlib verify (no batch equation
    shortcuts — each lane independently checks encode([s]B+[h](-A)) == R, so
    a mixed batch yields the exact per-lane mask with no re-run).
    """
    B = len(sigs)
    if B == 0:
        return np.zeros(0, dtype=bool)
    faultinject.fire(_FAULT_ED_BATCH)
    t0 = time.perf_counter()
    with trace.span("crypto.batch_verify", curve="ed25519", lanes=B) as sp:
        with trace.span("ed25519.prepare", lanes=B):
            packed, host_ok = prepare_batch_packed(pks, msgs, sigs)
        pbr = pallas_breaker("ed25519")
        use_kernel = use_pallas_kernel() and pbr.allow()
        impl = "pallas" if use_kernel else "xla"
        if use_kernel:
            from tmtpu.tpu import kernel as tk

            padded = max(tk.DEFAULT_TILE, _pad_to_bucket(B))
        else:
            padded = _pad_to_bucket(B)
        sp.set(impl=impl, padded=padded)
        with trace.span("ed25519.pad", padded=padded):
            packed = pad_packed(packed, padded)
        with trace.span("ed25519.device_put"):
            dev = jnp.asarray(packed)
        with trace.span("ed25519.execute", impl=impl):
            if use_kernel:
                try:
                    out = jax.block_until_ready(
                        _verify_packed_kernel_jit(dev))
                    pbr.record_success()
                except Exception as e:  # noqa: BLE001 — kernel fault:
                    # breaker decides latch-vs-retry, XLA serves THIS batch
                    note_pallas_failure(pbr, e)
                    impl = "xla"
                    sp.set(impl=impl)
                    out = jax.block_until_ready(
                        _verify_packed_jit(dev, base_table_f32()))
            else:
                out = jax.block_until_ready(
                    _verify_packed_jit(dev, base_table_f32()))
        with trace.span("ed25519.readback"):
            mask = np.asarray(out)[:B]
    from tmtpu.libs import metrics as _m

    _m.observe_crypto_batch("ed25519", backend_label(), impl, B, padded,
                            time.perf_counter() - t0)
    return mask & host_ok
