"""Fused Pallas TPU kernel for batched ed25519 verification.

Same per-lane semantics as ``tmtpu.tpu.verify.verify_core_compact`` (the
cofactorless Go-stdlib verify; reference crypto/ed25519/ed25519.go:148-155,
oracle tmtpu.crypto.ed25519_ref.verify), but the entire pipeline — byte
unpack, point decompression, the 64-window Straus/Shamir ladder and the
byte-exact compressed comparison — runs inside ONE Pallas kernel per lane
tile, so the ~3000 field multiplies per signature keep their operands in
VMEM/vector registers instead of round-tripping [20, B] limb arrays through
HBM after every op (which is what bounds the plain-XLA graph: it measures
~22k sig/s on a v5e chip, two orders of magnitude below the VPU's integer
throughput).

Layout: limb arrays are [NLIMBS, T] int32 with the T lanes on the TPU vector
lanes — identical to tmtpu.tpu.fe — so the field/curve routines from
``fe``/``curve`` are reused verbatim inside the kernel. Kernel-specific code
is only what touches refs or needs [1, T]-shaped masks: byte→limb unpack,
the per-lane window-table build/lookup (select chains instead of one-hot
matmuls), decompression and the final compare.

Grid: one program per tile of ``tile`` lanes; programs are independent
(data-parallel over signatures), so the kernel composes with shard_map
lane-sharding across a device mesh unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tmtpu.tpu import curve, fe

NLIMBS = fe.NLIMBS
RADIX = fe.RADIX
WINDOW = curve.WINDOW
NDIGITS = curve.NDIGITS
NTAB = 1 << WINDOW

# Constants plane layout: one [NLIMBS, CONST_COLS] int32 input carries every
# limb-vector constant the kernel needs (Pallas rejects closed-over arrays).
# Columns 0-4: K64P, P_LIMBS, 2d, d, sqrt(-1); columns 16..63: the fixed-base
# niels table (entry d, coord c at column 16 + 3*d + c).
CONST_COLS = 64
_BTAB_COL0 = 16

# default lane-tile per kernel program; batch sizes must be multiples
DEFAULT_TILE = 256

_CONSTS_PLANE = None


def _consts_plane() -> np.ndarray:
    global _CONSTS_PLANE
    if _CONSTS_PLANE is None:
        plane = np.zeros((NLIMBS, CONST_COLS), dtype=np.int32)
        plane[:, 0] = fe.K64P
        plane[:, 1] = fe.P_LIMBS
        plane[:, 2] = curve.D2_LIMBS
        plane[:, 3] = fe.limbs_of_int(curve.ref.D)
        plane[:, 4] = fe.limbs_of_int(curve.ref.SQRT_M1)
        btab = curve.fixed_base_niels_table()  # [16, 3, 20]
        for d in range(NTAB):
            for c in range(3):
                plane[:, _BTAB_COL0 + 3 * d + c] = btab[d, c]
        _CONSTS_PLANE = plane
    return _CONSTS_PLANE


def _unpack_limbs_255(b):
    """[32, T] int32 LE bytes -> [20, T] radix-2^13 limbs of the low 255
    bits (bit 255 — the ed25519 sign bit — is excluded). Each limb spans at
    most 3 bytes, so this is ~6 elementwise row ops per limb."""
    rows = []
    for limb in range(NLIMBS):
        lo_bit = RADIX * limb
        if lo_bit >= 255:
            rows.append(jnp.zeros_like(b[0:1]))
            continue
        hi_bit = min(lo_bit + RADIX, 255)  # exclusive
        nbits = hi_bit - lo_bit
        off = lo_bit & 7
        k0 = lo_bit >> 3
        acc = b[k0 : k0 + 1] >> off
        shift = 8 - off
        k = k0 + 1
        while shift < nbits:
            acc = acc | (b[k : k + 1] << shift)
            shift += 8
            k += 1
        rows.append(acc & ((1 << nbits) - 1))
    return jnp.concatenate(rows, axis=0)


def _digit_rows_msb(b):
    """[32, T] int32 LE scalar bytes -> list of 64 [1, T] 4-bit windows,
    most-significant window first (row w = window 63-w)."""
    rows = []
    for w in range(NDIGITS):
        j = NDIGITS - 1 - w
        byte = b[j // 2 : j // 2 + 1]
        rows.append((byte >> 4) if (j & 1) else (byte & 0x0F))
    return rows


def _row0_one(y):
    """[20, T]-shaped constant 1 (limb vector of the field element 1) —
    concat form; .at[].set lowers to scatter, unsupported in Mosaic."""
    return jnp.concatenate(
        [jnp.ones((1, y.shape[1]), jnp.int32),
         jnp.zeros((NLIMBS - 1, y.shape[1]), jnp.int32)], axis=0)


def _eq_all(a, b):
    """[20, T] x2 -> bool [1, T]: rows equal in every limb. Limbs are
    canonical (< 2^13) so the |diff| sum can't overflow."""
    return jnp.sum(jnp.abs(a - b), axis=0, keepdims=True) == 0


def _decompress(y, sign):
    """Kernel twin of tmtpu.tpu.verify.decompress with [1, T] masks.
    y: [20, T] canonical limbs (host-checked < p), sign: [1, T] in {0,1}."""
    one = _row0_one(y)
    y2 = fe.sq(y)
    u = fe.sub(y2, one)
    v = fe.add(fe.mul(fe.const_col("D", fe.limbs_of_int(curve.ref.D)), y2), one)
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.freeze(fe.mul(v, fe.sq(x)))
    u_f = fe.freeze(u)
    nu_f = fe.freeze(fe.neg(u))
    ok_direct = _eq_all(vxx, u_f)
    ok_twist = _eq_all(vxx, nu_f)
    x = jnp.where(
        ok_twist,
        fe.mul(x, fe.const_col("SQRT_M1", fe.limbs_of_int(curve.ref.SQRT_M1))),
        x,
    )
    valid = ok_direct | ok_twist
    xf = fe.freeze(x)
    x_is_zero = jnp.sum(xf, axis=0, keepdims=True) == 0
    valid &= ~(x_is_zero & (sign == 1))
    x = jnp.where((xf[0:1] & 1) != sign, fe.neg(x), x)
    z = _row0_one(y)
    return (x, y, z, fe.mul(x, y)), valid


def _compress_check(p, y_claim, sign_claim):
    """Kernel twin of curve.compress_check -> bool [1, T]."""
    X, Y, Z, _ = p
    zinv = fe.invert(Z)
    y = fe.freeze(fe.mul(Y, zinv))
    x = fe.freeze(fe.mul(X, zinv))
    return _eq_all(y, y_claim) & ((x[0:1] & 1) == sign_claim)


def _verify_kernel(consts_ref, fc_ref, pk_ref, r_ref, s_ref, h_ref, out_ref,
                   ym_ref, yp_ref, z2_ref, t2_ref, sd_ref, hd_ref,
                   use_dus: bool = True):
    """One lane tile end-to-end. Scratch: the per-lane cached table of
    d*(-A) for d in 0..15 as 4 coordinate planes [16*20, T], plus the two
    MSB-first digit planes [64, T].

    fc_ref carries the five fe-level limb constants pre-replicated to full
    tile width [5*20, T]: narrow [20, 1] constants inside the kernel die in
    Mosaic's layout pass (slice-of-broadcast canonicalizes to a
    2-axis-broadcast of a [1, 1], which has no lowering). consts_ref
    ([20, 64]) still feeds the fixed-base table selects, which never get
    row-sliced."""
    consts = consts_ref[:]
    ctx = {
        "K64P": fc_ref[0 * NLIMBS : 1 * NLIMBS],
        "P_LIMBS": fc_ref[1 * NLIMBS : 2 * NLIMBS],
        "D2": fc_ref[2 * NLIMBS : 3 * NLIMBS],
        "D": fc_ref[3 * NLIMBS : 4 * NLIMBS],
        "SQRT_M1": fc_ref[4 * NLIMBS : 5 * NLIMBS],
        "_dus": use_dus,
    }
    with fe.const_context(ctx):
        _verify_body(consts, pk_ref, r_ref, s_ref, h_ref, out_ref,
                     ym_ref, yp_ref, z2_ref, t2_ref, sd_ref, hd_ref)


def _shamir_ladder(consts, neg_a, tab_refs, d1_ref, d2_ref, T):
    """Shared kernel core: build the per-lane cached window table for -A
    in scratch (entry 0 = identity, entry 1 = -A, then 14 sequential
    adds — each ~8 field muls, unrolled), then run the 64-window
    Straus/Shamir ladder [scalar1]B + [scalar2](-A) with select-chain
    lookups (fixed-base niels from the constants plane; per-lane cached
    from scratch). Returns the extended result."""
    ym_ref, yp_ref, z2_ref, t2_ref = tab_refs
    ident = curve.identity((T,))
    ic = curve.to_cached(ident)
    c1 = curve.to_cached(neg_a)
    for ref_, val in zip(tab_refs, ic):
        ref_[0:NLIMBS] = val
    for ref_, val in zip(tab_refs, c1):
        ref_[NLIMBS : 2 * NLIMBS] = val
    acc = neg_a
    for d in range(2, NTAB):
        acc = curve.add_cached(acc, c1)
        for ref_, val in zip(tab_refs, curve.to_cached(acc)):
            ref_[d * NLIMBS : (d + 1) * NLIMBS] = val

    def lookup_base(dig):
        """dig [1, T] -> niels tuple of [20, T]: select over the 16 table
        columns of the constants plane."""
        sel = [None, None, None]
        for d in range(NTAB):
            m = dig == d
            for c in range(3):
                col = _BTAB_COL0 + 3 * d + c
                const = consts[:, col : col + 1]  # [20, 1]
                sel[c] = (jnp.where(m, const, sel[c])
                          if sel[c] is not None
                          else jnp.broadcast_to(const, (NLIMBS, T)))
        return tuple(sel)

    def lookup_a(dig):
        """dig [1, T] -> cached tuple of [20, T] from the scratch table."""
        outs = []
        for ref_ in tab_refs:
            acc_c = ref_[0:NLIMBS]
            for d in range(1, NTAB):
                acc_c = jnp.where(dig == d,
                                  ref_[d * NLIMBS : (d + 1) * NLIMBS], acc_c)
            outs.append(acc_c)
        return tuple(outs)

    def body(w, p):
        for _ in range(WINDOW):
            p = curve.double(p)
        d1 = d1_ref[pl.ds(w, 1)]
        d2 = d2_ref[pl.ds(w, 1)]
        p = curve.add_niels(p, lookup_base(d1))
        p = curve.add_cached(p, lookup_a(d2))
        return p

    return jax.lax.fori_loop(0, NDIGITS, body, ident)


def _verify_body(consts, pk_ref, r_ref, s_ref, h_ref, out_ref,
                 ym_ref, yp_ref, z2_ref, t2_ref, sd_ref, hd_ref):
    T = pk_ref.shape[1]

    pk_b = pk_ref[:].astype(jnp.int32)
    r_b = r_ref[:].astype(jnp.int32)

    pk_y = _unpack_limbs_255(pk_b)
    r_y = _unpack_limbs_255(r_b)
    pk_sign = pk_b[31:32] >> 7
    r_sign = r_b[31:32] >> 7

    for w, row in enumerate(_digit_rows_msb(s_ref[:].astype(jnp.int32))):
        sd_ref[w : w + 1] = row
    for w, row in enumerate(_digit_rows_msb(h_ref[:].astype(jnp.int32))):
        hd_ref[w : w + 1] = row

    a_point, a_ok = _decompress(pk_y, pk_sign)
    rp = _shamir_ladder(consts, curve.negate(a_point),
                        (ym_ref, yp_ref, z2_ref, t2_ref), sd_ref, hd_ref, T)

    ok = a_ok & _compress_check(rp, r_y, r_sign)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, T))


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_pallas_jit(pk_b, r_b, s_b, h_b, tile: int, interpret: bool):
    B = pk_b.shape[1]
    grid = (B // tile,)
    spec_in = pl.BlockSpec((32, tile), lambda i: (0, i),
                           memory_space=pltpu.VMEM)
    spec_consts = pl.BlockSpec((NLIMBS, CONST_COLS), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    plane = _consts_plane()
    # fe-level constants at full tile width (see _verify_kernel docstring)
    fcols = np.concatenate([plane[:, j] for j in range(5)])  # [5*20]
    fc = jnp.asarray(np.repeat(fcols[:, None], tile, axis=1))
    spec_fc = pl.BlockSpec((5 * NLIMBS, tile), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_verify_kernel, use_dus=not interpret),
        grid=grid,
        in_specs=[spec_consts, spec_fc] + [spec_in] * 4,
        out_specs=pl.BlockSpec((8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, B), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # ym
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # yp
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # z2
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # t2d
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # s digits
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # h digits
        ],
        interpret=interpret,
    )(jnp.asarray(plane), fc, pk_b.astype(jnp.int32),
      r_b.astype(jnp.int32), s_b.astype(jnp.int32), h_b.astype(jnp.int32))
    return out[0]


def _default_interpret() -> bool:
    # device platform, not default_backend(): under the axon PJRT plugin
    # the backend name is "axon" but the devices are real TPUs (same check
    # as verify.use_pallas_kernel)
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def verify_compact_kernel(pk_b, r_b, s_b, h_b, *, tile: int = 256,
                          interpret: bool | None = None):
    """Drop-in twin of verify.verify_core_compact running as one fused
    Pallas kernel. pk_b/r_b/s_b/h_b: [32, B] uint8 device arrays (B a
    multiple of ``tile``; verify.batch_verify pads). Returns bool [B]."""
    if interpret is None:
        interpret = _default_interpret()
    return _verify_pallas_jit(pk_b, r_b, s_b, h_b, tile, interpret) != 0


# ---------------------------------------------------------------------------
# sr25519 fused kernel. Same skeleton as the ed25519 kernel — unpack,
# decompress, per-lane window table, the 64-window Straus/Shamir ladder —
# with ristretto255 decompression (SQRT_RATIO_M1, run for BOTH the pubkey
# A and the signature's R) and projective coset equality replacing the
# Edwards decompress/compress-compare. Semantics twin:
# tmtpu.tpu.sr_verify.sr_verify_core_compact (oracle
# tmtpu.crypto.sr25519.PubKeySr25519.verify_signature).

# fc plane columns for the sr kernel (full tile width; see _verify_kernel
# docstring for why narrow constants can't live inside the kernel):
# K64P, P_LIMBS, D2, D, SQRT_M1, NEG_ONE, NEG_SQRT_M1.
_SR_FC_N = 7

_SR_FCOLS = None


def _sr_fcols() -> np.ndarray:
    global _SR_FCOLS
    if _SR_FCOLS is None:
        P = curve.ref.P
        plane = _consts_plane()  # columns 0-4 are the five fe constants
        _SR_FCOLS = np.concatenate(
            [plane[:, j] for j in range(5)]
            + [fe.limbs_of_int(P - 1), fe.limbs_of_int(P - curve.ref.SQRT_M1)]
        )  # [7*20]
    return _SR_FCOLS


def _abs_fe_k(x):
    """CT_ABS with a [1, T] mask: negate iff the canonical form is odd."""
    xf = fe.freeze(x)
    return jnp.where((xf[0:1] & 1) == 1, fe.neg(xf), xf)


def _ristretto_decompress_k(s):
    """Kernel twin of sr_verify.ristretto_decompress: s [20, T] canonical
    limbs (host-checked < p and even). Returns (extended point, valid
    [1, T])."""
    one = _row0_one(s)
    ss = fe.sq(s)
    u1 = fe.sub(one, ss)
    u2 = fe.add(one, ss)
    u2_sqr = fe.sq(u2)
    d = fe.const_col("D", fe.limbs_of_int(curve.ref.D))
    v = fe.sub(fe.neg(fe.mul(d, fe.sq(u1))), u2_sqr)
    # SQRT_RATIO_M1(1, w) with w = v*u2^2
    w = fe.mul(v, u2_sqr)
    w3 = fe.mul(fe.sq(w), w)
    w7 = fe.mul(fe.sq(w3), w)
    r = fe.mul(w3, fe.pow_p58(w7))
    check = fe.freeze(fe.mul(w, fe.sq(r)))
    correct = _eq_all(check, one)
    flipped = _eq_all(
        check, fe.const_col("NEG_ONE", fe.limbs_of_int(curve.ref.P - 1)))
    flipped_i = _eq_all(
        check,
        fe.const_col("NEG_SQRT_M1",
                     fe.limbs_of_int(curve.ref.P - curve.ref.SQRT_M1)))
    sqrt_m1 = fe.const_col("SQRT_M1", fe.limbs_of_int(curve.ref.SQRT_M1))
    r = jnp.where(flipped | flipped_i, fe.mul(r, sqrt_m1), r)
    ok = correct | flipped
    invsqrt = _abs_fe_k(r)
    den_x = fe.mul(invsqrt, u2)
    den_y = fe.mul(fe.mul(invsqrt, den_x), v)
    x = _abs_fe_k(fe.mul(fe.add(s, s), den_x))
    y = fe.mul(u1, den_y)
    t = fe.mul(x, y)
    yf = fe.freeze(y)
    y_zero = jnp.sum(yf, axis=0, keepdims=True) == 0
    valid = ok & ((fe.freeze(t)[0:1] & 1) == 0) & ~y_zero
    return (x, y, one, t), valid


def _coset_eq_k(p, q):
    """Kernel twin of sr_verify.ristretto_equal -> bool [1, T] (canonical
    limbs are non-negative, so sum == 0 means every limb is zero)."""
    x1, y1 = p[0], p[1]
    x2, y2 = q[0], q[1]
    a = fe.freeze(fe.sub(fe.mul(x1, y2), fe.mul(y1, x2)))
    b = fe.freeze(fe.sub(fe.mul(x1, x2), fe.mul(y1, y2)))
    za = jnp.sum(a, axis=0, keepdims=True) == 0
    zb = jnp.sum(b, axis=0, keepdims=True) == 0
    return za | zb


def _sr_verify_kernel(consts_ref, fc_ref, pk_ref, r_ref, s_ref, k_ref,
                      out_ref, ym_ref, yp_ref, z2_ref, t2_ref, sd_ref,
                      kd_ref, use_dus: bool = True):
    consts = consts_ref[:]
    ctx = {
        "K64P": fc_ref[0 * NLIMBS : 1 * NLIMBS],
        "P_LIMBS": fc_ref[1 * NLIMBS : 2 * NLIMBS],
        "D2": fc_ref[2 * NLIMBS : 3 * NLIMBS],
        "D": fc_ref[3 * NLIMBS : 4 * NLIMBS],
        "SQRT_M1": fc_ref[4 * NLIMBS : 5 * NLIMBS],
        "NEG_ONE": fc_ref[5 * NLIMBS : 6 * NLIMBS],
        "NEG_SQRT_M1": fc_ref[6 * NLIMBS : 7 * NLIMBS],
        "_dus": use_dus,
    }
    with fe.const_context(ctx):
        _sr_verify_body(consts, pk_ref, r_ref, s_ref, k_ref, out_ref,
                        ym_ref, yp_ref, z2_ref, t2_ref, sd_ref, kd_ref)


def _sr_verify_body(consts, pk_ref, r_ref, s_ref, k_ref, out_ref,
                    ym_ref, yp_ref, z2_ref, t2_ref, sd_ref, kd_ref):
    T = pk_ref.shape[1]

    # canonical ristretto encodings have bit 255 clear (value < p,
    # host-checked), so the 255-bit unpack captures the full value
    pk_s = _unpack_limbs_255(pk_ref[:].astype(jnp.int32))
    r_s = _unpack_limbs_255(r_ref[:].astype(jnp.int32))

    for w, row in enumerate(_digit_rows_msb(s_ref[:].astype(jnp.int32))):
        sd_ref[w : w + 1] = row
    for w, row in enumerate(_digit_rows_msb(k_ref[:].astype(jnp.int32))):
        kd_ref[w : w + 1] = row

    a_point, a_ok = _ristretto_decompress_k(pk_s)
    r_point, r_ok = _ristretto_decompress_k(r_s)
    rp = _shamir_ladder(consts, curve.negate(a_point),
                        (ym_ref, yp_ref, z2_ref, t2_ref), sd_ref, kd_ref, T)

    ok = a_ok & r_ok & _coset_eq_k(rp, r_point)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, T))


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _sr_verify_pallas_jit(pk_b, r_b, s_b, k_b, tile: int, interpret: bool):
    B = pk_b.shape[1]
    grid = (B // tile,)
    spec_in = pl.BlockSpec((32, tile), lambda i: (0, i),
                           memory_space=pltpu.VMEM)
    spec_consts = pl.BlockSpec((NLIMBS, CONST_COLS), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    fc = jnp.asarray(np.repeat(_sr_fcols()[:, None], tile, axis=1))
    spec_fc = pl.BlockSpec((_SR_FC_N * NLIMBS, tile), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sr_verify_kernel, use_dus=not interpret),
        grid=grid,
        in_specs=[spec_consts, spec_fc] + [spec_in] * 4,
        out_specs=pl.BlockSpec((8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, B), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # ym
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # yp
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # z2
            pltpu.VMEM((NTAB * NLIMBS, tile), jnp.int32),  # t2d
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # s digits
            pltpu.VMEM((NDIGITS, tile), jnp.int32),        # k digits
        ],
        interpret=interpret,
    )(jnp.asarray(_consts_plane()), fc, pk_b.astype(jnp.int32),
      r_b.astype(jnp.int32), s_b.astype(jnp.int32), k_b.astype(jnp.int32))
    return out[0]


def sr_verify_compact_kernel(pk_b, r_b, s_b, k_b, *, tile: int = 256,
                             interpret: bool | None = None):
    """Fused-kernel twin of sr_verify.sr_verify_core_compact.
    pk_b/r_b/s_b/k_b: [32, B] uint8 device arrays (B a multiple of
    ``tile``). Returns bool [B]."""
    if interpret is None:
        interpret = _default_interpret()
    return _sr_verify_pallas_jit(pk_b, r_b, s_b, k_b, tile, interpret) != 0
