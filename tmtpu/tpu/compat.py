"""Backend selection helpers.

This image boots every Python process with an `axon` PJRT plugin
(sitecustomize) that force-sets ``jax_platforms=axon`` in jax config — so
neither ``JAX_PLATFORMS=cpu`` in the environment nor os.environ tweaks are
enough to get a CPU backend for tests / multi-chip dry-runs, and a wedged
TPU tunnel hangs backend init for every process. ``force_cpu_backend``
reliably pins jax to host CPU with ``n`` virtual devices; call it before
any jax computation (it is a no-op if a backend is already initialized —
too late by then, so call early).
"""

from __future__ import annotations

import os


def force_cpu_backend(n_devices: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # The config pin (after import, so it wins over sitecustomize's env) is
    # sufficient: backend init is lazy and only the requested platform is
    # initialized, so the axon tunnel is never dialed. Do NOT pop the other
    # backend factories from xla_bridge: their registration is what makes
    # "tpu" a known lowering platform, and removing it breaks importing
    # jax.experimental.pallas (checkify registers a tpu lowering rule).
    jax.config.update("jax_platforms", "cpu")

    # XLA:CPU compiles of the big curve graphs run 1-2 minutes EACH;
    # every forced-CPU consumer (the test suite, the driver's multichip
    # dry-run, bench children, the A/B harnesses) repeats them from
    # scratch per process. The persistent compilation cache turns every
    # repeat into a ~15s deserialization. Scoped to this dev/CI path on
    # purpose — production TPU processes never come through here.
    # TMTPU_NO_COMPILE_CACHE=1 opts out (e.g. timing fresh compiles).
    if os.environ.get("TMTPU_NO_COMPILE_CACHE") != "1":
        cache_dir = os.environ.get("TMTPU_COMPILE_CACHE_DIR") or \
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:  # noqa: BLE001 — older jax without the knobs
            pass
