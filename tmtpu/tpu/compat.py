"""Backend selection helpers.

This image boots every Python process with an `axon` PJRT plugin
(sitecustomize) that force-sets ``jax_platforms=axon`` in jax config — so
neither ``JAX_PLATFORMS=cpu`` in the environment nor os.environ tweaks are
enough to get a CPU backend for tests / multi-chip dry-runs, and a wedged
TPU tunnel hangs backend init for every process. ``force_cpu_backend``
reliably pins jax to host CPU with ``n`` virtual devices; call it before
any jax computation (it is a no-op if a backend is already initialized —
too late by then, so call early).
"""

from __future__ import annotations

import os


def force_cpu_backend(n_devices: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # The config pin (after import, so it wins over sitecustomize's env) is
    # sufficient: backend init is lazy and only the requested platform is
    # initialized, so the axon tunnel is never dialed. Do NOT pop the other
    # backend factories from xla_bridge: their registration is what makes
    # "tpu" a known lowering platform, and removing it breaks importing
    # jax.experimental.pallas (checkify registers a tpu lowering rule).
    jax.config.update("jax_platforms", "cpu")
