"""GF(2^255-19) field arithmetic for TPU, batch-vectorized in JAX.

This is the arithmetic core of the TPU batch signature verifier (the
north-star `crypto.backend=tpu` path; the reference verifies serially on CPU
via Go stdlib — crypto/ed25519/ed25519.go:148).

Representation
--------------
A field element is 20 limbs in radix 2^13 (20*13 = 260 bits), dtype int32,
stored limbs-FIRST: an array of shape ``[20, B]`` for a batch of B elements.
The batch dimension is trailing so it lands on the TPU vector lanes (128-wide)
and the small limb dimension on sublanes; every op below is elementwise over
the batch.

TPUs have no 64-bit integer ALU, so limbs are sized such that all
intermediate products and sums fit in int32:

- all routine outputs keep limbs in ``[0, 9500]`` ("loose" form);
- schoolbook products then satisfy ``20 * 9500^2 = 1.805e9 < 2^31``;
- 2^260 ≡ 608 (mod p) folds the high half back (608 = 2^5 * 19), and
  2^520 ≡ 608^2 folds the product's final carry-out.

Carry propagation is done with *vectorized* passes (all limbs at once); the
number of passes per op is chosen so the stated bounds hold for any input in
loose form (see the per-op comments — these are static bounds, not
probabilistic). Only `freeze` (canonicalization for byte-exact compare)
needs an exact sequential borrow chain, and it runs once per verification.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np

RADIX = 13
NLIMBS = 20
MASK = (1 << RADIX) - 1
# 2^260 = 2^(13*20) ≡ 2^5 * 19 = 608 (mod p)
FOLD = 608
# 2^520 ≡ 608^2 (mod p)
FOLD2 = FOLD * FOLD

P_INT = 2**255 - 19


def limbs_of_int(v: int) -> np.ndarray:
    """Canonical little-endian radix-2^13 limbs of ``v`` (host helper)."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def int_of_limbs(a) -> int:
    """Host-side: integer value of a single limb vector (any bounds)."""
    a = np.asarray(a)
    return sum(int(a[i]) << (RADIX * i) for i in range(a.shape[0]))


P_LIMBS = limbs_of_int(P_INT)


# ---------------------------------------------------------------------------
# Constant plumbing. Outside Pallas, limb-vector constants are just
# jnp.asarray'd numpy arrays (XLA embeds them). Inside a Pallas kernel,
# closed-over arrays are rejected ("captures constants — pass them as
# inputs"), so tmtpu.tpu.kernel passes one [20, n] constants plane as a
# kernel input and installs its columns here; every fe/curve routine then
# picks constants up from the active context.

import contextvars

# ContextVar, not a module global: a kernel trace on one thread must not
# leak its Ref-slice constants into an XLA-path trace running concurrently
# on another thread (e.g. consensus compiling the kernel while an RPC
# thread verifies over the plain graph).
_CONST_CTX: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "tmtpu_fe_const_ctx", default=None)


@contextlib.contextmanager
def const_context(consts: dict):
    """Install kernel-provided full-width constant planes (keys: K64P,
    P_LIMBS, D2, D, SQRT_M1) for the duration of a kernel trace."""
    token = _CONST_CTX.set(consts)
    try:
        yield
    finally:
        _CONST_CTX.reset(token)


def const_col(name: str, np_vec) -> jnp.ndarray:
    """Column(s) for a named limb constant — from the kernel context when
    one is active ([20, T] there), else a plain embedded [20, 1]."""
    ctx = _CONST_CTX.get()
    if ctx is not None:
        return ctx[name]
    return jnp.asarray(np_vec)[:, None]


def pack_bytes_le(b: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian byte strings -> [20, B] int32 limbs.

    Only the low 255 bits are packed (bit 255 — the ed25519 sign bit — is
    masked off by the caller before/after as needed: this packs all 256 bits'
    worth only up to 260, so callers must pre-mask byte 31's top bit if it
    must be excluded)."""
    assert b.ndim == 2 and b.shape[1] == 32
    bits = np.unpackbits(b, axis=1, bitorder="little")  # [B, 256]
    pad = np.zeros((b.shape[0], NLIMBS * RADIX - 256), dtype=bits.dtype)
    bits = np.concatenate([bits, pad], axis=1)  # [B, 260]
    w = (1 << np.arange(RADIX, dtype=np.int32))  # [13]
    limbs = bits.reshape(b.shape[0], NLIMBS, RADIX).astype(np.int32) @ w
    return np.ascontiguousarray(limbs.T)  # [20, B]


def pack_bytes_device(b):
    """DEVICE-side [32, B] uint8/int32 little-endian byte strings ->
    [20, B] int32 limbs (the on-device twin of ``pack_bytes_le``).

    Shipping raw 32-byte encodings and unpacking on device cuts H2D
    traffic 2.5x vs pre-packed [20, B] int32 limbs — the host->TPU link
    (a tunnel in this deployment) is the scarce resource, the few
    elementwise shifts here are noise. Callers mask byte 31's sign bit
    beforehand when packing point encodings."""
    b = b.astype(jnp.int32)  # [32, B]
    bits = (b[:, None, :] >> jnp.arange(8, dtype=jnp.int32)[None, :, None]) & 1
    bits = bits.reshape((256,) + b.shape[1:])  # [256, B], LSB-first
    pad = jnp.zeros((NLIMBS * RADIX - 256,) + b.shape[1:], dtype=jnp.int32)
    bits = jnp.concatenate([bits, pad], axis=0)  # [260, B]
    w = (1 << jnp.arange(RADIX, dtype=jnp.int32))  # [13]
    limbs = bits.reshape((NLIMBS, RADIX) + b.shape[1:])
    return (limbs * w[None, :, None]).sum(axis=1, dtype=jnp.int32)


def at_add(x, lo: int, v):
    """x.at[lo:lo+v.shape[0]].add(v), in the form the active compiler
    wants.

    jax lowers ``.at[].add`` to scatter-add even for static slices, and
    Mosaic (Pallas TPU) has no scatter-add lowering — while
    dynamic-update-slice + elementwise add are native to it. Outside the
    kernel the scatter form stays: XLA fuses it well, and the zeros-DUS-add
    expansion blows up XLA:CPU compile time (the multichip dryrun budget).
    Kernel traces are detected via the active const_context (installed by
    tmtpu.tpu.kernel for exactly the duration of the kernel trace); its
    "_dus" entry is False for interpret-mode kernels, which execute through
    XLA CPU where the scatter form is both supported and much faster to
    compile."""
    ctx = _CONST_CTX.get()
    if ctx is not None and ctx.get("_dus", True):
        n = v.shape[0]
        parts = []
        if lo:
            parts.append(x[:lo])
        parts.append(x[lo : lo + n] + v)
        if lo + n < x.shape[0]:
            parts.append(x[lo + n :])
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
    return x.at[lo : lo + v.shape[0]].add(v)


def _carry_pass(x, fold):
    """One vectorized carry pass. If ``fold`` is nonzero, the carry out of
    the top limb wraps to limb 0 multiplied by ``fold``; otherwise the top
    limb keeps its excess (caller guarantees no overflow)."""
    c = x >> RADIX
    x = x - (c << RADIX)
    x = at_add(x, 1, c[:-1])
    if fold:
        x = at_add(x, 0, fold * c[-1:])
    else:
        x = at_add(x, x.shape[0] - 1, c[-1:] << RADIX)
    return x


def carry(x, passes: int, fold: int = FOLD):
    for _ in range(passes):
        x = _carry_pass(x, fold)
    return x


def add(a, b):
    """a + b. Inputs loose (limbs ≤ 9500) -> sum limbs ≤ 19000 -> one pass:
    carries ≤ 2, fold adds ≤ 2*608 to limb 0 -> limbs ≤ 8191+2+1216 = 9409."""
    return carry(a + b, 1)


# 64p as 20 limbs, each in [15168, 16383]: canonical limbs of 64p (21 limbs,
# top = 1) with the top limb folded down and one unit borrowed into each
# lower limb so that limbwise subtraction of any loose element stays with
# small magnitude. Verified in tests: int value == 64 * P_INT.
def _k64p() -> np.ndarray:
    m = np.zeros(NLIMBS + 1, dtype=np.int64)
    v = 64 * P_INT
    for i in range(NLIMBS + 1):
        m[i] = v & MASK
        v >>= RADIX
    k = m[:NLIMBS].copy()
    k[NLIMBS - 1] += m[NLIMBS] << RADIX  # fold 21st limb into the 20th
    # borrow 1 from limb i+1, add 2^13 to limb i, for i = 18..0
    for i in range(NLIMBS - 2, -1, -1):
        k[i] += 1 << RADIX
        k[i + 1] -= 1
    out = k.astype(np.int32)
    assert int_of_limbs(out) == 64 * P_INT
    assert out.min() >= 15000
    return out


K64P = _k64p()


def sub(a, b):
    """a - b + 64p (so the value stays non-negative). Pre-carry limbs are in
    [15168-9500, 16383+2*9500] ⊂ [5668, 35383]; two passes: after pass 1
    carries ≤ 4 so limb0 ≤ 8191+4+608*4 ≤ 10627, after pass 2 carries ≤ 1 so
    limbs ≤ 8191+1+608 = 8800."""
    return carry(a + const_col("K64P", K64P) - b, 2)


def neg(a):
    zero = jnp.zeros_like(a)
    return sub(zero, a)


def _fold_product(c):
    """[40, B] raw-ish coefficients -> [20, B] loose limbs."""
    # Two no-top-fold passes bring 40 coefficients from ≤ 1.9e9 down:
    # pass 1 carries ≤ 232k -> limbs ≤ 8191+232k; pass 2 carries ≤ 29 ->
    # limbs ≤ 8191+30 (the top limb may keep an excess ≤ 2^31 via the
    # explicit fold below).
    c = carry(c, 1, fold=FOLD2)
    c = carry(c, 1, fold=FOLD2)
    # Fold limbs 20..39 (weight 2^260 * 2^13j ≡ 608 * 2^13j):
    low = c[:NLIMBS] + FOLD * c[NLIMBS:]
    # low ≤ 8221 + 608*8221 ≈ 5.0e6; three folding passes:
    # p1: carries ≤ 611 -> limb0 ≤ 8191 + 611 + 608*611 ≈ 3.8e5
    # p2: carries ≤ 47  -> limbs ≤ 8191 + 47 + 608
    # p3: carries ≤ 1   -> limbs ≤ 8191 + 1 + 608 = 8800
    return carry(low, 3)


def mul(a, b):
    """Schoolbook product + reduction. Inputs loose (≤ 9500 -> coefficient
    bound 20*9500^2 = 1.805e9 < 2^31-1). Output loose (≤ 8800)."""
    B = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    # broadcast [20, 1] constants up front: per-row slices of an
    # unbroadcast constant are [1, 1] and their implicit broadcast against
    # [20, B] is a 2-axis broadcast Mosaic can't lower (XLA: free either way)
    a = jnp.broadcast_to(a, (NLIMBS,) + B)
    b = jnp.broadcast_to(b, (NLIMBS,) + B)
    c = jnp.zeros((2 * NLIMBS,) + B, dtype=jnp.int32)
    for i in range(NLIMBS):
        c = at_add(c, i, a[i : i + 1] * b)
    return _fold_product(c)


def sq(a):
    """Square, using symmetry: c_k = sum_{i<j,i+j=k} 2 a_i a_j + a_{k/2}^2.
    With a ≤ 9500 the doubled-operand terms are ≤ 10*(2*9500)*9500 +
    9500^2 = 1.9e9 < 2^31."""
    B = a.shape[1:]
    a2 = a + a  # ≤ 19000; only ever multiplied by a ≤ 9500 below
    c = jnp.zeros((2 * NLIMBS,) + B, dtype=jnp.int32)
    for i in range(NLIMBS):
        c = at_add(c, 2 * i, a[i : i + 1] * a[i : i + 1])
        if i + 1 < NLIMBS:
            c = at_add(c, 2 * i + 1, a2[i : i + 1] * a[i + 1 :])
    return _fold_product(c)


def freeze(x):
    """Canonical form: limbs in [0, 2^13), value in [0, p). Input loose
    (non-negative value, limbs ≤ 9500).

    Verification compares the recomputed R' encoding byte-exactly against the
    signature's R (ed25519_ref.verify), so this must be *exactly* canonical
    for every input — the final carry and the conditional subtract use full
    sequential chains (20 steps each), not the probabilistic-settling
    vectorized passes. Runs once per point decode, so the cost is noise."""
    x = carry(x, 3)  # limbs ≤ 8800, value < 2^260
    for _ in range(2):
        # value < 2^260: bits ≥ 255 live in limb 19 (weight 2^247) bits ≥ 8.
        # Subtract q*2^255 and add q*19 (2^255 ≡ 19 mod p).
        q = x[NLIMBS - 1 :] >> (255 - RADIX * (NLIMBS - 1))
        x = at_add(x, NLIMBS - 1, -(q << 8))
        x = at_add(x, 0, 19 * q)
        x = carry(x, 2)
    # Now value < 2^255 + eps; exact sequential carry (no fold can trigger:
    # value < 2^256 << 2^260).
    for i in range(NLIMBS - 1):
        c = x[i : i + 1] >> RADIX
        x = at_add(at_add(x, i, -(c << RADIX)), i + 1, c)
    # x may still be in [p, 2^255): conditionally subtract p with an exact
    # borrow chain.
    t = x - const_col("P_LIMBS", P_LIMBS)
    for i in range(NLIMBS - 1):
        c = t[i : i + 1] >> RADIX
        t = at_add(at_add(t, i, -(c << RADIX)), i + 1, c)
    return jnp.where(t[NLIMBS - 1 :] < 0, x, t)


def sqn(a, n: int):
    """a^(2^n) — n repeated squarings via fori_loop (keeps the graph small
    for the long runs inside the inversion chain)."""
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return jax.lax.fori_loop(0, n, lambda _, x: sq(x), a)


def pow_p58(a):
    """a^((p-5)/8) = a^(2^252 - 3) — the square-root exponent used in point
    decompression (x = uv^3 (uv^7)^((p-5)/8)). Same ladder family as
    ``invert``: 252 squarings + 11 multiplies, batch-vectorized."""
    t0 = sq(a)  # 2
    t1 = mul(a, sq(sq(t0)))  # 9
    t0 = mul(t0, t1)  # 11
    t0 = mul(t1, sq(t0))  # 31 = 2^5 - 1
    t0 = mul(t0, sqn(t0, 5))  # 2^10 - 1
    t1 = mul(sqn(t0, 10), t0)  # 2^20 - 1
    t2 = mul(sqn(t1, 20), t1)  # 2^40 - 1
    t1 = mul(sqn(t2, 10), t0)  # 2^50 - 1
    t2 = mul(sqn(t1, 50), t1)  # 2^100 - 1
    t2 = mul(sqn(t2, 100), t2)  # 2^200 - 1
    t1 = mul(sqn(t2, 50), t1)  # 2^250 - 1
    return mul(sqn(t1, 2), a)  # 2^252 - 3


def invert(a):
    """a^(p-2) = a^(2^255 - 21) via the standard curve25519 addition chain
    (254 squarings + 11 multiplies), batch-vectorized."""
    t0 = sq(a)  # 2
    t1 = mul(a, sq(sq(t0)))  # 9
    t0 = mul(t0, t1)  # 11
    t1 = mul(t1, sq(t0))  # 31 = 2^5 - 1
    t1 = mul(t1, sqn(t1, 5))  # 2^10 - 1
    t2 = mul(sqn(t1, 10), t1)  # 2^20 - 1
    t2 = mul(sqn(t2, 20), t2)  # 2^40 - 1
    t1 = mul(sqn(t2, 10), t1)  # 2^50 - 1
    t2 = mul(sqn(t1, 50), t1)  # 2^100 - 1
    t2 = mul(sqn(t2, 100), t2)  # 2^200 - 1
    t1 = mul(sqn(t2, 50), t1)  # 2^250 - 1
    return mul(sqn(t1, 5), t0)  # 2^255 - 2^5 + 11 = 2^255 - 21
