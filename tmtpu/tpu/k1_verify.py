"""Batched secp256k1 ECDSA verification on TPU.

Reference semantics: crypto/secp256k1/secp256k1.go:195-197 (btcec Verify of
64-byte R||S low-S signatures over SHA-256(msg)); the serial oracle here is
tmtpu.crypto.secp256k1.PubKeySecp256k1.verify_signature. This completes the
BASELINE.md curve set (ed25519 — tmtpu.tpu.verify; sr25519 —
tmtpu.tpu.sr_verify; secp256k1 — this module) so mixed-curve valsets batch
every lane onto the device.

secp256k1 is short-Weierstrass (y^2 = x^3 + 7) over a different prime than
the 25519 curves, so this module pairs its own field (tmtpu.tpu.fe_k1) with
the *complete* projective addition formulas of Renes–Costello–Batina 2016
(algorithm 7, a = 0, b3 = 21): one formula valid for every input pair —
identity, doubling, inverses — which is what a SIMD batch needs, exactly as
the unified Edwards formulas are for ed25519 (tmtpu.tpu.curve).

Split of labor:
- **host**: signature parsing (r, s in [1, n-1], low-S), SHA-256 digests
  (C-speed via hashlib over the batch), the mod-n scalar work
  u1 = h/s, u2 = r/s (Python bigints per lane — mod-n inversion has no
  13-bit-limb-friendly shape and is ~2 µs/lane), and the canonical-x
  candidates r, r+n for the final comparison;
- **device**: pubkey decompression (sqrt via one (p+1)/4 power chain),
  the Straus/Shamir ladder R = [u1]G + [u2]Q over 64 4-bit windows, and
  the projective check x(R) ≡ r (mod n) — i.e. X == r*Z or (when
  r + n < p, probability ~2^-127) X == (r+n)*Z, with R != infinity.
"""

from __future__ import annotations

import hashlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from tmtpu.crypto.secp256k1 import N
from tmtpu.libs import faultinject, trace
from tmtpu.tpu import fe_k1 as fe
from tmtpu.tpu.verify import lt_le

P = fe.P_INT
B3 = 21  # 3*b for y^2 = x^3 + 7
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

WINDOW = 4
NDIGITS = 64

SEVEN_LIMBS = fe.limbs_of_int(7)


def _const(limbs):
    return jnp.asarray(limbs)[:, None]


# ---------------------------------------------------------------------------
# Complete projective point ops (RCB16 algorithm 7, a = 0).


def identity(batch_shape):
    z = jnp.zeros((fe.NLIMBS,) + tuple(batch_shape), dtype=jnp.int32)
    one = jnp.concatenate(
        [jnp.ones((1,) + tuple(batch_shape), dtype=jnp.int32), z[1:]], axis=0
    )
    return (z, one, z)


def add(p, q):
    """Complete addition: valid for ALL input pairs (including P+P, P+(-P),
    identity operands) — 12 muls + 2 small-constant muls. Validated against
    the affine oracle in tests/test_tpu_k1.py."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = fe.mul(X1, X2)
    t1 = fe.mul(Y1, Y2)
    t2 = fe.mul(Z1, Z2)
    t3 = fe.sub(fe.mul(fe.add(X1, Y1), fe.add(X2, Y2)), fe.add(t0, t1))
    t4 = fe.sub(fe.mul(fe.add(Y1, Z1), fe.add(Y2, Z2)), fe.add(t1, t2))
    y3 = fe.sub(fe.mul(fe.add(X1, Z1), fe.add(X2, Z2)), fe.add(t0, t2))
    t0 = fe.mul_small(t0, 3)  # 3 X1X2  (a = 0)
    t2 = fe.mul_small(t2, B3)  # b3 Z1Z2
    z3 = fe.add(t1, t2)  # Y1Y2 + b3 Z1Z2
    t1 = fe.sub(t1, t2)  # Y1Y2 - b3 Z1Z2
    y3 = fe.mul_small(y3, B3)  # b3 (X1Z2 + X2Z1)
    x3 = fe.sub(fe.mul(t3, t1), fe.mul(t4, y3))
    y3 = fe.add(fe.mul(y3, t0), fe.mul(t1, z3))
    z3 = fe.add(fe.mul(z3, t4), fe.mul(t0, t3))
    return (x3, y3, z3)


def double(p):
    """Complete doubling (RCB16 algorithm 9, a = 0): 6 muls + 2 squarings +
    1 small-constant mul — vs 12 + 2 for ``add(p, p)``. Exception-free for
    every curve point including the identity (traced: (0,1,0) -> (0,1,0));
    secp256k1 has no order-2 points (prime group order), so y = 0 never
    occurs on valid inputs. Validated against ``add(p, p)`` and the affine
    oracle in tests/test_tpu_k1.py."""
    X, Y, Z = p
    t0 = fe.sq(Y)
    z3 = fe.add(t0, t0)
    z3 = fe.add(z3, z3)
    z3 = fe.add(z3, z3)  # 8 Y^2
    t1 = fe.mul(Y, Z)
    t2 = fe.mul_small(fe.sq(Z), B3)  # b3 Z^2
    x3 = fe.mul(t2, z3)
    y3 = fe.add(t0, t2)
    z3 = fe.mul(t1, z3)
    t1 = fe.add(t2, t2)
    t2 = fe.add(t1, t2)
    t0 = fe.sub(t0, t2)
    y3 = fe.add(x3, fe.mul(t0, y3))
    t1 = fe.mul(X, Y)
    x3 = fe.mul(t0, t1)
    x3 = fe.add(x3, x3)
    return (x3, y3, z3)


def negate(p):
    X, Y, Z = p
    return (X, fe.neg(Y), Z)


# ---------------------------------------------------------------------------
# Window tables (mirrors tmtpu.tpu.curve, with 3-component projective rows).


def _affine_mult(k: int):
    """Host oracle: k*G affine via RCB over Python ints (exercised against
    the 'cryptography' library in tests)."""

    def aff_add(a, b):
        if a is None:
            return b
        if b is None:
            return a
        x1, y1 = a
        x2, y2 = b
        if x1 == x2 and (y1 + y2) % P == 0:
            return None
        if a == b:
            lam = 3 * x1 * x1 * pow(2 * y1, -1, P) % P
        else:
            lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
        x3 = (lam * lam - x1 - x2) % P
        return (x3, (lam * (x1 - x3) - y1) % P)

    acc = None
    g = (GX, GY)
    for _ in range(k):
        acc = aff_add(acc, g)
    return acc


def fixed_base_table() -> np.ndarray:
    """[16, 3, 20] int32: projective (X, Y, Z) of d*G for d in 0..15
    (identity (0,1,0) at d=0, affine Z=1 otherwise)."""
    rows = []
    for d in range(1 << WINDOW):
        if d == 0:
            x, y, z = 0, 1, 0
        else:
            x, y = _affine_mult(d)
            z = 1
        rows.append(
            np.stack(
                [fe.limbs_of_int(x), fe.limbs_of_int(y), fe.limbs_of_int(z)]
            )
        )
    return np.stack(rows)


def lookup_const(table_f32, digits):
    """[16, 3, 20] f32 table, [B] digits -> ([20, B] x3) via one-hot matmul
    (limbs < 2^13 are exact in f32; HIGHEST avoids bf16 truncation)."""
    oh = jax.nn.one_hot(digits, 1 << WINDOW, dtype=jnp.float32)  # [B, 16]
    flat = table_f32.reshape(1 << WINDOW, -1)
    sel = jnp.matmul(oh, flat, precision=jax.lax.Precision.HIGHEST)
    sel = sel.astype(jnp.int32).T.reshape(3, fe.NLIMBS, -1)
    return (sel[0], sel[1], sel[2])


def build_lane_table(q):
    """Per-lane window table [16, 3, 20, B]: d*Q for d in 0..15, built with
    15 complete adds under lax.scan (compile-size friendly)."""
    B = q[0].shape[1:]
    ident = identity(B)

    def step(acc, _):
        nxt = add(acc, q)
        return nxt, jnp.stack(nxt)

    _, rest = jax.lax.scan(step, q, None, length=(1 << WINDOW) - 2)
    head = jnp.stack([jnp.stack(ident), jnp.stack(q)])
    return jnp.concatenate([head, rest])


def lookup_lane(table_f32, digits):
    oh = jax.nn.one_hot(digits, 1 << WINDOW, dtype=jnp.float32, axis=0)
    sel = jnp.einsum(
        "tclb,tb->clb", table_f32, oh, precision=jax.lax.Precision.HIGHEST
    ).astype(jnp.int32)
    return (sel[0], sel[1], sel[2])


def shamir_double_scalar(u1_digits, u2_digits, q_point, base_table_f32):
    """[u1]G + [u2]Q per lane, MSB-first 4-bit windows — the Weierstrass
    twin of tmtpu.tpu.curve.shamir_double_scalar (doublings shared across
    both scalars, via the dedicated complete doubling)."""
    lane_table = build_lane_table(q_point).astype(jnp.float32)
    batch = q_point[0].shape[1:]

    def body(w, p):
        for _ in range(WINDOW):
            p = double(p)
        d1 = jax.lax.dynamic_index_in_dim(u1_digits, w, 0, keepdims=False)
        d2 = jax.lax.dynamic_index_in_dim(u2_digits, w, 0, keepdims=False)
        p = add(p, lookup_const(base_table_f32, d1))
        p = add(p, lookup_lane(lane_table, d2))
        return p

    return jax.lax.fori_loop(0, NDIGITS, body, identity(batch))


# ---------------------------------------------------------------------------
# Decompression + the verify graph.


def decompress(x, parity):
    """SEC1 point decompression: x [20, B] canonical limbs (host-checked
    < p), parity [B] in {0,1} (0x02 prefix -> even y). Returns
    ((x, y, 1), valid): y = sqrt(x^3 + 7) with the requested parity;
    invalid where x^3 + 7 is a non-residue."""
    y2 = fe.add(fe.mul(fe.sq(x), x), _const(SEVEN_LIMBS))
    y = fe.sqrt_candidate(y2)
    yf = fe.freeze(y)
    valid = jnp.all(fe.freeze(fe.sq(y)) == fe.freeze(y2), axis=0)
    flip = (yf[0] & 1) != parity
    y = jnp.where(flip[None], fe.neg(yf), yf)
    one = jnp.zeros_like(x).at[0].add(1)
    return (x, y, one), valid


def digits_msb_device_be(s_bytes):
    """DEVICE [32, B] big-endian scalar bytes -> [64, B] int32 4-bit
    windows, most-significant first (big-endian twin of
    tmtpu.tpu.verify.digits_msb_device)."""
    s = s_bytes.astype(jnp.int32)
    hi = s >> 4
    lo = s & 0x0F
    return jnp.stack([hi, lo], axis=1).reshape((64,) + s.shape[1:])


def verify_core_compact(pkx_b, parity, u1_b, u2_b, r_b, rpn_b, base_table):
    """The jittable device graph: raw byte columns in, mask out.

    pkx_b: [32, B] uint8 big-endian pubkey x (host-checked < p);
    parity: [B] int32 (compressed-prefix parity bit);
    u1_b, u2_b: [32, B] uint8 big-endian scalars h/s, r/s mod n;
    r_b: [32, B] uint8 big-endian r (as a field element, r < n < p);
    rpn_b: [32, B] uint8 big-endian second x-candidate — r+n when
    r + n < p, else a copy of r (a harmless duplicate check).
    Returns bool [B]: pubkey decodes AND R = [u1]G + [u2]Q is finite with
    x(R) mod n == r."""
    q_pt, q_ok = decompress(fe.pack_bytes_device(pkx_b), parity)
    r_pt = shamir_double_scalar(
        digits_msb_device_be(u1_b), digits_msb_device_be(u2_b),
        q_pt, base_table,
    )
    X, _, Z = r_pt
    zf = fe.freeze(Z)
    finite = ~jnp.all(zf == 0, axis=0)
    xf = fe.freeze(X)
    r_l = fe.pack_bytes_device(r_b)
    rpn_l = fe.pack_bytes_device(rpn_b)
    m1 = jnp.all(xf == fe.freeze(fe.mul(r_l, Z)), axis=0)
    m2 = jnp.all(xf == fe.freeze(fe.mul(rpn_l, Z)), axis=0)
    return q_ok & finite & (m1 | m2)


# ---------------------------------------------------------------------------
# Host-side preparation.

_P_BE = np.frombuffer(int.to_bytes(P, 32, "big"), dtype=np.uint8)
_N_BE = np.frombuffer(int.to_bytes(N, 32, "big"), dtype=np.uint8)
_HALF_N1_BE = np.frombuffer(
    int.to_bytes(N // 2 + 1, 32, "big"), dtype=np.uint8)
_ZERO33 = bytes(33)
_ZERO64 = bytes(64)
_DUMMY_SCALAR = int.to_bytes(1, 32, "big")


def _lt_be(arr: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """arr < bound lexicographically over big-endian [B, 32] rows
    (little-endian helper reversed)."""
    return lt_le(arr[:, ::-1], bound_be[::-1].copy())


def prepare_k1_batch_packed(pks, msgs, sigs):
    """Host prep, packed form: (numpy [168, B] uint8, host_ok). Host
    rejects wrong lengths, bad SEC1 prefixes, r/s out of [1, n-1], and
    non-low-S (s > n/2) — matching the serial path's checks before any
    curve math."""
    B = len(sigs)
    pks_b = [bytes(p) for p in pks]
    sigs_b = [bytes(s) for s in sigs]
    len_ok = np.fromiter(
        (len(pks_b[i]) == 33 and len(sigs_b[i]) == 64 for i in range(B)),
        dtype=bool, count=B,
    )
    if not len_ok.all():
        pks_b = [p if ok else _ZERO33 for p, ok in zip(pks_b, len_ok)]
        sigs_b = [s if ok else _ZERO64 for s, ok in zip(sigs_b, len_ok)]
    sig_arr = np.frombuffer(b"".join(sigs_b), dtype=np.uint8).reshape(B, 64)
    pk_arr = np.frombuffer(b"".join(pks_b), dtype=np.uint8).reshape(B, 33)
    r_arr = sig_arr[:, :32].copy()
    s_arr = sig_arr[:, 32:]
    prefix = pk_arr[:, 0]
    pkx = pk_arr[:, 1:].copy()
    nonzero_r = r_arr.any(axis=1)
    nonzero_s = s_arr.any(axis=1)
    host_ok = (
        len_ok
        & ((prefix == 2) | (prefix == 3))
        & _lt_be(pkx, _P_BE)
        & nonzero_r & _lt_be(r_arr, _N_BE)
        & nonzero_s & _lt_be(s_arr, _HALF_N1_BE)  # s <= n/2 (low-S)
    )
    # scalar work per lane (Python bigints): w = s^-1, u1 = h*w, u2 = r*w.
    # The n inversions fold into ONE via Montgomery's batch-inversion
    # trick (prefix products + a single pow(-1) + backward sweep): 9 ms
    # vs 103 ms per 4096 lanes — host prep would otherwise bottleneck the
    # fused kernel's device rate on this single-core host.
    ok_idx = [i for i in range(B) if host_ok[i]]
    svals = [int.from_bytes(s_arr[i], "big") for i in ok_idx]
    w_of = {}
    if svals:
        prefix = [0] * len(svals)
        acc = 1
        for j, s in enumerate(svals):
            prefix[j] = acc
            acc = acc * s % N
        inv_acc = pow(acc, -1, N)
        for j in range(len(svals) - 1, -1, -1):
            w_of[ok_idx[j]] = inv_acc * prefix[j] % N
            inv_acc = inv_acc * svals[j] % N
    u1_list, u2_list, rpn_list = [], [], []
    for i in range(B):
        if not host_ok[i]:
            u1_list.append(_DUMMY_SCALAR)
            u2_list.append(_DUMMY_SCALAR)
            rpn_list.append(_DUMMY_SCALAR)
            continue
        r = int.from_bytes(r_arr[i], "big")
        h = int.from_bytes(hashlib.sha256(bytes(msgs[i])).digest(), "big")
        w = w_of[i]
        u1_list.append((h * w % N).to_bytes(32, "big"))
        u2_list.append((r * w % N).to_bytes(32, "big"))
        rpn = r + N
        rpn_list.append((rpn if rpn < P else r).to_bytes(32, "big"))
    if not host_ok.all():
        bad = ~host_ok
        pkx[bad] = 0
        r_arr[bad] = np.frombuffer(_DUMMY_SCALAR, dtype=np.uint8)
    u1_arr = np.frombuffer(b"".join(u1_list), dtype=np.uint8).reshape(B, 32)
    u2_arr = np.frombuffer(b"".join(u2_list), dtype=np.uint8).reshape(B, 32)
    rpn_arr = np.frombuffer(b"".join(rpn_list), dtype=np.uint8).reshape(B, 32)
    parity = (pk_arr[:, 0] & 1).astype(np.uint8)
    # ONE [168, B] host plane: 5 byte planes + the parity row (+7 zero
    # rows to an 8-multiple) — single H2D transfer, split on device
    # (per-RPC latency dominates on the tunnel; see
    # verify.prepare_batch_packed)
    packed = np.concatenate(
        [np.ascontiguousarray(a.T)
         for a in (pkx, u1_arr, u2_arr, r_arr, rpn_arr)]
        + [parity[None, :], np.zeros((7, B), dtype=np.uint8)], axis=0)
    return packed, host_ok


def split_packed_k1(packed):
    """Device-side: [168, B] -> ((pkx, u1, u2, r, rpn) [32, B], parity
    [B] int32)."""
    planes = tuple(packed[32 * i : 32 * (i + 1)] for i in range(5))
    return planes, packed[160].astype(jnp.int32)


def prepare_k1_batch(pks, msgs, sigs):
    """Per-plane form of prepare_k1_batch_packed (tests): ((pkx, u1, u2,
    r, rpn) [32, B] jnp, parity [B] int32, host_ok)."""
    packed, host_ok = prepare_k1_batch_packed(pks, msgs, sigs)
    planes, parity = split_packed_k1(jnp.asarray(packed))
    return planes, parity, host_ok


_BASE_TABLE_F32 = None


def base_table_f32():
    global _BASE_TABLE_F32
    if _BASE_TABLE_F32 is None:
        _BASE_TABLE_F32 = jnp.asarray(fixed_base_table(), dtype=jnp.float32)
    return _BASE_TABLE_F32


@jax.jit
def _k1_verify_compact_jit(pkx_b, parity, u1_b, u2_b, r_b, rpn_b, table):
    return verify_core_compact(pkx_b, parity, u1_b, u2_b, r_b, rpn_b, table)


@jax.jit
def _k1_verify_packed_jit(packed, table):
    """Packed-input twin: ONE [168, B] uint8 H2D transfer, split device-
    side (slices are free under jit)."""
    planes, parity = split_packed_k1(packed)
    return verify_core_compact(planes[0], parity, *planes[1:], table)


@jax.jit
def _k1_kernel_packed_jit(packed):
    from tmtpu.tpu import k1_kernel as kk

    planes, parity = split_packed_k1(packed)
    return kk.k1_verify_compact_kernel(planes[0], parity, *planes[1:])


# chaos site on the device dispatch boundary (docs/RESILIENCE.md)
_FAULT_K1_BATCH = faultinject.register("tpu.secp256k1.batch")


def batch_verify_k1(pks, msgs, sigs) -> np.ndarray:
    """secp256k1 batch verification: bool [B] per-signature validity,
    matching serial PubKeySecp256k1.verify_signature per lane. On real
    TPUs the fused Pallas kernel (tmtpu.tpu.k1_kernel) runs the whole
    device half in VMEM; the plain-XLA graph remains the CPU/virtual-mesh
    path and the fallback should Mosaic reject the kernel."""
    from tmtpu.tpu import verify as tv
    from tmtpu.tpu.verify import pad_packed

    B = len(sigs)
    if B == 0:
        return np.zeros(0, dtype=bool)
    faultinject.fire(_FAULT_K1_BATCH)
    from tmtpu.libs import metrics as _m

    t0 = time.perf_counter()
    with trace.span("secp256k1.prepare", lanes=B):
        packed, host_ok = prepare_k1_batch_packed(pks, msgs, sigs)
    # breaker replaces the old module _kernel_broken latch (policy in
    # tmtpu.tpu.verify.note_pallas_failure, same as sr_verify)
    pbr = tv.pallas_breaker("secp256k1")
    if tv.use_pallas_kernel() and pbr.allow():
        from tmtpu.tpu import k1_kernel as kk

        padded = max(kk.DEFAULT_TILE, tv._pad_to_bucket(B))
        try:
            with trace.span("secp256k1.execute", impl="pallas",
                            lanes=B, padded=padded):
                mask = np.asarray(_k1_kernel_packed_jit(
                    jnp.asarray(pad_packed(packed, padded))))[:B]
            pbr.record_success()
            _m.observe_crypto_batch("secp256k1", tv.backend_label(),
                                    "pallas", B, padded,
                                    time.perf_counter() - t0)
            return mask & host_ok
        except Exception as e:  # noqa: BLE001
            tv.note_pallas_failure(pbr, e)
            import sys

            print(
                "k1_verify: Pallas kernel "
                f"{'disabled' if pbr.state != 'closed' else 'failed'}"
                f" (breaker {pbr.state}): {e!r}",
                file=sys.stderr)
    padded = tv._pad_to_bucket(B)
    with trace.span("secp256k1.execute", impl="xla", lanes=B,
                    padded=padded):
        packed = pad_packed(packed, padded)
        mask = np.asarray(
            _k1_verify_packed_jit(jnp.asarray(packed), base_table_f32()))[:B]
    _m.observe_crypto_batch("secp256k1", tv.backend_label(), "xla",
                            B, padded, time.perf_counter() - t0)
    return mask & host_ok
