"""GF(2^256 - 2^32 - 977) field arithmetic for TPU (secp256k1's base field),
batch-vectorized in JAX — the secp256k1 twin of tmtpu.tpu.fe.

The reference verifies secp256k1 serially on CPU via btcec
(crypto/secp256k1/secp256k1.go:195-197); BASELINE.md lists secp256k1
batches among the north-star curves. Layout matches tmtpu.tpu.fe: a field
element is 20 radix-2^13 int32 limbs, limbs-first ([20, B], batch on the
TPU vector lanes).

Reduction identities (everything below follows from these):

    2^260 ≡ 2^36 + 15632                      (mod p)   [ = 2^4 (2^32+977) ]
    2^520 ≡ 256 + 29829*2^13 + 3908*2^39 + 128*2^72     [ = (2^36+15632)^2 ]
    2^256 ≡ 2^32 + 977                        (mod p)   [ used by freeze ]

Unlike ed25519 (fold constant 608), the 2^260 fold constant 15632 is
nearly two limbs wide: a carry c out of limb 19 folds back as
``limb0 += 7440 c; limb1 += c; limb2 += 1024 c`` (15632 = 8192 + 7440,
2^36 = 2^10 * 2^26). The 7440 multiplier means limb 0's resting bound is
one fold above the mask, so the "loose" invariant here is NON-UNIFORM:

    limb 0      in [0, 15700]
    limbs 1..19 in [0, 9300]

Product coefficients then satisfy
``2*15700*9300 + 18*9300^2 = 1.85e9 < 2^31 - 1`` (pair (0,k) plus at most
18 inner terms), so schoolbook accumulation stays in int32 — checked
per-op below, as in tmtpu.tpu.fe these are static bounds, not
probabilistic ones.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmtpu.tpu.fe import at_add, const_col

RADIX = 13
NLIMBS = 20
MASK = (1 << RADIX) - 1

P_INT = 2**256 - 2**32 - 977

# 2^260 mod p decomposition (see module doc)
F0, F1, F2 = 7440, 1, 1024
# 2^520 mod p decomposition: positions 0, 1, 3, 5
G0, G1, G3, G5 = 256, 29829, 3908, 128

LOOSE0 = 15700  # resting bound for limb 0
LOOSEK = 9300   # resting bound for limbs 1..19


def limbs_of_int(v: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def int_of_limbs(a) -> int:
    a = np.asarray(a)
    return sum(int(a[i]) << (RADIX * i) for i in range(a.shape[0]))


P_LIMBS = limbs_of_int(P_INT)

assert (2**260) % P_INT == F2 * 2**26 + F1 * 2**13 + F0
assert (2**520) % P_INT == G0 + G1 * 2**13 + G3 * 2**39 + G5 * 2**65


def _carry_pass(x, fold: bool):
    """One vectorized carry pass. ``fold`` wraps the top-limb carry through
    2^260 ≡ 2^36 + 15632; otherwise the top limb keeps its excess."""
    c = x >> RADIX
    x = x - (c << RADIX)
    x = at_add(x, 1, c[:-1])
    top = c[-1:]
    if fold:
        x = at_add(
            x, 0, jnp.concatenate([F0 * top, F1 * top, F2 * top], axis=0)
        )
    else:
        x = at_add(x, x.shape[0] - 1, top << RADIX)
    return x


def carry(x, passes: int, fold: bool = True):
    for _ in range(passes):
        x = _carry_pass(x, fold)
    return x


def add(a, b):
    """a + b. Loose inputs -> pre-carry limb0 <= 31400, others <= 18600.
    Pass 1: carries <= 3, top carry c19 <= 2 -> limb0 <= 8191+14880+3.
    Pass 2: c19 <= 1 -> limb0 <= 8191+7440 = 15631 <= LOOSE0; limb1 <=
    8191+3+1; limb2 <= 8191+1+1024 = 9216 <= LOOSEK."""
    return carry(a + b, 2)


def _ksub() -> np.ndarray:
    """64p as 20 int32 limbs with every limb >= the loose bound of the
    corresponding position (so limbwise ``ksub - b`` is non-negative for
    any loose b), built by borrowing 2^13 units downward from the top.
    Limbs stay <= 41000, so ``a + ksub - b`` coefficients are < 66000 —
    far inside int32."""
    m = np.zeros(NLIMBS + 1, dtype=np.int64)
    v = 64 * P_INT
    for i in range(NLIMBS + 1):
        m[i] = v & MASK
        v >>= RADIX
    k = m[:NLIMBS].copy()
    k[NLIMBS - 1] += m[NLIMBS] << RADIX  # fold limb 20 into limb 19
    need = np.full(NLIMBS, LOOSEK + 100, dtype=np.int64)
    need[0] = LOOSE0 + 100
    for i in range(NLIMBS - 2, -1, -1):
        while k[i] < need[i]:
            k[i] += 1 << RADIX
            k[i + 1] -= 1
    assert (k[:-1] >= need[:-1]).all() and k[NLIMBS - 1] >= need[NLIMBS - 1]
    assert k.max() < 41000
    out = k.astype(np.int32)
    assert int_of_limbs(out) == 64 * P_INT
    return out


KSUB = _ksub()


def sub(a, b):
    """a - b + 64p (limbwise non-negative; see _ksub). Pre-carry limbs
    <= 15700+41000 = 56700 -> pass 1 carries <= 6, c19 <= 6 -> limb0 <=
    8191+6+44640; pass 2: c19 <= 1, limb0 <= 8191+7440+6 <= LOOSE0,
    limb2 <= 8191+1+1024 <= LOOSEK."""
    return carry(a + const_col("K1_KSUB", KSUB) - b, 2)


def neg(a):
    return sub(jnp.zeros_like(a), a)


def _fold_product(c):
    """[40, B] schoolbook coefficients (<= 1.95e9) -> [20, B] loose limbs.

    Stage 0: extend to 42 coefficients (two zero tops) and run two no-fold
    passes: pass 1 carries <= 238k -> limbs <= 8191+238k, c[40] <= 238k,
    c[41] = 0; pass 2 carries <= 30 -> limbs <= 8221, c[40] <= 238k+30,
    c[41] <= 29. Then split c[40] = hi*2^13 + lo so every coefficient
    that the fold multiplies is <= 8221 (lo) or <= 30 (hi, joins c[41]).

    Stage 1 (analytic fold of positions 20..41 into 0..19):
    - pos 20+j, j=0..17:  ``j += 7440c, j+1 += c, j+2 += 1024c``;
    - pos 38's j+2-spill lands on pos 20 -> refold analytically:
      1024*2^260 = 1954*2^13 + 128*2^39  (exact)  -> pos1 += 1954c,
      pos3 += 128c;
    - pos 39: 7440c at pos 19; its +c spill at pos 20 -> pos0 += 7440c,
      pos1 += c, pos2 += 1024c; its 1024c spill at pos 21 ->
      1024*2^273 = 1954*2^26 + 128*2^52 -> pos2 += 1954c, pos4 += 128c;
    - pos 40 (= 2^520, c = lo <= 8221): pos0 += 256c, pos1 += 29829c,
      pos3 += 3908c, pos5 += 128c;
    - pos 41 (= 2^533): same shifted up one limb, c <= 30.
    Worst-case accumulated limb (pos1): 8221 + 69.7e6 + 1954*8221 +
    8221 + 29829*8221 + 256*30 ≈ 0.33e9 < 2^31.

    Stage 2: four folding carry passes: pass 1 carries <= 41k (limb0 <=
    8191+41k+7440*41k ≈ 0.31e9); pass 2 limb0 <= 53k; pass 3 limbs near
    rest; pass 4 -> limb0 <= 15631, limb2 <= 9216 (loose)."""
    B = c.shape[1:]
    z = jnp.zeros((2,) + B, dtype=jnp.int32)
    c = jnp.concatenate([c, z], axis=0)  # [42, B]
    c = carry(c, 2, fold=False)
    lo40 = c[40:41] & MASK
    hi40 = c[40:41] >> RADIX
    low = c[:NLIMBS]
    h = c[NLIMBS:]  # [22, B]; h[20] = c[40] (replaced by lo40/hi40), h[21]

    def acc(x, pos, v):
        return at_add(x, pos, v)

    # standard rule for positions 20..37 (j = 0..17)
    low = acc(low, 0, F0 * h[0:18])
    low = acc(low, 1, F1 * h[0:18])
    low = acc(low, 2, F2 * h[0:18])
    # pos 38: spill at j+2 == 20 refolds to pos1/pos3
    c38 = h[18:19]
    low = acc(low, 18, F0 * c38)
    low = acc(low, 19, F1 * c38)
    low = acc(low, 1, 1954 * c38)
    low = acc(low, 3, 128 * c38)
    # pos 39: 7440 at pos19; +c spill at 20; +1024c spill at 21
    c39 = h[19:20]
    low = acc(low, 19, F0 * c39)
    low = acc(low, 0, F0 * c39)
    low = acc(low, 1, F1 * c39)
    low = acc(low, 2, F2 * c39)
    low = acc(low, 2, 1954 * c39)
    low = acc(low, 4, 128 * c39)
    # pos 40 = 2^520 (lo part)
    low = acc(low, 0, G0 * lo40)
    low = acc(low, 1, G1 * lo40)
    low = acc(low, 3, G3 * lo40)
    low = acc(low, 5, G5 * lo40)
    # pos 41 = 2^533 (hi part of c[40] plus c[41])
    c41 = hi40 + h[21:22]
    low = acc(low, 1, G0 * c41)
    low = acc(low, 2, G1 * c41)
    low = acc(low, 4, G3 * c41)
    low = acc(low, 6, G5 * c41)
    return carry(low, 4)


def mul(a, b):
    """Schoolbook product + reduction. Loose inputs: coefficient bound
    2*15700*9300 + 18*9300^2 = 1.85e9 < 2^31. Output loose."""
    B = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    a = jnp.broadcast_to(a, (NLIMBS,) + B)
    b = jnp.broadcast_to(b, (NLIMBS,) + B)
    c = jnp.zeros((2 * NLIMBS,) + B, dtype=jnp.int32)
    for i in range(NLIMBS):
        c = at_add(c, i, a[i : i + 1] * b)
    return _fold_product(c)


def sq(a):
    """Square via symmetry. Doubled-pair terms: pair (0,k) contributes
    2*15700*9300 = 0.29e9, at most 9 inner pairs 2*9300^2 plus the
    diagonal 9300^2 -> <= 1.94e9 < 2^31."""
    B = a.shape[1:]
    a2 = a + a
    c = jnp.zeros((2 * NLIMBS,) + B, dtype=jnp.int32)
    for i in range(NLIMBS):
        c = at_add(c, 2 * i, a[i : i + 1] * a[i : i + 1])
        if i + 1 < NLIMBS:
            c = at_add(c, 2 * i + 1, a2[i : i + 1] * a[i + 1 :])
    return _fold_product(c)


def mul_small(a, k: int):
    """a * k for a small constant k (k <= 21 here: b3 = 3b = 21 in the
    complete addition formulas). Coefficients <= 21*15700 = 330k; two
    folding passes restore loose bounds (pass 1 carries <= 41, limb0 <=
    8191+41+7440*41 = 0.31e6; pass 2 -> loose)."""
    assert 0 < k < 64
    return carry(a * k, 3)


def freeze(x):
    """Canonical form: value in [0, p), limbs in [0, 2^13). Mirrors
    tmtpu.tpu.fe.freeze: bring the value under 2^256+eps via
    2^256 ≡ 2^32 + 977 (limb 19 holds bits >= 256 at weight 2^9:
    q = x19 >> 9; x0 += 977q; x2 += 64q since 2^32 = 64*2^26), then an
    exact sequential carry and one conditional subtract of p."""
    x = carry(x, 3)
    for _ in range(2):
        q = x[NLIMBS - 1 :] >> (256 - RADIX * (NLIMBS - 1))
        x = at_add(x, NLIMBS - 1, -(q << (256 - RADIX * (NLIMBS - 1))))
        x = at_add(x, 0, 977 * q)
        x = at_add(x, 2, 64 * q)
        x = carry(x, 2)
    for i in range(NLIMBS - 1):
        c = x[i : i + 1] >> RADIX
        x = at_add(at_add(x, i, -(c << RADIX)), i + 1, c)
    t = x - const_col("K1_P", P_LIMBS)
    for i in range(NLIMBS - 1):
        c = t[i : i + 1] >> RADIX
        t = at_add(at_add(t, i, -(c << RADIX)), i + 1, c)
    return jnp.where(t[NLIMBS - 1 :] < 0, x, t)


def sqn(a, n: int):
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return jax.lax.fori_loop(0, n, lambda _, x: sq(x), a)


def sqrt_candidate(a):
    """a^((p+1)/4) — since p ≡ 3 (mod 4) this is a square root of a
    whenever one exists (callers must check sq(result) == a). Uses the
    libsecp256k1 addition chain (253 squarings + 13 multiplies),
    validated against pow(a, (p+1)//4, p) in tests."""
    x2 = mul(sqn(a, 1), a)
    x3 = mul(sqn(x2, 1), a)
    x6 = mul(sqn(x3, 3), x3)
    x9 = mul(sqn(x6, 3), x3)
    x11 = mul(sqn(x9, 2), x2)
    x22 = mul(sqn(x11, 11), x11)
    x44 = mul(sqn(x22, 22), x22)
    x88 = mul(sqn(x44, 44), x44)
    x176 = mul(sqn(x88, 88), x88)
    x220 = mul(sqn(x176, 44), x44)
    x223 = mul(sqn(x220, 3), x3)
    t1 = mul(sqn(x223, 23), x22)
    t1 = mul(sqn(t1, 6), x2)
    return sqn(t1, 2)


def pack_bytes_device(b):
    """DEVICE-side [32, B] big-endian byte strings -> [20, B] int32 limbs.
    secp256k1 wire encodings are big-endian (SEC1), unlike ed25519 —
    reverse, then pack LSB-first like tmtpu.tpu.fe.pack_bytes_device."""
    b = b[::-1].astype(jnp.int32)  # now little-endian [32, B]
    bits = (b[:, None, :] >> jnp.arange(8, dtype=jnp.int32)[None, :, None]) & 1
    bits = bits.reshape((256,) + b.shape[1:])
    pad = jnp.zeros((NLIMBS * RADIX - 256,) + b.shape[1:], dtype=jnp.int32)
    bits = jnp.concatenate([bits, pad], axis=0)
    w = (1 << jnp.arange(RADIX, dtype=jnp.int32))
    limbs = bits.reshape((NLIMBS, RADIX) + b.shape[1:])
    return (limbs * w[None, :, None]).sum(axis=1, dtype=jnp.int32)
