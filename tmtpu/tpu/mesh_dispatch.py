"""Mesh-aware dispatch: shard production verify flushes across chips.

The four sharded primitives in tpu/sharding.py are MULTICHIP-certified
but, until this layer, nothing in the production path called them —
``crypto/batch.py`` and the sidecar coalescer dispatched to one device.
This module owns the process-wide device :class:`~jax.sharding.Mesh`
and the per-curve sharded callables, and routes any flush of at least
``crypto.shard_min_lanes`` lanes across every chip on the host:

- ed25519 rides the fused verify+tally step with the voting-power
  reduction psum'd ON DEVICE, so the host reads back one packed mask
  plus five int32 limb sums regardless of mesh size;
- sr25519 / secp256k1 ride their lane-sharded XLA graphs (verification
  is embarrassingly parallel — no collective at all).

Contract with the callers: every entry point here either returns the
EXACT single-device result or raises. ``crypto.batch.TPUBatchVerifier``
wraps each call in its own try — a mesh failure records against the
``crypto.mesh`` breaker (never ``crypto.tpu``) and the flush falls
through to the single-device path inside the same dispatch window, so
the degradation ladder is mesh → single-device → CPU-serial with exact
masks at every rung.

Padding: the packed bitarray output shards one uint32 word per 32
lanes, so sharded lane counts must be a multiple of ``32 x n_devices``
(the dryrun_multichip quantum); on top of that the padded size reuses
``tv._pad_to_bucket`` so the jit cache sees the same handful of shapes
the single-device path does. Pad lanes replicate lane 0's bytes but
carry ZERO power limbs, so they can never contribute to the tally.

jax is imported lazily — ``configure()`` runs in every node at startup,
including CPU-only ones that must not pay backend init.

Tier-1 testability: under ``XLA_FLAGS=--xla_force_host_platform_device_
count=N`` (tests/conftest.py) the whole path runs on a virtual CPU
mesh; ``TMTPU_MESH_DEVICES`` / ``TMTPU_SHARD_MIN_LANES`` are call-time
env overrides for tests and the bench flood mode.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from tmtpu.libs import breaker as _bk

# mesh failures get their own failure budget: a broken collective on one
# host must degrade to single-device dispatch WITHOUT opening crypto.tpu
# (the single-device path may be perfectly healthy)
MESH_BREAKER_NAME = "crypto.mesh"

ED25519 = "ed25519"
SR25519 = "sr25519"
SECP256K1 = "secp256k1"

_lock = threading.Lock()
# defaults mirror config/config.py CryptoConfig; configure() overwrites
_cfg = {"mesh_devices": 0, "shard_min_lanes": 2048}
_state: Dict = {
    "mesh": None,          # cached jax Mesh
    "mesh_key": None,      # (n, device ids) the cache was built for
    "fns": {},             # (kind, mesh_key) -> jitted sharded callable
    "dispatches": 0,
    "occupancy": {},       # device index -> cumulative sharded lanes
    "last": None,          # last dispatch summary (sidecar Stats)
}


class MeshUnavailable(RuntimeError):
    """No multi-device mesh can be built (one device, or init failed)."""


def breaker() -> "_bk.CircuitBreaker":
    return _bk.get(MESH_BREAKER_NAME)


def configure(crypto_cfg) -> None:
    """Apply CryptoConfig mesh knobs. Safe to call on config reload;
    a device-count change drops the cached mesh and callables."""
    set_overrides(
        mesh_devices=getattr(crypto_cfg, "mesh_devices", 0),
        shard_min_lanes=getattr(crypto_cfg, "shard_min_lanes", 2048))


def set_overrides(mesh_devices: Optional[int] = None,
                  shard_min_lanes: Optional[int] = None) -> None:
    """Direct knob setter (sidecar daemon startup, tools). None leaves
    a knob untouched."""
    with _lock:
        if mesh_devices is not None and \
                mesh_devices != _cfg["mesh_devices"]:
            _cfg["mesh_devices"] = int(mesh_devices)
            _state["mesh"] = None
            _state["mesh_key"] = None
            _state["fns"].clear()
        if shard_min_lanes is not None:
            _cfg["shard_min_lanes"] = int(shard_min_lanes)


def reset() -> None:
    """Drop every cache and counter (tests)."""
    with _lock:
        _state["mesh"] = None
        _state["mesh_key"] = None
        _state["fns"].clear()
        _state["dispatches"] = 0
        _state["occupancy"] = {}
        _state["last"] = None


def mesh_devices() -> int:
    """Configured mesh width; 0 = every visible device. The env var is
    read at call time (same pattern as batch_deadline_s) so tests and
    the bench flood child can steer without a config file."""
    raw = os.environ.get("TMTPU_MESH_DEVICES", "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return _cfg["mesh_devices"]


def shard_min_lanes() -> int:
    raw = os.environ.get("TMTPU_SHARD_MIN_LANES", "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return _cfg["shard_min_lanes"]


def _get_mesh():
    """The cached Mesh, rebuilt when the configured width changes.
    Raises :class:`MeshUnavailable` when fewer than 2 devices answer."""
    import jax

    from tmtpu.tpu import sharding as sh

    want = mesh_devices()
    devs = jax.devices()
    n = len(devs) if want <= 0 else min(want, len(devs))
    if n < 2:
        raise MeshUnavailable(
            f"mesh needs >=2 devices, have {len(devs)} "
            f"(mesh_devices={want})")
    key = (n, tuple(d.id for d in devs[:n]))
    with _lock:
        if _state["mesh"] is not None and _state["mesh_key"] == key:
            return _state["mesh"]
    mesh = sh.make_mesh(n)
    with _lock:
        _state["mesh"] = mesh
        _state["mesh_key"] = key
        _state["fns"].clear()
    from tmtpu.libs import metrics as _m

    _m.crypto_mesh_devices.set(n)
    return mesh


def device_count() -> int:
    """Devices a sharded dispatch would span right now; 0 when the mesh
    cannot be built (never raises — route() gates on it)."""
    try:
        return int(_get_mesh().devices.size)
    except Exception:  # noqa: BLE001 — unavailable == 0
        return 0


def route(curve: str, lanes: int) -> bool:
    """Gate: should this flush ride the mesh? False below the lane
    threshold, on a <2-device host, or while the crypto.mesh breaker is
    open (the open-breaker skip is counted as a fallback so operators
    can see sharded capacity sitting unused)."""
    if lanes < max(1, shard_min_lanes()):
        return False
    if device_count() < 2:
        return False
    if not breaker().allow():
        from tmtpu.libs import metrics as _m

        _m.crypto_mesh_fallback_total.inc(lanes, curve=curve,
                                          reason="breaker-open")
        return False
    return True


def note_failure(curve: str, lanes: int, exc: Exception) -> None:
    """A sharded dispatch raised: record against crypto.mesh (only) and
    count the lanes that will re-ride the single-device path."""
    breaker().record_failure(exc)
    from tmtpu.libs import metrics as _m

    _m.crypto_mesh_fallback_total.inc(lanes, curve=curve,
                                      reason="device-error")


def padded_lanes(b: int, n_devices: int) -> int:
    """Bucket-pad B (jit-cache stability, tv._pad_to_bucket), then round
    up to the mesh quantum 32 x n so every shard gets whole bitarray
    words and equal lane counts."""
    from tmtpu.tpu import verify as tv

    q = 32 * n_devices
    base = max(b, tv._pad_to_bucket(b))
    return ((base + q - 1) // q) * q


def _fn(kind: str, mesh, builder):
    key = (kind, _state["mesh_key"])
    with _lock:
        f = _state["fns"].get(key)
    if f is None:
        f = builder(mesh)
        with _lock:
            _state["fns"][key] = f
    return f


def _note_dispatch(curve: str, lanes: int, padded: int, n: int,
                   psum_s: float, total_s: float) -> None:
    from tmtpu.libs import metrics as _m
    from tmtpu.libs import timeline as _tl

    with _lock:
        _state["dispatches"] += 1
        seq = _state["dispatches"]
        per_shard = padded // n
        for d in range(n):
            _state["occupancy"][d] = \
                _state["occupancy"].get(d, 0) + per_shard
        _state["last"] = {
            "seq": seq, "curve": curve, "lanes": lanes,
            "padded": padded, "devices": n, "shard_lanes": per_shard,
            "seconds": round(total_s, 6),
        }
    _m.crypto_mesh_devices.set(n)
    _m.crypto_mesh_dispatches_total.inc(curve=curve)
    _m.crypto_mesh_shard_lanes.observe(per_shard, curve=curve)
    _m.crypto_mesh_pad_ratio.observe(padded / max(1, lanes), curve=curve)
    _m.crypto_mesh_psum_seconds.observe(psum_s)
    _tl.record_flush(backend="mesh", curve=curve, lanes=lanes,
                     shards=n, shard_lanes=per_shard,
                     seconds=round(total_s, 6))


def dispatch_count() -> int:
    with _lock:
        return _state["dispatches"]


def snapshot() -> Dict:
    """Mesh occupancy for sidecar Stats / health surfaces: per-device
    cumulative sharded lanes plus the last dispatch's shape."""
    with _lock:
        return {
            "devices": (_state["mesh_key"][0]
                        if _state["mesh_key"] else 0),
            "shard_min_lanes": shard_min_lanes(),
            "dispatches": _state["dispatches"],
            "occupancy_lanes": {str(d): v for d, v
                                in sorted(_state["occupancy"].items())},
            "last": dict(_state["last"]) if _state["last"] else None,
            "breaker": breaker().state,
        }


# --- sharded entry points ---------------------------------------------------


def batch_verify_tally_mesh(pks, msgs, sigs, powers
                            ) -> Tuple[np.ndarray, int]:
    """ed25519 fused verify + tally across the host mesh: bit-exact twin
    of sharding.batch_verify_tally with the power reduction psum'd over
    the "sig" axis. Raises on any device/mesh failure (caller degrades
    to single-device)."""
    import jax
    import jax.numpy as jnp

    from tmtpu.libs import trace
    from tmtpu.tpu import sharding as sh
    from tmtpu.tpu import verify as tv

    b = len(sigs)
    if b == 0:
        return np.zeros(0, dtype=bool), 0
    mesh = _get_mesh()
    n = int(mesh.devices.size)
    t0 = time.perf_counter()
    with trace.span("crypto.mesh_verify_tally", curve=ED25519,
                    lanes=b, shards=n) as sp:
        packed, host_ok = tv.prepare_batch_packed(pks, msgs, sigs)
        p = np.asarray(powers, dtype=np.int64).copy()
        p[~host_ok] = 0
        use_kernel = tv.use_pallas_kernel()
        padded = padded_lanes(b, n)
        if use_kernel:
            from tmtpu.tpu import kernel as tk

            q = tk.DEFAULT_TILE * n
            padded = ((padded + q - 1) // q) * q
        sp.set(padded=padded, impl="pallas" if use_kernel else "xla")
        # pad lanes replicate lane 0's BYTES only — their power limbs
        # stay zero, so padding can never leak into the tally
        power_limbs = np.zeros((sh.POWER_LIMBS, padded), dtype=np.int32)
        power_limbs[:, :b] = sh.powers_to_limbs(p)
        packed_h = tv.pad_packed(packed, padded)
        if use_kernel:
            fn = _fn("ed25519-kernel", mesh,
                     sh.sharded_verify_tally_packed_kernel)
            mask, power_sums, _bits = fn(jnp.asarray(packed_h),
                                         jnp.asarray(power_limbs))
        else:
            fn = _fn("ed25519-xla", mesh, sh.sharded_verify_tally_packed)
            mask, power_sums, _bits = fn(jnp.asarray(packed_h),
                                         jnp.asarray(power_limbs),
                                         tv.base_table_f32())
        mask = jax.block_until_ready(mask)
        t_mask = time.perf_counter()
        tallied = sh.limb_sums_to_int(power_sums)   # the psum readback
        psum_s = time.perf_counter() - t_mask
        mask = np.asarray(mask)[:b] & host_ok
    total = time.perf_counter() - t0
    _note_dispatch(ED25519, b, padded, n, psum_s, total)
    breaker().record_success()
    from tmtpu.libs import metrics as _m

    _m.observe_crypto_batch(ED25519, tv.backend_label(), "mesh", b,
                            padded, total)
    return mask, tallied


def batch_verify_mesh(curve: str, pks, msgs, sigs) -> np.ndarray:
    """Mask-only lane-sharded batch verify for any supported curve —
    bit-exact twin of the single-device batch_verify/batch_verify_sr/
    batch_verify_k1. Raises on failure."""
    import jax
    import jax.numpy as jnp

    from tmtpu.libs import trace
    from tmtpu.tpu import sharding as sh
    from tmtpu.tpu import verify as tv

    b = len(sigs)
    if b == 0:
        return np.zeros(0, dtype=bool)
    mesh = _get_mesh()
    n = int(mesh.devices.size)
    t0 = time.perf_counter()
    with trace.span("crypto.mesh_verify", curve=curve, lanes=b,
                    shards=n) as sp:
        if curve == ED25519:
            packed, host_ok = tv.prepare_batch_packed(pks, msgs, sigs)
            table = tv.base_table_f32()

            def build(m):
                return sh.sharded_verify_tally_packed(m)
        elif curve == SR25519:
            from tmtpu.tpu import sr_verify as srv

            packed, host_ok = srv.prepare_sr_batch_packed(pks, msgs, sigs)
            table = tv.base_table_f32()
            build = sh.sharded_verify_sr
        elif curve == SECP256K1:
            from tmtpu.tpu import k1_verify as kv

            packed, host_ok = kv.prepare_k1_batch_packed(pks, msgs, sigs)
            table = kv.base_table_f32()
            build = sh.sharded_verify_k1
        else:
            raise ValueError(f"unsupported mesh curve {curve!r}")
        padded = padded_lanes(b, n)
        sp.set(padded=padded)
        packed_h = tv.pad_packed(packed, padded)
        if curve == ED25519:
            # reuse the fused tally callable with zero powers: one jit
            # cache entry serves both verify and verify_tally flushes
            fn = _fn("ed25519-xla", mesh, build)
            zeros = jnp.zeros((sh.POWER_LIMBS, padded), dtype=jnp.int32)
            mask, _sums, _bits = fn(jnp.asarray(packed_h), zeros, table)
        else:
            fn = _fn(curve, mesh, build)
            mask = fn(jnp.asarray(packed_h), table)
        mask = np.asarray(jax.block_until_ready(mask))[:b] & host_ok
    total = time.perf_counter() - t0
    _note_dispatch(curve, b, padded, n, 0.0, total)
    breaker().record_success()
    from tmtpu.libs import metrics as _m

    _m.observe_crypto_batch(curve, tv.backend_label(), "mesh", b,
                            padded, total)
    return mask
