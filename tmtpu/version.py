"""Version constants (reference: version/version.go:10-23)."""

# Semantic version of this framework. Tracks the reference's 0.34 protocol
# line: block/p2p/abci protocol versions below are wire-compatible constants.
TMCoreSemVer = "0.34.24-tpu.1"

# ABCI protocol semantic version (reference: version/version.go:14).
ABCISemVer = "0.17.0"
ABCIVersion = ABCISemVer

# Block protocol version: changes when the block format changes
# (reference: version/version.go:20).
BlockProtocol = 11

# P2P protocol version: changes when the p2p wire format changes
# (reference: version/version.go:23).
P2PProtocol = 8
