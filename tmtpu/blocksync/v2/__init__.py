"""Fast sync v2: event-driven scheduler/processor (reference:
blockchain/v2/). Selected via ``block_sync.version = "v2"``.

The reference splits v2 into three actors (scheduler, processor,
demuxing reactor) joined by routines (blockchain/v2/routine.go). Here
the scheduler and processor are PURE deterministic state machines —
events in, events out, no threads, no sockets, no clocks of their own —
and the reactor serializes them on one pump thread (a single-queue
actor loop; same serialization the reference gets from its demuxer,
with far less machinery). Purity is what makes the v2 design testable:
tests drive event sequences and assert exact outputs.

Batch-first twist: the processor releases blocks in CONTIGUOUS RUNS and
the reactor verifies a whole run's commits in ONE batched device
dispatch (types/commit_verify.verify_commits_light_batch), like the v0
reactor — the reference verifies one block at a time
(blockchain/v2/processor.go:120).
"""

from tmtpu.blocksync.v2.reactor import BlocksyncReactorV2

__all__ = ["BlocksyncReactorV2"]
