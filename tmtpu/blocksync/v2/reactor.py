"""v2 fast-sync reactor: the pump joining scheduler + processor to the
switch (reference: blockchain/v2/reactor.go + routine.go + io.go).

Same wire protocol and channel as v0 (the reference v2 speaks the
identical blockchain channel messages — blockchain/v2/io.go), so a v2
node syncs from v0 peers and serves them. The reference demuxes three
actor routines over buffered queues; here one pump thread serializes
scheduler and processor transitions (they are pure state machines, see
tmtpu/blocksync/v2/__init__.py) and does the block I/O + the batched
run verification.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from tmtpu.blocksync.common import (
    BLOCKCHAIN_CHANNEL, BlockServingMixin, verify_block_run,
)
from tmtpu.blocksync.msgs import BlockRequestPB, BlocksyncMessagePB
from tmtpu.blocksync.v2 import processor as proc_mod
from tmtpu.blocksync.v2 import scheduler as sched_mod
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor
from tmtpu.types.block import Block

STATUS_UPDATE_INTERVAL_S = 10.0
TICK_S = 0.02
MAX_BATCH_BLOCKS = 32


class BlocksyncReactorV2(BlockServingMixin, Reactor):
    """Drop-in alternative to BlocksyncReactor, selected by
    ``block_sync.version = "v2"`` (node.go NewNode picks the blockchain
    reactor by config the same way)."""

    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None,
                 verify_backend: Optional[str] = None):
        super().__init__("BLOCKSYNC")
        if state.last_block_height != block_store.height():
            raise ValueError(
                f"state ({state.last_block_height}) and store "
                f"({block_store.height()}) height mismatch")
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.verify_backend = verify_backend
        start = block_store.height() + 1
        if start == 1:
            start = state.initial_height
        self.sched = sched_mod.Scheduler(start)
        self.proc = proc_mod.Processor(start, max_run=MAX_BATCH_BLOCKS)
        self.blocks_synced = 0
        self._events: "queue.Queue" = queue.Queue(maxsize=10_000)
        self._pump_alive = False
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0
        # caught-up grace: like v0's pool, don't hand over before we've
        # heard from peers at all
        self._grace_s = 3.0

    # -- reactor interface --------------------------------------------------

    def get_channels(self):
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_start(self) -> None:
        if self.fast_sync:
            self._start_pump(state_synced=False)

    def _start_pump(self, state_synced: bool) -> None:
        self._started_at = time.monotonic()
        # alive BEFORE start(): on a single-core box the switch can
        # deliver add_peer/status for already-connected peers before the
        # pump thread is ever scheduled — those events must not drop
        self._pump_alive = True
        self._thread = threading.Thread(
            target=self._pump, args=(state_synced,), daemon=True,
            name="blocksync-v2")
        self._thread.start()

    def on_stop(self) -> None:
        self._stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _enqueue(self, ev) -> None:
        """Events are only meaningful while the pump is running; after
        handover (or if the queue is somehow full) they are DROPPED —
        a blocking put here would wedge the p2p receive thread."""
        if not self._pump_alive:
            return
        try:
            self._events.put_nowait(ev)
        except queue.Full:
            pass

    def add_peer(self, peer: Peer) -> None:
        peer.send(BLOCKCHAIN_CHANNEL, self._status_msg())
        self._enqueue(("add_peer", peer.node_id))

    def remove_peer(self, peer: Peer, reason) -> None:
        self._enqueue(("remove_peer", peer.node_id))

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = BlocksyncMessagePB.decode(msg_bytes)
        if msg.block_request is not None:
            self._respond_to_peer(msg.block_request.height, peer)
        elif msg.status_request is not None:
            peer.try_send(BLOCKCHAIN_CHANNEL, self._status_msg())
        elif msg.block_response is not None:
            block = Block.from_proto(msg.block_response.block)
            self._enqueue(
                ("block", peer.node_id, block, len(msg_bytes)))
        elif msg.status_response is not None:
            self._enqueue(("status", peer.node_id,
                           msg.status_response.base,
                           msg.status_response.height))
        elif msg.no_block_response is not None:
            self._enqueue(
                ("no_block", peer.node_id, msg.no_block_response.height))

    # serving + handover come from BlockServingMixin

    # -- the pump (reactor.go demux loop) -----------------------------------

    def _pump(self, state_synced: bool) -> None:
        try:
            self._pump_loop(state_synced)
        except Exception:  # noqa: BLE001 — a dead pump must be loud
            import traceback

            traceback.print_exc()
            raise
        finally:
            self._pump_alive = False

    def _pump_loop(self, state_synced: bool) -> None:
        last_status = 0.0
        while not self._stopped.is_set():
            now = time.monotonic()
            if now - last_status > STATUS_UPDATE_INTERVAL_S:
                last_status = now
                self.broadcast_status_request()
            # drain queued events into scheduler/processor transitions
            drained = False
            try:
                while True:
                    ev = self._events.get_nowait()
                    drained = True
                    self._dispatch(ev, time.monotonic())
            except queue.Empty:
                pass
            self._emit(self.sched.tick(time.monotonic()))
            if self._process_runs():
                drained = True
            if self.sched.finished or self._caught_up(now):
                self._switch_to_consensus(state_synced)
                return
            if not drained:
                self._stopped.wait(TICK_S)

    def _dispatch(self, ev, now: float) -> None:
        kind = ev[0]
        if kind == "add_peer":
            self.sched.add_peer(ev[1], now)
        elif kind == "remove_peer":
            # scheduler reschedules the peer's in-flight heights; the
            # processor drops its queued blocks (they'll be re-fetched)
            self._emit(self.sched.remove_peer(ev[1]))
            self.proc.purge_peer(ev[1])
        elif kind == "status":
            self._emit(self.sched.status(ev[1], ev[2], ev[3], now))
        elif kind == "block":
            _, peer_id, block, size = ev
            h = block.header.height
            out = self.sched.block_received(peer_id, h, size, now)
            if not out:  # solicited: queue for processing
                self.proc.enqueue(h, block, peer_id)
            self._emit(out)
        elif kind == "no_block":
            self._emit(self.sched.no_block(ev[1], ev[2]))

    def _emit(self, events) -> None:
        for e in events:
            if isinstance(e, sched_mod.BlockRequest):
                peer = (self.switch.peers.get(e.peer_id)
                        if self.switch else None)
                if peer is not None:
                    peer.try_send(
                        BLOCKCHAIN_CHANNEL,
                        BlocksyncMessagePB(block_request=BlockRequestPB(
                            height=e.height)).encode())
            elif isinstance(e, sched_mod.PeerError):
                self._stop_peer(e.peer_id, e.reason)
            # Finished is read via sched.finished in the pump loop

    # -- batched run verification (the v0 fused path, v2-scheduled) ---------

    def _process_runs(self) -> bool:
        run = self.proc.next_run()
        if len(run) < 2:
            return False
        blocks = [q.block for q in run[:-1]]
        successors = [q.block for q in run[1:]]
        vals_now = self.state.validators
        if any(b.header.validators_hash != vals_now.hash()
               for b in blocks):
            blocks, successors = blocks[:1], successors[:1]  # valset edge
        results, parts_bids = verify_block_run(
            self.state, blocks, successors, self.verify_backend)
        applied = 0
        for blk, nxt, err, (parts, bid) in zip(blocks, successors, results,
                                               parts_bids):
            if err is not None:
                self._fail_height(blk.header.height, err)
                break
            try:
                self.block_exec.validate_block(self.state, blk)
            except Exception as e:  # noqa: BLE001
                self._fail_height(blk.header.height, e)
                break
            self.store.save_block(blk, parts, nxt.last_commit)
            self.state, _ = self.block_exec.apply_block(
                self.state, bid, blk)
            self.blocks_synced += 1
            applied += 1
        if applied:
            self.proc.applied(applied)
            for h in range(self.sched.height, self.sched.height + applied):
                self._emit(self.sched.processed(h))
        return applied > 0

    def _fail_height(self, height: int, err) -> None:
        self.proc.failed(height)
        self._emit(self.sched.verification_failure(height))

    def _caught_up(self, now: float) -> bool:
        """v0 pool.is_caught_up analogue (pool.go:170-186): past the
        grace period, at least one ready peer heard from, and past the
        best reported peer height (max_h == 0 means peers are at
        genesis — nothing to sync)."""
        if now - self._started_at < self._grace_s:
            return False
        ready = any(p.state == "ready" for p in self.sched.peers.values())
        # within one block of the best peer height, like v0: the tip
        # block cannot fast-sync (its verifying successor commit doesn't
        # exist yet on a LIVE chain) — consensus gossip fetches it after
        # the handover (pool.go:181 uses the same >= max-1 shape)
        return ready and self.sched.height >= self.sched.max_peer_height()

    # -- statesync handoff --------------------------------------------------

    def switch_to_fast_sync(self, state) -> None:
        self.state = state
        self.fast_sync = True
        h = state.last_block_height + 1
        self.sched = sched_mod.Scheduler(h)
        self.proc = proc_mod.Processor(h, max_run=MAX_BATCH_BLOCKS)
        self._start_pump(state_synced=True)
