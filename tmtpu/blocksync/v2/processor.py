"""v2 processor: a pure state machine ordering received blocks into
contiguous runs for batch verification (reference:
blockchain/v2/processor.go).

The reference processor verifies one block per pcProcessBlock event.
Batch-first redesign: ``next_run()`` exposes the longest contiguous run
of queued blocks starting at the processing height; the reactor
verifies the WHOLE run's commits in one device dispatch and reports
either ``applied(n)`` or ``failed(height)``. The queue itself stays
pure — no verification happens here, so tests can drive it without
crypto."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class QueuedBlock:
    height: int
    block: object
    peer_id: str


class Processor:
    def __init__(self, initial_height: int, max_run: int = 32):
        self.height = initial_height    # next height to apply
        self.max_run = max_run
        self.queue: Dict[int, QueuedBlock] = {}

    def enqueue(self, height: int, block, peer_id: str) -> None:
        """Keep the first copy (processor.go ignores duplicates)."""
        if height >= self.height and height not in self.queue:
            self.queue[height] = QueuedBlock(height, block, peer_id)

    def next_run(self) -> List[QueuedBlock]:
        """Longest contiguous [height, height+k] run, capped at
        max_run + 1 (the +1 block supplies the last verifying commit —
        block h is verified by h+1's LastCommit, processor.go:120)."""
        run: List[QueuedBlock] = []
        h = self.height
        while h in self.queue and len(run) < self.max_run + 1:
            run.append(self.queue[h])
            h += 1
        return run

    def applied(self, n: int) -> None:
        """First ``n`` blocks of the run were verified + applied."""
        for h in range(self.height, self.height + n):
            self.queue.pop(h, None)
        self.height += n

    def failed(self, height: int) -> Tuple[Optional[str], Optional[str]]:
        """Verification failed at ``height``: drop block h and h+1 (both
        suppliers suspect, processor.go handleVerificationFailure) and
        return their peer ids for scheduler errors."""
        a = self.queue.pop(height, None)
        b = self.queue.pop(height + 1, None)
        return (a.peer_id if a else None, b.peer_id if b else None)

    def purge_peer(self, peer_id: str) -> List[int]:
        """Peer removed: drop its queued blocks; the scheduler will
        re-request those heights. Returns the dropped heights."""
        drop = [h for h, q in self.queue.items() if q.peer_id == peer_id]
        for h in drop:
            del self.queue[h]
        return drop
