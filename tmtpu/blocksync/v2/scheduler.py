"""v2 scheduler: a pure state machine deciding which peer to ask for
which height (reference: blockchain/v2/scheduler.go).

Inputs are plain method calls (one per reference event); outputs are
lists of Event dataclasses. No I/O, no threads, no wall clock — the
caller passes ``now`` into time-dependent transitions, so every
behavior (touch timeouts, slow-peer pruning, termination) is unit
testable deterministically.

Block lifecycle per height (scheduler.go blockState):
    new -> pending (request sent) -> received -> processed
A pruned/errored peer sends its pending/received heights back to new.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# scheduler.go defaults
MAX_PENDING_PER_PEER = 20
PEER_TIMEOUT_S = 15.0       # no useful message for this long -> prune
MIN_RECV_RATE = 0           # bytes/s; 0 disables rate pruning (as v0 does
#                             on small nets; reference uses 7680 in prod)
TARGET_PENDING = 64         # total in-flight request budget


# -- output events ----------------------------------------------------------


@dataclass
class BlockRequest:
    peer_id: str
    height: int


@dataclass
class PeerError:
    peer_id: str
    reason: str


@dataclass
class Finished:
    reason: str


@dataclass
class _Peer:
    peer_id: str
    base: int = 0
    height: int = 0
    state: str = "new"          # new | ready | removed
    last_touch: float = 0.0
    pending: Dict[int, float] = field(default_factory=dict)  # height->sent
    received_bytes: int = 0
    first_request: float = 0.0


class Scheduler:
    def __init__(self, initial_height: int, *,
                 max_pending_per_peer: int = MAX_PENDING_PER_PEER,
                 peer_timeout_s: float = PEER_TIMEOUT_S,
                 target_pending: int = TARGET_PENDING):
        self.height = initial_height      # next height to schedule/process
        self.max_pending_per_peer = max_pending_per_peer
        self.peer_timeout_s = peer_timeout_s
        self.target_pending = target_pending
        self.peers: Dict[str, _Peer] = {}
        # height -> state ("pending"|"received"); absent = new/processed
        self.pending: Dict[int, str] = {}
        self.pending_peer: Dict[int, str] = {}
        self.received_peer: Dict[int, str] = {}
        self.finished = False

    # -- peer events (scheduler.go handleAddNewPeer etc.) -------------------

    def add_peer(self, peer_id: str, now: float) -> None:
        if peer_id not in self.peers:
            self.peers[peer_id] = _Peer(peer_id, last_touch=now)

    def remove_peer(self, peer_id: str) -> List[object]:
        """Peer gone: its in-flight heights go back to new so another
        peer picks them up (scheduler.go removePeer)."""
        p = self.peers.pop(peer_id, None)
        if p is None:
            return []
        for h in list(self.pending_peer):
            if self.pending_peer[h] == peer_id:
                del self.pending_peer[h]
                self.pending.pop(h, None)
        for h in list(self.received_peer):
            if self.received_peer[h] == peer_id:
                del self.received_peer[h]
                self.pending.pop(h, None)
        return self._maybe_finished()

    def status(self, peer_id: str, base: int, height: int,
               now: float) -> List[object]:
        """StatusResponse (scheduler.go handleStatusResponse): a peer
        reporting a LOWER height than before is suspect."""
        p = self.peers.get(peer_id)
        if p is None:
            self.add_peer(peer_id, now)
            p = self.peers[peer_id]
        if height < p.height:
            self.remove_peer(peer_id)
            return [PeerError(peer_id, "peer height regressed")]
        p.base, p.height = base, height
        p.state = "ready"
        p.last_touch = now
        return []

    # -- block events -------------------------------------------------------

    def block_received(self, peer_id: str, height: int, size: int,
                       now: float) -> List[object]:
        p = self.peers.get(peer_id)
        if p is None or self.pending_peer.get(height) != peer_id:
            # unsolicited block (scheduler.go: error the peer)
            self.remove_peer(peer_id)
            return [PeerError(peer_id, f"unsolicited block {height}")]
        p.last_touch = now
        p.received_bytes += size
        p.pending.pop(height, None)
        del self.pending_peer[height]
        self.pending[height] = "received"
        self.received_peer[height] = peer_id
        return []

    def no_block(self, peer_id: str, height: int) -> List[object]:
        """Peer advertised the height but won't serve it
        (scheduler.go handleNoBlockResponse: remove the peer)."""
        if self.pending_peer.get(height) == peer_id:
            out = self.remove_peer(peer_id)
            return [PeerError(peer_id, f"no block at {height}")] + out
        return []

    def processed(self, height: int) -> List[object]:
        """Processor applied ``height`` (scheduler.go handleBlockProcessed)."""
        self.pending.pop(height, None)
        self.received_peer.pop(height, None)
        if height >= self.height:
            self.height = height + 1
        return self._maybe_finished()

    def verification_failure(self, height: int) -> List[object]:
        """Block h failed verification against h+1 (scheduler.go
        handleBlockProcessError): both suppliers are suspect; their
        heights reschedule."""
        out: List[object] = []
        for h in (height, height + 1):
            pid = self.received_peer.get(h) or self.pending_peer.get(h)
            if pid is not None and pid in self.peers:
                out.append(PeerError(pid, f"bad block run at {height}"))
                out += self.remove_peer(pid)
        return out

    # -- tick: scheduling + pruning (rTrySchedule / rTryPrunePeer) ----------

    def tick(self, now: float) -> List[object]:
        out: List[object] = []
        out += self._prune(now)
        out += self._schedule(now)
        out += self._maybe_finished()
        return out

    def _prune(self, now: float) -> List[object]:
        out: List[object] = []
        for pid, p in list(self.peers.items()):
            if p.state != "ready":
                continue
            if now - p.last_touch > self.peer_timeout_s:
                out.append(PeerError(pid, "peer timeout"))
                out += self.remove_peer(pid)
        return out

    def _schedule(self, now: float) -> List[object]:
        out: List[object] = []
        budget = self.target_pending - len(self.pending)
        h = self.height
        max_h = self.max_peer_height()
        while budget > 0 and h <= max_h:
            if not any(p.state == "ready"
                       and len(p.pending) < self.max_pending_per_peer
                       for p in self.peers.values()):
                break  # every ready peer at its cap: scanning further
                #        heights is pure waste (500k-height chains would
                #        otherwise burn the pump thread every tick)
            if h not in self.pending:
                p = self._pick_peer(h)
                if p is None:
                    # no peer can serve h right now (base above it) —
                    # skip it this tick but keep scanning so other
                    # peers prefetch later heights
                    h += 1
                    continue
                p.pending[h] = now
                if not p.first_request:
                    p.first_request = now
                self.pending[h] = "pending"
                self.pending_peer[h] = p.peer_id
                out.append(BlockRequest(p.peer_id, h))
                budget -= 1
            h += 1
        return out

    def _pick_peer(self, height: int) -> Optional[_Peer]:
        best = None
        for p in self.peers.values():
            if (p.state == "ready" and p.base <= height <= p.height
                    and len(p.pending) < self.max_pending_per_peer):
                if best is None or len(p.pending) < len(best.pending):
                    best = p
        return best

    def max_peer_height(self) -> int:
        return max((p.height for p in self.peers.values()
                    if p.state == "ready"), default=0)

    def _maybe_finished(self) -> List[object]:
        """scheduler.go allBlocksProcessed: every height up to the best
        peer height is processed and nothing is in flight."""
        if self.finished:
            return []
        ready = [p for p in self.peers.values() if p.state == "ready"]
        if ready and not self.pending and \
                self.height > self.max_peer_height():
            self.finished = True
            return [Finished("caught up to max peer height")]
        return []
