"""Block sync wire messages (reference: proto/tendermint/blockchain/
types.proto) — field numbers match the reference."""

from __future__ import annotations

from tmtpu.libs.protoio import ProtoMessage
from tmtpu.types import pb


class BlockRequestPB(ProtoMessage):
    FIELDS = [(1, "height", "int64")]


class NoBlockResponsePB(ProtoMessage):
    FIELDS = [(1, "height", "int64")]


class BlockResponsePB(ProtoMessage):
    FIELDS = [(1, "block", ("msg!", pb.Block))]


class StatusRequestPB(ProtoMessage):
    FIELDS = []


class StatusResponsePB(ProtoMessage):
    FIELDS = [
        (1, "height", "int64"),
        (2, "base", "int64"),
    ]


class BlocksyncMessagePB(ProtoMessage):
    """Message oneof wrapper."""

    FIELDS = [
        (1, "block_request", ("msg", BlockRequestPB)),
        (2, "no_block_response", ("msg", NoBlockResponsePB)),
        (3, "block_response", ("msg", BlockResponsePB)),
        (4, "status_request", ("msg", StatusRequestPB)),
        (5, "status_response", ("msg", StatusResponsePB)),
    ]
