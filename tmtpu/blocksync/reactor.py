"""Fast-sync (block sync) reactor (reference: blockchain/v0/reactor.go).

Serves blocks to catching-up peers and, when started in fast-sync mode,
drives the BlockPool: request blocks from taller peers, verify each block
with its successor's LastCommit, apply through the BlockExecutor, and hand
over to the consensus reactor once caught up (SwitchToConsensus,
reactor.go:303-330).

TPU-first deviation from the reference: instead of one VerifyCommitLight
per block (reactor.go:366), a contiguous run of fetched blocks is verified
with ONE batched dispatch over all their commits' signatures
(types.commit_verify.verify_commits_light_batch) — fast-sync replay is the
BASELINE "per-block Commit batch verification" config, batched further
across blocks.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from tmtpu.blocksync.common import (
    BLOCKCHAIN_CHANNEL, BlockServingMixin, verify_block_run,
)
from tmtpu.blocksync.msgs import BlockRequestPB, BlocksyncMessagePB
from tmtpu.blocksync.pool import BlockPool
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor
from tmtpu.types import commit_verify
from tmtpu.types.block import Block, BlockID
from tmtpu.types.part_set import PartSet


TRY_SYNC_INTERVAL_S = 0.01          # trySyncIntervalMS
STATUS_UPDATE_INTERVAL_S = 10.0     # statusUpdateIntervalSeconds
SWITCH_TO_CONSENSUS_INTERVAL_S = 1.0
MAX_BATCH_BLOCKS = 32               # commits fused per device dispatch


class BlocksyncReactor(BlockServingMixin, Reactor):
    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None, verify_backend: Optional[str] = None):
        super().__init__("BLOCKSYNC")
        if state.last_block_height != block_store.height():
            raise ValueError(
                f"state ({state.last_block_height}) and store "
                f"({block_store.height()}) height mismatch")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.verify_backend = verify_backend
        start = block_store.height() + 1
        if start == 1:
            start = state.initial_height
        self.pool = BlockPool(start, on_peer_error=self._stop_peer)
        self.blocks_synced = 0
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- reactor interface --------------------------------------------------

    def get_channels(self):
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_start(self) -> None:
        if self.fast_sync:
            self._thread = threading.Thread(
                target=self._pool_routine, daemon=True, name="blocksync-pool")
            self._thread.start()

    def on_stop(self) -> None:
        self._stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def add_peer(self, peer: Peer) -> None:
        # reactor.go AddPeer: send our status so the peer can request
        peer.send(BLOCKCHAIN_CHANNEL, self._status_msg())

    def remove_peer(self, peer: Peer, reason) -> None:
        self.pool.remove_peer(peer.node_id)

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = BlocksyncMessagePB.decode(msg_bytes)
        if msg.block_request is not None:
            self._respond_to_peer(msg.block_request.height, peer)
        elif msg.block_response is not None:
            block = Block.from_proto(msg.block_response.block)
            self.pool.add_block(peer.node_id, block, len(msg_bytes))
        elif msg.status_request is not None:
            peer.try_send(BLOCKCHAIN_CHANNEL, self._status_msg())
        elif msg.status_response is not None:
            self.pool.set_peer_range(peer.node_id,
                                     msg.status_response.base,
                                     msg.status_response.height)
        elif msg.no_block_response is not None:
            pass  # reactor.go just logs it

    # serving + handover (status/respond/stop-peer/switch-to-consensus)
    # come from BlockServingMixin — shared with BlocksyncReactorV2

    # -- the sync loop (reactor.go poolRoutine) -----------------------------

    def _pool_routine(self, state_synced: bool = False) -> None:
        last_status = 0.0
        last_switch_check = 0.0
        while not self._stopped.is_set():
            now = time.monotonic()
            if now - last_status > STATUS_UPDATE_INTERVAL_S:
                last_status = now
                self.broadcast_status_request()
            for peer_id, height in self.pool.make_requests():
                peer = self.switch.peers.get(peer_id) if self.switch else None
                if peer is not None:
                    peer.try_send(
                        BLOCKCHAIN_CHANNEL,
                        BlocksyncMessagePB(
                            block_request=BlockRequestPB(height=height)
                        ).encode())
            if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL_S:
                last_switch_check = now
                if self.pool.is_caught_up():
                    self._switch_to_consensus(state_synced)
                    return
            if not self._try_sync_batch():
                self._stopped.wait(TRY_SYNC_INTERVAL_S)

    def _try_sync_batch(self) -> bool:
        """Verify + apply a contiguous run of fetched blocks. The commits of
        the whole run are batch-verified in one dispatch; the verified
        prefix is applied, the first failure re-requested. Returns True if
        any block was applied."""
        run = self.pool.peek_run(MAX_BATCH_BLOCKS + 1)
        if len(run) < 2:
            return False
        # block h is verified by block h+1's LastCommit (reactor.go:366);
        # the fused path needs one valset for the whole run — valset changes
        # mid-run (rare) fall back to block-at-a-time
        blocks, successors = run[:-1], run[1:]
        vals_now = self.state.validators
        if any(b.header.validators_hash != vals_now.hash() for b in blocks):
            return self._try_sync_one()
        results, parts_bids = verify_block_run(
            self.state, blocks, successors, self.verify_backend)
        applied = False
        for blk, nxt, err, (parts, bid) in zip(blocks, successors, results,
                                               parts_bids):
            if err is not None:
                self._handle_bad_block(blk.header.height, err)
                return applied
            if not self._apply_one(blk, nxt, parts, bid):
                return applied
            applied = True
        return applied

    def _try_sync_one(self) -> bool:
        first, second = self.pool.peek_two_blocks()
        if first is None or second is None:
            return False
        parts = PartSet.from_data(first.encode())
        bid = BlockID(first.hash(), parts.total, parts.hash)
        try:
            self.state.validators.verify_commit_light(
                self.state.chain_id, bid, first.header.height,
                second.last_commit, backend=self.verify_backend)
        except commit_verify.VerificationError as e:
            self._handle_bad_block(first.header.height, e)
            return False
        return self._apply_one(first, second, parts, bid)

    def _apply_one(self, block: Block, successor: Block,
                   parts=None, bid=None) -> bool:
        if parts is None:
            parts = PartSet.from_data(block.encode())
            bid = BlockID(block.hash(), parts.total, parts.hash)
        try:
            self.block_exec.validate_block(self.state, block)
        except Exception as e:  # noqa: BLE001
            self._handle_bad_block(block.header.height, e)
            return False
        self.pool.pop_request()
        self.store.save_block(block, parts, successor.last_commit)
        self.state, _ = self.block_exec.apply_block(self.state, bid, block)
        self.blocks_synced += 1
        return True

    def _handle_bad_block(self, height: int, err) -> None:
        # punish the server of the bad block and its successor's server
        # (either could have lied — reactor.go:377-390)
        for h in (height, height + 1):
            bad = self.pool.redo_request(h)
            if bad is not None:
                self._stop_peer(bad, f"blocksync validation error: {err}")

    # -- statesync handoff (reactor.go SwitchToFastSync) --------------------

    def switch_to_fast_sync(self, state) -> None:
        self.state = state
        self.initial_state = state
        self.fast_sync = True
        self.pool.height = state.last_block_height + 1
        # restart the caught-up grace period: both the wall clock AND the
        # start height, or is_caught_up()'s height > _start_height check
        # passes instantly with a stale _max_peer_height and we'd hand over
        # to consensus without fetching the tail
        self.pool._started_at = time.monotonic()
        self.pool._start_height = self.pool.height
        self._thread = threading.Thread(
            target=self._pool_routine, args=(True,), daemon=True,
            name="blocksync-pool")
        self._thread.start()
