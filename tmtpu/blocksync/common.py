"""Shared halves of the v0 and v2 blocksync reactors: block serving,
peer discipline, consensus handover, and the batched run verification
(reference: blockchain/v0/reactor.go + blockchain/v2/io.go — both
versions speak the identical blockchain channel protocol)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from tmtpu.blocksync.msgs import (
    BlockResponsePB, BlocksyncMessagePB, NoBlockResponsePB,
    StatusRequestPB, StatusResponsePB,
)
from tmtpu.types import commit_verify
from tmtpu.types.block import BlockID
from tmtpu.types.part_set import PartSet

BLOCKCHAIN_CHANNEL = 0x40


class BlockServingMixin:
    """Serving + handover shared by BlocksyncReactor (v0) and
    BlocksyncReactorV2. Requires: ``self.store``, ``self.switch``,
    ``self.state``, ``self.blocks_synced``, ``self.consensus_reactor``."""

    def _status_msg(self) -> bytes:
        return BlocksyncMessagePB(status_response=StatusResponsePB(
            height=self.store.height(), base=self.store.base())).encode()

    def _respond_to_peer(self, height: int, peer) -> None:
        block = self.store.load_block(height)
        if block is not None:
            m = BlocksyncMessagePB(
                block_response=BlockResponsePB(block=block.to_proto()))
        else:
            m = BlocksyncMessagePB(
                no_block_response=NoBlockResponsePB(height=height))
        peer.try_send(BLOCKCHAIN_CHANNEL, m.encode())

    def broadcast_status_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                BLOCKCHAIN_CHANNEL,
                BlocksyncMessagePB(status_request=StatusRequestPB()).encode())

    def _stop_peer(self, peer_id: str, reason: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            self.switch.stop_peer_for_error(peer, reason)

    def _switch_to_consensus(self, state_synced: bool) -> None:
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(
                self.state, skip_wal=self.blocks_synced > 0 or state_synced)


def verify_block_run(state, blocks: List, successors: List,
                     verify_backend: Optional[str]
                     ) -> Tuple[List, List[Tuple[PartSet, BlockID]]]:
    """Verify block h against block h+1's LastCommit for a contiguous
    run, the WHOLE run's commit signatures in one batched dispatch
    (v0 reactor.go:366 does one VerifyCommitLight per block).

    Returns (per-block error list, per-block (PartSet, BlockID)) — the
    parts/bid pairs are returned so callers reuse them for save/apply
    instead of re-encoding 22 MB blocks."""
    entries = []
    parts_bids: List[Tuple[PartSet, BlockID]] = []
    vals = state.validators
    chain_id = state.chain_id
    for blk, nxt in zip(blocks, successors):
        parts = PartSet.from_data(blk.encode())
        bid = BlockID(blk.hash(), parts.total, parts.hash)
        parts_bids.append((parts, bid))
        entries.append((vals, chain_id, bid, blk.header.height,
                        nxt.last_commit))
    results = commit_verify.verify_commits_light_batch(
        entries, backend=verify_backend)
    return results, parts_bids
