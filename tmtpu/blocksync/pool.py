"""BlockPool — fast-sync block request scheduling (reference:
blockchain/v0/pool.go).

Idiomatic redesign: the reference spawns one goroutine per in-flight height
(up to 600 bpRequesters, pool.go:33). Python threads at that count are all
overhead, so the pool here is a passive, lock-protected scheduler driven by
the reactor's single pool-routine thread: ``make_requests()`` assigns
pending heights to peers with spare capacity and returns the (peer, height)
pairs to send, ``add_block`` matches responses to assignments, and timed-out
assignments are recycled on the next scheduling pass. Semantics kept from
the reference: only the assigned peer may answer a height (pool.go
AddBlock), per-peer pending caps, ban-on-timeout, ``IsCaughtUp`` =
max-peer-height reached (pool.go:170-186).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tmtpu.types.block import Block

# pool.go:30-47
REQUEST_WINDOW = 400           # max heights in flight (maxTotalRequesters)
MAX_PENDING_PER_PEER = 20      # maxPendingRequestsPerPeer
REQUEST_RETRY_S = 30.0         # requestRetrySeconds
PEER_TIMEOUT_S = 15.0          # peerTimeout


class _PoolPeer:
    __slots__ = ("peer_id", "base", "height", "n_pending", "last_recv")

    def __init__(self, peer_id: str, base: int, height: int):
        self.peer_id = peer_id
        self.base = base
        self.height = height
        self.n_pending = 0
        self.last_recv = time.monotonic()


class _Request:
    __slots__ = ("height", "peer_id", "block", "sent_at", "tries")

    def __init__(self, height: int):
        self.height = height
        self.peer_id: Optional[str] = None
        self.block: Optional[Block] = None
        self.sent_at = 0.0
        self.tries = 0


class BlockPool:
    def __init__(self, start_height: int,
                 on_peer_error: Optional[Callable[[str, str], None]] = None):
        self._lock = threading.RLock()
        self.height = start_height          # next height to apply
        self._start_height = start_height
        self._peers: Dict[str, _PoolPeer] = {}
        self._requests: Dict[int, _Request] = {}
        self._max_peer_height = 0
        self._on_peer_error = on_peer_error
        self._started_at = time.monotonic()

    # -- peer bookkeeping (pool.go SetPeerRange / RemovePeer) ---------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        with self._lock:
            p = self._peers.get(peer_id)
            if p is None:
                p = _PoolPeer(peer_id, base, height)
                self._peers[peer_id] = p
            else:
                p.base = base
                p.height = height
            p.last_recv = time.monotonic()
            if height > self._max_peer_height:
                self._max_peer_height = height

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self._remove_peer_locked(peer_id)

    def _remove_peer_locked(self, peer_id: str) -> None:
        p = self._peers.pop(peer_id, None)
        if p is None:
            return
        for req in self._requests.values():
            if req.peer_id == peer_id and req.block is None:
                req.peer_id = None  # recycle on next scheduling pass
        if p.height == self._max_peer_height:
            self._max_peer_height = max(
                (q.height for q in self._peers.values()), default=0)

    # -- scheduling ---------------------------------------------------------

    def make_requests(self) -> List[Tuple[str, int]]:
        """One scheduling pass: create requesters up to the window, assign
        unassigned/timed-out heights to peers with capacity. Returns
        (peer_id, height) pairs the reactor should send BlockRequests for.
        Peers that time out (no block for PEER_TIMEOUT_S while assigned) are
        reported through on_peer_error."""
        out: List[Tuple[str, int]] = []
        errors: List[Tuple[str, str]] = []
        now = time.monotonic()
        with self._lock:
            # grow the request window
            top = self.height + REQUEST_WINDOW - 1
            for h in range(self.height, min(top, self._max_peer_height) + 1):
                if h not in self._requests:
                    self._requests[h] = _Request(h)
            # recycle timed-out assignments; drop timed-out peers
            for req in self._requests.values():
                if (req.peer_id is not None and req.block is None
                        and now - req.sent_at > REQUEST_RETRY_S):
                    p = self._peers.get(req.peer_id)
                    if p is not None:
                        errors.append((req.peer_id, "block request timed out"))
                        self._remove_peer_locked(req.peer_id)
                    req.peer_id = None
            # assign
            pending = sorted(h for h, r in self._requests.items()
                             if r.peer_id is None)
            for h in pending:
                peer = self._pick_peer_locked(h)
                if peer is None:
                    continue
                req = self._requests[h]
                req.peer_id = peer.peer_id
                req.sent_at = now
                req.tries += 1
                peer.n_pending += 1
                out.append((peer.peer_id, h))
        for pid, reason in errors:
            if self._on_peer_error:
                self._on_peer_error(pid, reason)
        return out

    def _pick_peer_locked(self, height: int) -> Optional[_PoolPeer]:
        best = None
        for p in self._peers.values():
            if p.n_pending >= MAX_PENDING_PER_PEER:
                continue
            if not (p.base <= height <= p.height):
                continue
            if best is None or p.n_pending < best.n_pending:
                best = p
        return best

    # -- responses (pool.go AddBlock) ---------------------------------------

    def add_block(self, peer_id: str, block: Block, _size: int = 0) -> bool:
        """Accept a block only from the peer assigned to that height."""
        err = None
        with self._lock:
            req = self._requests.get(block.header.height)
            if req is None or req.peer_id != peer_id or req.block is not None:
                # unsolicited block — the reference treats this as peer
                # misbehavior (pool.go:244-255)
                if peer_id in self._peers:
                    err = f"unsolicited block at height {block.header.height}"
            else:
                req.block = block
                p = self._peers.get(peer_id)
                if p is not None:
                    p.n_pending = max(0, p.n_pending - 1)
                    p.last_recv = time.monotonic()
                return True
        if err and self._on_peer_error:
            self._on_peer_error(peer_id, err)
        return False

    # -- the verify/apply interface (pool.go PeekTwoBlocks/PopRequest) ------

    def peek_two_blocks(self) -> Tuple[Optional[Block], Optional[Block]]:
        with self._lock:
            first = self._requests.get(self.height)
            second = self._requests.get(self.height + 1)
            return (first.block if first else None,
                    second.block if second else None)

    def peek_run(self, max_blocks: int) -> List[Block]:
        """Contiguous run of fetched blocks starting at pool.height — the
        reactor batch-verifies run[:-1] against run[1:]'s LastCommits in one
        device dispatch (new vs reference's block-at-a-time PeekTwoBlocks)."""
        out = []
        with self._lock:
            h = self.height
            while len(out) < max_blocks:
                req = self._requests.get(h)
                if req is None or req.block is None:
                    break
                out.append(req.block)
                h += 1
            return out

    def pop_request(self) -> None:
        with self._lock:
            self._requests.pop(self.height, None)
            self.height += 1

    def redo_request(self, height: int) -> Optional[str]:
        """Validation failed: forget the block and re-request from another
        peer; returns the peer that served it (to be punished)."""
        with self._lock:
            req = self._requests.get(height)
            if req is None:
                return None
            bad = req.peer_id
            req.block = None
            req.peer_id = None
            if bad is not None:
                self._remove_peer_locked(bad)
            return bad

    # -- progress -----------------------------------------------------------

    def max_peer_height(self) -> int:
        with self._lock:
            return self._max_peer_height

    def num_pending(self) -> int:
        with self._lock:
            return sum(1 for r in self._requests.values() if r.block is None)

    def is_caught_up(self) -> bool:
        """pool.go:170-186 IsCaughtUp: need >=1 peer; then caught up once a
        block arrived (or 5s elapsed) and our height is within 1 of the best
        reported peer height."""
        with self._lock:
            if not self._peers:
                return False
            received_or_timed_out = (
                self.height > self._start_height
                or time.monotonic() - self._started_at > 5.0
            )
            longest = (self._max_peer_height == 0
                       or self.height >= self._max_peer_height - 1)
            return received_or_timed_out and longest
