"""v1 fast-sync FSM + block pool as one pure state machine
(reference: blockchain/v1/reactor_fsm.go + pool.go + peer.go).

Inputs are methods named after the reference's bReactorEvent values
(startFSMEv, statusResponseEv, blockResponseEv, noBlockResponseEv,
processedBlockEv, makeRequestsEv, stateTimeoutEv, peerRemoveEv,
stopFSMEv); outputs are lists of event dataclasses the reactor turns
into sends. No I/O, no threads, no wall clock — callers pass ``now``,
and run the state timer themselves off ``state`` / ``timeout_s``
(reactor_fsm.go resetStateTimer), so every transition in the
reference's table is unit-testable deterministically.

The pool half (pool.go) assigns planned heights to peers round-robin
with a per-peer in-flight cap and yields blocks in (first, second)
pairs: first is applied only after its successor's LastCommit verifies
it (reactor.go processBlock).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# reactor_fsm.go timeouts
WAIT_FOR_PEER_TIMEOUT_S = 3.0
WAIT_FOR_BLOCK_TIMEOUT_S = 10.0
# pool.go / reactor.go request discipline
MAX_PENDING_PER_PEER = 20      # peer.go maxRequestsPerPeer
MAX_NUM_REQUESTS = 64          # reactor.go maxNumRequests

# error strings (reactor_fsm.go errors; values the reactor reports in
# PeerError / uses to decide whether sync failed)
ERR_PEER_TOO_SHORT = "peer height too low"
ERR_PEER_LOWERS_HEIGHT = "peer reports a height lower than previous"
ERR_DUPLICATE_BLOCK = "duplicate block from peer"
ERR_BAD_DATA = "block from wrong peer or block is bad"
ERR_MISSING_BLOCK = "missing blocks"
ERR_NO_TALLER_PEER = "timed out waiting for a taller peer"
ERR_NO_PEER_RESPONSE_CURRENT = "no peer response for current heights"
ERR_SLOW_PEER = "peer is not sending us data fast enough"


# -- output events ----------------------------------------------------------


@dataclass
class SendStatusRequest:
    pass


@dataclass
class BlockRequest:
    peer_id: str
    height: int


@dataclass
class PeerError:
    peer_id: str
    reason: str


@dataclass
class SyncFinished:
    reason: str
    failed: bool = False


@dataclass
class _Peer:
    """pool.go BpPeer (the timer/monitor lives in the FSM's state
    timeout rather than per-peer goroutines)."""
    peer_id: str
    base: int = 0
    height: int = 0
    blocks: Dict[int, Optional[object]] = field(default_factory=dict)
    # height -> Block or None while the request is in flight
    last_touch: float = 0.0

    @property
    def num_pending(self) -> int:
        return sum(1 for b in self.blocks.values() if b is None)


class BlockPool:
    """pool.go BlockPool: peers, height→peer assignments, planned
    requests, and the first/second block window at ``height``."""

    def __init__(self, height: int):
        self.height = height              # next height to execute
        self.peers: Dict[str, _Peer] = {}
        self.blocks: Dict[int, str] = {}  # height -> assigned peer
        self.planned: set = set()
        self.next_request_height = height
        self.max_peer_height = 0

    # -- peers (pool.go UpdatePeer / RemovePeer) ---------------------------

    def update_peer(self, peer_id: str, base: int, height: int,
                    now: float) -> List[object]:
        p = self.peers.get(peer_id)
        if p is None:
            if height < self.height:
                # pool.go UpdatePeer errPeerTooShort: simply not added —
                # a lagging peer is healthy, never disconnected for it
                return []
            self.peers[peer_id] = _Peer(peer_id, base, height,
                                        last_touch=now)
        else:
            if height < p.height:
                out = self.remove_peer(peer_id)
                return [PeerError(peer_id, ERR_PEER_LOWERS_HEIGHT)] + out
            p.base, p.height, p.last_touch = base, height, now
        self._update_max_height()
        return []

    def remove_peer(self, peer_id: str) -> List[object]:
        """Reschedule the peer's heights and delete it
        (pool.go RemovePeer)."""
        p = self.peers.pop(peer_id, None)
        if p is None:
            return []
        for h in list(p.blocks):
            self.planned.add(h)
            self.blocks.pop(h, None)
        self._update_max_height()
        self._remove_short_peers()
        return []

    def _remove_short_peers(self) -> None:
        # pool.go removeShortPeers: execution advanced past their tip
        for pid in [pid for pid, p in self.peers.items()
                    if p.height < self.height]:
            self.remove_peer(pid)

    def _update_max_height(self) -> None:
        self.max_peer_height = max(
            (p.height for p in self.peers.values()), default=0)

    # -- requests (pool.go MakeNextRequests / sendRequest) -----------------

    def make_next_requests(self, max_num: int, now: float) -> List[object]:
        out: List[object] = []
        # extend the planned window, capping TOTAL outstanding work
        # (in-flight assignments + planned) at max_num — the reference's
        # maxNumRequests bounds outstanding requests, and an uncapped
        # planned set would grow by ~20 heights per pump tick against a
        # distant peer tip
        h = self.next_request_height
        while (len(self.blocks) + len(self.planned) < max_num
               and h <= self.max_peer_height):
            if h not in self.blocks and h not in self.planned:
                self.planned.add(h)
            h += 1
            self.next_request_height = h
        for h in sorted(self.planned):
            p = self._pick_peer(h)
            if p is None:
                continue  # no peer can serve h right now
            p.blocks[h] = None
            p.last_touch = now
            self.blocks[h] = p.peer_id
            self.planned.discard(h)
            out.append(BlockRequest(p.peer_id, h))
        return out

    def _pick_peer(self, height: int) -> Optional[_Peer]:
        best = None
        for p in self.peers.values():
            if (p.base <= height <= p.height
                    and p.num_pending < MAX_PENDING_PER_PEER):
                if best is None or p.num_pending < best.num_pending:
                    best = p
        return best

    # -- blocks (pool.go AddBlock / FirstTwoBlocksAndPeers) ----------------

    def add_block(self, peer_id: str, height: int, block,
                  now: float) -> List[object]:
        """Any AddBlock error removes the peer (reactor_fsm.go
        blockResponseEv: unsolicited / wrong peer / duplicate)."""
        p = self.peers.get(peer_id)
        if p is None or self.blocks.get(height) != peer_id:
            out = self.remove_peer(peer_id)
            return [PeerError(peer_id, ERR_BAD_DATA)] + out
        if p.blocks.get(height) is not None:
            out = self.remove_peer(peer_id)
            return [PeerError(peer_id, ERR_DUPLICATE_BLOCK)] + out
        p.blocks[height] = block
        p.last_touch = now
        return []

    def first_two_blocks(self) -> Optional[Tuple[object, str, object, str]]:
        """(first, its peer, second, its peer) at (height, height+1), or
        None while either is missing (pool.go FirstTwoBlocksAndPeers)."""
        got = []
        for h in (self.height, self.height + 1):
            pid = self.blocks.get(h)
            p = self.peers.get(pid) if pid else None
            blk = p.blocks.get(h) if p else None
            if blk is None:
                return None
            got += [blk, pid]
        return tuple(got)

    def invalidate_first_two(self) -> List[object]:
        """Verification failed: both suppliers are suspect
        (pool.go InvalidateFirstTwoBlocks)."""
        out: List[object] = []
        for h in (self.height, self.height + 1):
            pid = self.blocks.get(h)
            if pid is not None:
                out.append(PeerError(pid, ERR_BAD_DATA))
                out += self.remove_peer(pid)
        return out

    def processed_current_height(self) -> None:
        h = self.height
        pid = self.blocks.pop(h, None)
        if pid in self.peers:
            self.peers[pid].blocks.pop(h, None)
        self.planned.discard(h)
        self.height = h + 1
        self._remove_short_peers()

    def remove_peers_at_current_heights(self) -> List[object]:
        """No response at (height, height+1) inside the state timeout:
        drop whoever was assigned them (pool.go
        RemovePeerAtCurrentHeights)."""
        out: List[object] = []
        for h in (self.height, self.height + 1):
            pid = self.blocks.get(h)
            if pid is not None and pid in self.peers \
                    and self.peers[pid].blocks.get(h) is None:
                out.append(PeerError(pid, ERR_NO_PEER_RESPONSE_CURRENT))
                out += self.remove_peer(pid)
        return out

    def needs_blocks(self) -> bool:
        return bool(self.peers) and not self.reached_max_height()

    def reached_max_height(self) -> bool:
        return bool(self.peers) and self.height >= self.max_peer_height


class FSM:
    """reactor_fsm.go BcReactorFSM. ``state`` ∈ {"unknown",
    "wait_for_peer", "wait_for_block", "finished"}; ``timeout_s`` is the
    current state's timer (None = no timer). The caller restarts its
    timer whenever ``state`` or ``timer_generation`` changes and feeds
    expiry back via ``state_timeout``."""

    def __init__(self, start_height: int):
        self.pool = BlockPool(start_height)
        self.state = "unknown"
        self.timer_generation = 0  # bumped on every resetStateTimer
        self.failed: Optional[str] = None

    @property
    def timeout_s(self) -> Optional[float]:
        return {"wait_for_peer": WAIT_FOR_PEER_TIMEOUT_S,
                "wait_for_block": WAIT_FOR_BLOCK_TIMEOUT_S}.get(self.state)

    def _to(self, state: str) -> None:
        if self.state != state:
            self.state = state
        self.timer_generation += 1

    # -- events (one method per bReactorEvent) -----------------------------

    def start(self) -> List[object]:
        if self.state != "unknown":
            return []
        self._to("wait_for_peer")
        return [SendStatusRequest()]

    def stop(self) -> List[object]:
        if self.state == "finished":
            return []
        self._to("finished")
        return [SyncFinished("stopped", failed=self.failed is not None)]

    def status_response(self, peer_id: str, base: int, height: int,
                        now: float) -> List[object]:
        if self.state not in ("wait_for_peer", "wait_for_block"):
            return []
        out = self.pool.update_peer(peer_id, base, height, now)
        if self.state == "wait_for_peer":
            if self.pool.peers:
                self._to("wait_for_block")
            return out
        # wait_for_block (reactor_fsm.go statusResponseEv): losing every
        # peer sends us back to waiting; covering the max height ends it
        if not self.pool.peers:
            self._to("wait_for_peer")
        elif self.pool.reached_max_height():
            self._to("finished")
            out = out + [SyncFinished("caught up")]
        return out

    def block_response(self, peer_id: str, height: int, block,
                       now: float) -> List[object]:
        if self.state != "wait_for_block":
            return []
        out = self.pool.add_block(peer_id, height, block, now)
        if not self.pool.peers:
            self._to("wait_for_peer")
        return out

    def no_block_response(self, peer_id: str, height: int) -> List[object]:
        """reactor_fsm.go treats this as informational; the peer stays
        (its state timer will catch real starvation)."""
        return []

    def processed_block(self, err: Optional[str]) -> List[object]:
        """reactor_fsm.go processedBlockEv: invalidate-and-punish on a
        verification error, advance and reset the state timer on
        success; either path may land on the max height."""
        if self.state != "wait_for_block":
            return []
        if err is not None:
            out = self.pool.invalidate_first_two()
        else:
            out = []
            self.pool.processed_current_height()
            self._to(self.state)  # progress: reset the block timer
        if self.pool.reached_max_height():
            self._to("finished")
            return out + [SyncFinished("caught up")]
        if not self.pool.peers:
            self._to("wait_for_peer")
        return out

    def make_requests(self, now: float,
                      max_num: int = MAX_NUM_REQUESTS) -> List[object]:
        if self.state != "wait_for_block":
            return []
        return self.pool.make_next_requests(max_num, now)

    def peer_remove(self, peer_id: str) -> List[object]:
        """peerRemoveEv (sent by the switch for disconnected/errored
        peers)."""
        out = self.pool.remove_peer(peer_id)
        if self.state != "wait_for_block":
            return out
        if not self.pool.peers:
            self._to("wait_for_peer")
        elif self.pool.reached_max_height():
            self._to("finished")
            out = out + [SyncFinished("caught up")]
        return out

    def state_timeout(self, state_name: str) -> List[object]:
        """stateTimeoutEv: ignored when stale (for a different state
        than the current one — errTimeoutEventWrongState)."""
        if state_name != self.state:
            return []
        if self.state == "wait_for_peer":
            # no taller peer ever reported in: fast sync failed
            self.failed = ERR_NO_TALLER_PEER
            self._to("finished")
            return [SyncFinished(ERR_NO_TALLER_PEER, failed=True)]
        if self.state == "wait_for_block":
            # the blocks at (height, height+1) never arrived: drop the
            # peers assigned to them and keep waiting
            out = self.pool.remove_peers_at_current_heights()
            if not self.pool.peers:
                self._to("wait_for_peer")
            elif self.pool.reached_max_height():
                self._to("finished")
                out = out + [SyncFinished("caught up")]
            else:
                self._to(self.state)  # resetStateTimer
            return out
        return []
