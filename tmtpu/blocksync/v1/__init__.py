"""Fast sync v1 (reference: blockchain/v1/): an event-driven reactor
built around an explicit four-state FSM (unknown → waitForPeer →
waitForBlock → finished) and a block pool that assigns heights to peers
and retrieves blocks two at a time (block h is verified with block
h+1's LastCommit before being applied).

Like v2 here, the machine is PURE — `fsm.py` has no I/O, threads, or
wall clock (callers pass ``now`` in); the reactor pumps switch events
through it and performs the block I/O. The wire protocol and channel
are identical to v0/v2 (the reference's three fast-sync versions all
speak the same blockchain channel messages), so a v1 node syncs from
and serves v0/v2 peers. Selected by ``block_sync.version = "v1"``
(node.go:450 picks the blockchain reactor by config the same way).
"""

from tmtpu.blocksync.v1.reactor import BlocksyncReactorV1

__all__ = ["BlocksyncReactorV1"]
