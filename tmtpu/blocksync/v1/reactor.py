"""v1 fast-sync reactor: pumps switch events through the pure FSM and
performs block I/O (reference: blockchain/v1/reactor.go).

The reference runs a poolRoutine demuxing message/error/timeout
channels plus tickers (trySync 10 ms, statusUpdate 10 s) into FSM
events; here one pump thread does the same serially. Block processing
follows the v1 shape — the pair (h, h+1) from the pool, h verified with
h+1's LastCommit, then applied — through the shared batched verifier
(a 1-block run), and the FSM's state timer is emulated off
``fsm.timeout_s`` / ``fsm.timer_generation``.

Wire protocol and channel are identical to v0/v2, so a v1 node syncs
from either and serves both.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from tmtpu.blocksync.common import (
    BLOCKCHAIN_CHANNEL, BlockServingMixin, verify_block_run,
)
from tmtpu.blocksync.msgs import BlockRequestPB, BlocksyncMessagePB
from tmtpu.blocksync.v1 import fsm as fsm_mod
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor
from tmtpu.types.block import Block

STATUS_UPDATE_INTERVAL_S = 10.0
TICK_S = 0.02


class BlocksyncReactorV1(BlockServingMixin, Reactor):
    """Selected by ``block_sync.version = "v1"`` (node.go:450 picks the
    blockchain reactor by config the same way)."""

    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None,
                 verify_backend: Optional[str] = None):
        super().__init__("BLOCKSYNC")
        if state.last_block_height != block_store.height():
            raise ValueError(
                f"state ({state.last_block_height}) and store "
                f"({block_store.height()}) height mismatch")
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.verify_backend = verify_backend
        start = block_store.height() + 1
        if start == 1:
            start = state.initial_height
        self.fsm = fsm_mod.FSM(start)
        self.blocks_synced = 0
        self._events: "queue.Queue" = queue.Queue(maxsize=10_000)
        self.event_drops: dict = {}  # kind -> count (queue-full drops)
        self._pump_alive = False
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- reactor interface --------------------------------------------------

    def get_channels(self):
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_start(self) -> None:
        if self.fast_sync:
            self._start_pump(state_synced=False)

    def _start_pump(self, state_synced: bool) -> None:
        # alive BEFORE start(): the switch can deliver add_peer/status
        # for already-connected peers before the thread is scheduled
        self._pump_alive = True
        self._thread = threading.Thread(
            target=self._pump, args=(state_synced,), daemon=True,
            name="blocksync-v1")
        self._thread.start()

    def on_stop(self) -> None:
        self._stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _enqueue(self, ev) -> None:
        if not self._pump_alive:
            return
        try:
            self._events.put_nowait(ev)
            return
        except queue.Full:
            pass
        dropped = ev
        if ev[0] == "block":
            # a full queue prefers dropping a queued STATUS update over
            # this block: statuses refresh for free every 10s, a dropped
            # block costs a request timeout + re-request round trip
            with self._events.mutex:
                q = self._events.queue
                for i, queued in enumerate(q):
                    if queued[0] == "status":
                        dropped = queued
                        del q[i]
                        q.append(ev)
                        break
        self.event_drops[dropped[0]] = \
            self.event_drops.get(dropped[0], 0) + 1
        from tmtpu.libs import log

        log.default_logger().error(
            "blocksync event queue full, dropped event",
            module="blocksync", kind=dropped[0],
            drops=self.event_drops[dropped[0]])

    def add_peer(self, peer: Peer) -> None:
        peer.send(BLOCKCHAIN_CHANNEL, self._status_msg())

    def remove_peer(self, peer: Peer, reason) -> None:
        self._enqueue(("remove_peer", peer.node_id))

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = BlocksyncMessagePB.decode(msg_bytes)
        if msg.block_request is not None:
            self._respond_to_peer(msg.block_request.height, peer)
        elif msg.status_request is not None:
            peer.try_send(BLOCKCHAIN_CHANNEL, self._status_msg())
        elif msg.block_response is not None:
            block = Block.from_proto(msg.block_response.block)
            self._enqueue(("block", peer.node_id, block))
        elif msg.status_response is not None:
            self._enqueue(("status", peer.node_id,
                           msg.status_response.base,
                           msg.status_response.height))
        elif msg.no_block_response is not None:
            self._enqueue(
                ("no_block", peer.node_id, msg.no_block_response.height))

    # -- the pump (reactor.go poolRoutine) ----------------------------------

    def _pump(self, state_synced: bool) -> None:
        try:
            self._pump_loop(state_synced)
        except Exception:  # noqa: BLE001 — a dead pump must be loud
            import traceback

            traceback.print_exc()
            raise
        finally:
            self._pump_alive = False

    def _pump_loop(self, state_synced: bool) -> None:
        fsm = self.fsm
        self._emit(fsm.start())
        last_status = 0.0
        timer_gen = fsm.timer_generation
        timer_deadline = (time.monotonic() + fsm.timeout_s
                          if fsm.timeout_s else None)
        while not self._stopped.is_set():
            now = time.monotonic()
            if now - last_status > STATUS_UPDATE_INTERVAL_S:
                last_status = now
                self.broadcast_status_request()
            drained = False
            try:
                while True:
                    ev = self._events.get_nowait()
                    drained = True
                    self._dispatch(fsm, ev, time.monotonic())
            except queue.Empty:
                pass
            # state timer (reactor_fsm.go resetStateTimer semantics:
            # restart whenever the FSM bumps timer_generation)
            if fsm.timer_generation != timer_gen:
                timer_gen = fsm.timer_generation
                timer_deadline = (time.monotonic() + fsm.timeout_s
                                  if fsm.timeout_s else None)
            elif timer_deadline is not None and now > timer_deadline:
                self._emit(fsm.state_timeout(fsm.state))
                timer_gen = fsm.timer_generation
                timer_deadline = (time.monotonic() + fsm.timeout_s
                                  if fsm.timeout_s else None)
            self._emit(fsm.make_requests(time.monotonic()))
            if self._try_process(fsm):
                drained = True
            if fsm.state == "finished":
                if fsm.failed:
                    # reference behaviour on errNoTallerPeer: switch to
                    # consensus anyway — a lone (or fully caught-up)
                    # node must start proposing
                    pass
                self._switch_to_consensus(state_synced)
                return
            if not drained:
                self._stopped.wait(TICK_S)

    def _dispatch(self, fsm, ev, now: float) -> None:
        kind = ev[0]
        if kind == "remove_peer":
            self._emit(fsm.peer_remove(ev[1]))
        elif kind == "status":
            self._emit(fsm.status_response(ev[1], ev[2], ev[3], now))
        elif kind == "block":
            _, peer_id, block = ev
            self._emit(fsm.block_response(
                peer_id, block.header.height, block, now))
        elif kind == "no_block":
            self._emit(fsm.no_block_response(ev[1], ev[2]))

    def _emit(self, events) -> None:
        for e in events:
            if isinstance(e, fsm_mod.SendStatusRequest):
                self.broadcast_status_request()
            elif isinstance(e, fsm_mod.BlockRequest):
                peer = (self.switch.peers.get(e.peer_id)
                        if self.switch else None)
                if peer is not None:
                    peer.try_send(
                        BLOCKCHAIN_CHANNEL,
                        BlocksyncMessagePB(block_request=BlockRequestPB(
                            height=e.height)).encode())
            elif isinstance(e, fsm_mod.PeerError):
                self._stop_peer(e.peer_id, e.reason)
            # SyncFinished is read via fsm.state in the pump loop

    # -- processing (reactor.go processBlock) -------------------------------

    def _try_process(self, fsm) -> bool:
        pair = fsm.pool.first_two_blocks()
        if pair is None:
            return False
        first, _pid1, second, _pid2 = pair
        results, parts_bids = verify_block_run(
            self.state, [first], [second], self.verify_backend)
        err, (parts, bid) = results[0], parts_bids[0]
        if err is None:
            try:
                self.block_exec.validate_block(self.state, first)
            except Exception as e:  # noqa: BLE001
                err = e
        if err is not None:
            self._emit(fsm.processed_block(str(err)))
            return True
        self.store.save_block(first, parts, second.last_commit)
        self.state, _ = self.block_exec.apply_block(self.state, bid, first)
        self.blocks_synced += 1
        self._emit(fsm.processed_block(None))
        return True

    # -- statesync handoff --------------------------------------------------

    def switch_to_fast_sync(self, state) -> None:
        self.state = state
        self.fast_sync = True
        self.fsm = fsm_mod.FSM(state.last_block_height + 1)
        self._start_pump(state_synced=True)
