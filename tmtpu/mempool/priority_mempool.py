"""Priority mempool — v1 (reference: mempool/v1/mempool.go).

Same Mempool interface as CListMempool, but ordered by the per-tx
priority the app returns from CheckTx (ResponseCheckTx.priority):

- ``reap_max_bytes_max_gas`` serves highest-priority first (FIFO within
  a priority level);
- when full, a new higher-priority tx EVICTS the lowest-priority
  resident txs to make room (mempool.go:  TryAddNewTx eviction loop) —
  a full v0 mempool just rejects.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional

from tmtpu.abci import types as abci
from tmtpu.crypto import tmhash
from tmtpu.libs import txlat
from tmtpu.libs.clist import CElement, CList
from tmtpu.mempool.clist_mempool import (
    AsyncRecheckMixin, BatchCheckMixin, MempoolFullError, TxCache,
    TxInMempoolError, pipelined_check_tx,
)


class PriorityMempool(BatchCheckMixin, AsyncRecheckMixin):
    def __init__(self, proxy_app, max_txs: int = 5000,
                 max_txs_bytes: int = 1 << 30, cache_size: int = 10000,
                 keep_invalid_txs_in_cache: bool = False,
                 pre_check: Optional[Callable] = None,
                 ttl_num_blocks: int = 0, ttl_duration_ns: int = 0,
                 batch_check: bool = True,
                 batch_gather_wait_s: float = 0.002,
                 batch_max_txs: int = 256,
                 verify_signatures: bool = True):
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self.pre_check = pre_check
        # v1 TTLs (mempool.go:730 purgeExpiredTxs): 0 disables each axis
        self.ttl_num_blocks = int(ttl_num_blocks)
        self.ttl_duration_ns = int(ttl_duration_ns)
        self.cache = TxCache(cache_size)
        self._txs: dict = {}  # hash -> info
        self._list = CList()  # arrival order, for cursor-based gossip
        self._txs_bytes = 0
        self._height = 0
        self._seq = itertools.count()  # FIFO tiebreak within a priority
        self._init_recheck()
        self._init_batch_check(batch_check, batch_gather_wait_s,
                               batch_max_txs, verify_signatures)
        self._lock = threading.RLock()
        self._update_lock = threading.RLock()
        self._notify: List[Callable] = []

    # -- Mempool interface ---------------------------------------------------
    # check_tx / check_tx_nowait provided by BatchCheckMixin. v1 has no
    # up-front full check: fullness resolves in _add via eviction.

    def _precheck_admit(self, tx: bytes) -> None:
        if not self.cache.push(tx):
            raise TxInMempoolError("tx already exists in cache")
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err is not None:
                self.cache.remove(tx)
                raise ValueError(f"pre-check failed: {err}")

    def _apply_check_tx_result(self, tx: bytes, res: abci.ResponseCheckTx,
                               tx_info: dict) -> None:
        if res.is_ok():
            self._add(tx, res, tx_info)  # may raise MempoolFullError
        elif not self.keep_invalid_txs_in_cache:
            self.cache.remove(tx)

    def _add(self, tx: bytes, res: abci.ResponseCheckTx,
             tx_info: dict) -> None:
        key = tmhash.sum(tx)
        with self._lock:
            if key in self._txs or self._already_committed(key):
                # committed while this admission was in flight: inserting
                # now would get the tx proposed (and applied) twice
                return
            # eviction (v1): make room by dropping strictly-lower-priority
            # residents; refuse if the newcomer can't fit even then
            while (len(self._txs) >= self.max_txs or
                   self._txs_bytes + len(tx) > self.max_txs_bytes):
                victim_key = None
                victim = None
                for k, info in self._txs.items():
                    if info["priority"] < res.priority and (
                            victim is None
                            or (info["priority"], -info["seq"])
                            < (victim["priority"], -victim["seq"])):
                        victim_key, victim = k, info
                if victim_key is None:
                    self.cache.remove(tx)
                    raise MempoolFullError(
                        f"mempool is full: {len(self._txs)} txs and no "
                        f"lower-priority tx to evict")
                # evicted txs must be re-submittable (they're in no block)
                self._remove_tx(victim_key, drop_cache=True)
            info = {
                "tx": tx, "hash": key, "priority": res.priority,
                "gas_wanted": res.gas_wanted, "seq": next(self._seq),
                "height": self._height,
                "time_ns": time.time_ns(),  # for ttl_duration (tx.go:16)
                "senders": set(filter(None, [tx_info.get("sender")])),
            }
            info["_el"] = self._list.push_back(info)
            self._txs[key] = info
            self._txs_bytes += len(tx)
            txlat.stamp(key, "admit")
        # callbacks run OUTSIDE self._lock: a txs-available listener that
        # re-enters the mempool (or grabs its own lock) must not nest
        # under the admission lock
        for fn in self._notify:
            fn()
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def _remove_tx(self, key: bytes, drop_cache: bool) -> None:
        """Drop one resident tx, keeping map/clist/byte-counter/cache in
        sync — the single place that invariant lives. Caller holds
        self._lock."""
        info = self._txs.pop(key, None)
        if info is None:
            return
        self._list.remove(info["_el"])
        self._txs_bytes -= len(info["tx"])
        if drop_cache:
            self.cache.remove(info["tx"])

    def _ordered(self) -> List[dict]:
        return sorted(self._txs.values(),
                      key=lambda i: (-i["priority"], i["seq"]))

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]:
        with self._lock:
            out, total_b, total_g = [], 0, 0
            for info in self._ordered():
                nb = total_b + len(info["tx"]) + 20
                ng = total_g + max(info["gas_wanted"], 0)
                if max_bytes > -1 and nb > max_bytes:
                    break
                if max_gas > -1 and ng > max_gas:
                    break
                total_b, total_g = nb, ng
                out.append(info["tx"])
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [i["tx"] for i in self._ordered()]
            return txs if n < 0 else txs[:n]

    def lock(self) -> None:
        self._update_lock.acquire()

    def unlock(self) -> None:
        self._update_lock.release()

    def update(self, height: int, txs: List[bytes],
               deliver_tx_responses) -> None:
        with self._lock:
            self._height = height
            for tx, res in zip(txs, deliver_tx_responses):
                key = tmhash.sum(tx)
                if res.is_ok():
                    self.cache.push(tx)
                    self._note_committed(key)
                elif not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
                self._remove_tx(key, drop_cache=False)
            self._purge_expired(height)
        # async recheck, same rationale as CListMempool._schedule_recheck
        self._schedule_recheck()
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def _purge_expired(self, block_height: int) -> None:
        """mempool.go:730 purgeExpiredTxs — drop txs past either TTL
        axis (block age, wall age). Caller holds self._lock. Purged txs
        leave the cache so they can be resubmitted."""
        if self.ttl_num_blocks == 0 and self.ttl_duration_ns == 0:
            return
        now = time.time_ns()
        for key in list(self._txs):
            info = self._txs[key]
            if (self.ttl_num_blocks > 0 and
                    block_height - info["height"] > self.ttl_num_blocks) \
                    or (self.ttl_duration_ns > 0 and
                        now - info["time_ns"] > self.ttl_duration_ns):
                self._remove_tx(key, drop_cache=True)

    def _recheck_pass(self) -> None:
        # one pipelined async batch (N queued requests + a single flush)
        # instead of N serial sync round trips — same rationale as
        # CListMempool._recheck_pass
        with self._lock:
            remaining = [i["tx"] for i in self._txs.values()]
        if not remaining:
            return
        responses = pipelined_check_tx(self.proxy_app, [
            abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_RECHECK)
            for tx in remaining])
        for tx, res in zip(remaining, responses):
            with self._lock:
                info = self._txs.get(tmhash.sum(tx))
                if info is None:
                    continue
                if not res.is_ok():
                    self._remove_tx(
                        tmhash.sum(tx),
                        drop_cache=not self.keep_invalid_txs_in_cache)
                else:
                    info["priority"] = res.priority

    def flush(self) -> None:
        with self._lock:
            for info in self._txs.values():
                self._list.remove(info["_el"])
            self._txs.clear()
            self._txs_bytes = 0
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(0)

    def flush_app_conn(self) -> None:
        self.proxy_app.flush_sync()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._lock:
            return self._txs_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def txs_available(self, fn: Callable) -> None:
        self._notify.append(fn)

    def front(self) -> Optional[CElement]:
        """Arrival-order front, for the reactor's gossip cursor (gossip
        runs in arrival order; priority governs reaping only)."""
        return self._list.front()

    def wait_front(self, timeout: float | None = None) -> Optional[CElement]:
        return self._list.wait_chan(timeout)

    def mark_sender(self, tx: bytes, sender) -> None:
        with self._lock:
            info = self._txs.get(tmhash.sum(tx))
            if info is not None:
                info["senders"].add(sender)

    def senders(self, tx: bytes) -> set:
        with self._lock:
            info = self._txs.get(tmhash.sum(tx))
            return set(info["senders"]) if info else set()
