"""Mempool reactor (reference: mempool/v0/reactor.go) — gossips txs on
channel 0x30 via per-peer broadcast threads; received txs go through
CheckTx with the sender recorded so they aren't echoed back.

Dedup-aware gossip: each peer carries a seen-tx LRU covering both
directions — txs the peer SENT us and txs we already sent IT. The
cursor-based broadcast consults it before echoing, which (a) never
returns a tx to its sender even after the tx leaves the mempool (the
``senders`` set dies with the mempool entry), and (b) fixes the
tail-removal restart: when the cursor resets to the mempool front, the
LRU prevents re-sending everything the peer already has.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Dict

from tmtpu.crypto import tmhash
from tmtpu.libs import metrics as _m
from tmtpu.libs import trace as _trace
from tmtpu.libs import txlat
from tmtpu.libs.protoio import ProtoMessage
from tmtpu.mempool.clist_mempool import CListMempool, MempoolFullError, \
    TxInMempoolError
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30


class TxsPB(ProtoMessage):
    """proto/tendermint/mempool/types.proto Txs.

    Field 2 is an optional piggybacked trace context (libs/trace.py wire
    form) naming the in-flight height's root trace at the sender; old
    peers skip it, empty is omitted (absent ⇒ untraced batch).
    """

    FIELDS = [(1, "txs", ("rep", "bytes")),
              (2, "trace_ctx", "bytes")]


class PeerSeenCache:
    """Bounded LRU of tx hashes one peer is known to have (either
    direction). Thread-safe: the p2p recv thread and the peer's
    broadcast thread both touch it."""

    def __init__(self, size: int):
        self.size = int(size)
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def add(self, key: bytes) -> None:
        if self.size <= 0:
            return
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return
            self._map[key] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)

    def __contains__(self, key: bytes) -> bool:
        if self.size <= 0:
            return False
        with self._lock:
            return key in self._map


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, broadcast: bool = True,
                 seen_cache: int = 4096):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast = broadcast
        self.seen_cache = int(seen_cache)
        self._stopped = threading.Event()
        self._seen: Dict[str, PeerSeenCache] = {}
        self._seen_mtx = threading.Lock()
        # received txs are admitted on a dedicated worker, NOT the p2p recv
        # thread (the reference uses CheckTxAsync for the same reason): a
        # CheckTx ABCI round-trip per tx on the recv thread makes every
        # consensus vote/proposal on that connection queue behind the tx
        # flood — under load the consensus thread starves and rounds fail
        self._rx_q: "queue.Queue[tuple]" = queue.Queue(maxsize=10000)
        self._rx_thread: threading.Thread | None = None

    def on_start(self) -> None:
        self._rx_thread = threading.Thread(target=self._admit_routine,
                                           daemon=True, name="mempool-admit")
        self._rx_thread.start()

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_stop(self) -> None:
        self._stopped.set()
        t = self._rx_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _peer_seen(self, node_id: str) -> PeerSeenCache:
        with self._seen_mtx:
            cache = self._seen.get(node_id)
            if cache is None:
                cache = self._seen[node_id] = PeerSeenCache(self.seen_cache)
            return cache

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast or not peer.has_channel(MEMPOOL_CHANNEL):
            return
        t = threading.Thread(target=self._broadcast_routine, args=(peer,),
                             daemon=True,
                             name=f"mempool-bcast-{peer.node_id[:8]}")
        t.start()

    def remove_peer(self, peer: Peer, reason: str) -> None:
        with self._seen_mtx:
            self._seen.pop(peer.node_id, None)

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        m = TxsPB.decode(msg_bytes)
        if m.trace_ctx:
            # one mark per traced batch, never per tx; garbage decodes
            # to None and is only counted
            ctx = _trace.adopt(bytes(m.trace_ctx))
            if ctx is not None:
                _m.trace_context_rx.inc(transport="gossip")
                _trace.mark("gossip.txs_rx", ctx=ctx, txs=len(m.txs),
                            peer=peer.node_id)
            else:
                _m.trace_context_invalid.inc(transport="gossip")
        seen = self._peer_seen(peer.node_id)
        for tx in m.txs:
            tx = bytes(tx)
            # the sender obviously has this tx: record it so the
            # broadcast cursor never echoes it back
            h = tmhash.sum(tx)
            seen.add(h)
            # first-stamp-wins: only the FIRST gossip arrival opens the
            # follower-side journey; re-receipts are no-ops
            txlat.stamp(h, "gossip_rx")
            try:
                self._rx_q.put_nowait((tx, peer.node_id))
            except queue.Full:
                # backpressure: drop — the peer's broadcast routine will
                # offer it again via another peer or a later batch
                return

    def _admit_routine(self) -> None:
        while not self._stopped.is_set():
            try:
                tx, sender = self._rx_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                # enqueue-and-return: the mempool's gather worker does
                # the signature flush + pipelined ABCI round trip, so a
                # tx flood never parks this thread on the gather window
                self.mempool.check_tx_nowait(tx, tx_info={"sender": sender})
            except TxInMempoolError:
                _m.mempool_gossip_rx_dups.inc()
                self.mempool.mark_sender(tx, sender)
            except MempoolFullError:
                self.mempool.mark_sender(tx, sender)
            except Exception:
                pass

    def _broadcast_routine(self, peer: Peer) -> None:
        """mempool/v0/reactor.go:148 broadcastTxRoutine — hold a CElement
        cursor into the mempool's concurrent list and block on wait-chans.
        Never rescans: O(1) per new tx regardless of mempool depth (the
        old full-reap-per-iteration loop went quadratic under load and
        starved CheckTx/reap of the mempool lock)."""
        el = None
        seen = self._peer_seen(peer.node_id)
        while peer.is_running() and not self._stopped.is_set():
            if el is None:
                el = self.mempool.wait_front(timeout=0.2)
                if el is None:
                    continue
            # collect a batch from the cursor forward, without waiting
            batch, keys, cur, last = [], [], el, el
            while cur is not None and len(batch) < 100:
                v = cur.value
                if not cur.removed:
                    key = v.get("hash") or tmhash.sum(v["tx"])
                    if key in seen or peer.node_id in v["senders"]:
                        _m.mempool_gossip_dedup_skips.inc()
                    else:
                        batch.append(v["tx"])
                        keys.append(key)
                last = cur
                cur = cur.next
            if batch:
                # tag the batch with the in-flight height's root trace
                # (the height these txs are racing to land in)
                next_h = self.mempool.height + 1
                ctx = _trace.wire_context(next_h)
                if ctx:
                    _m.trace_context_tx.inc(transport="gossip")
                    _trace.mark_height(next_h, "gossip.txs_tx",
                                       txs=len(batch), peer=peer.node_id)
                if not peer.send(MEMPOOL_CHANNEL,
                                 TxsPB(txs=batch, trace_ctx=ctx).encode()):
                    time.sleep(0.05)  # send queue full: retry same position
                    continue
            # only a handed-off batch counts as delivered to the peer's
            # send queue — a failed send must stay eligible for retry
            for key in keys:
                seen.add(key)
            # advance: block until `last` gains a successor or is removed
            nxt = last.next_wait(timeout=0.2)
            if nxt is not None:
                el = nxt
            elif last.removed:
                el = None  # tail removed: restart from the current front
            else:
                el = last  # timeout: re-wait (also re-checks peer liveness)
