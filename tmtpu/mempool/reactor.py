"""Mempool reactor (reference: mempool/v0/reactor.go) — gossips txs on
channel 0x30 via per-peer broadcast threads; received txs go through
CheckTx with the sender recorded so they aren't echoed back."""

from __future__ import annotations

import threading
import time
from typing import Dict

from tmtpu.libs.protoio import ProtoMessage
from tmtpu.mempool.clist_mempool import CListMempool, MempoolFullError, \
    TxInMempoolError
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30


class TxsPB(ProtoMessage):
    """proto/tendermint/mempool/types.proto Txs."""

    FIELDS = [(1, "txs", ("rep", "bytes"))]


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, broadcast: bool = True):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast = broadcast
        self._stopped = threading.Event()

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_stop(self) -> None:
        self._stopped.set()

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast or not peer.has_channel(MEMPOOL_CHANNEL):
            return
        t = threading.Thread(target=self._broadcast_routine, args=(peer,),
                             daemon=True,
                             name=f"mempool-bcast-{peer.node_id[:8]}")
        t.start()

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        m = TxsPB.decode(msg_bytes)
        for tx in m.txs:
            try:
                self.mempool.check_tx(bytes(tx),
                                      tx_info={"sender": peer.node_id})
            except (TxInMempoolError, MempoolFullError):
                self.mempool.mark_sender(bytes(tx), peer.node_id)
            except Exception:
                pass

    def _broadcast_routine(self, peer: Peer) -> None:
        """mempool/v0/reactor.go:148 broadcastTxRoutine — iterate the
        mempool, send txs the peer hasn't seen."""
        sent: set = set()
        while peer.is_running() and not self._stopped.is_set():
            batch = []
            for tx in self.mempool.reap_max_txs(-1):
                key = hash(tx)
                if key in sent:
                    continue
                if peer.node_id in self.mempool.senders(tx):
                    sent.add(key)
                    continue
                batch.append(tx)
                sent.add(key)
                if len(batch) >= 100:
                    break
            if batch:
                if not peer.send(MEMPOOL_CHANNEL, TxsPB(txs=batch).encode()):
                    for tx in batch:
                        sent.discard(hash(tx))
            else:
                time.sleep(0.02)
            if len(sent) > 100_000:
                sent.clear()
