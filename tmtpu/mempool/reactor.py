"""Mempool reactor (reference: mempool/v0/reactor.go) — gossips txs on
channel 0x30 via per-peer broadcast threads; received txs go through
CheckTx with the sender recorded so they aren't echoed back."""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict

from tmtpu.libs.protoio import ProtoMessage
from tmtpu.mempool.clist_mempool import CListMempool, MempoolFullError, \
    TxInMempoolError
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30


class TxsPB(ProtoMessage):
    """proto/tendermint/mempool/types.proto Txs."""

    FIELDS = [(1, "txs", ("rep", "bytes"))]


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, broadcast: bool = True):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast = broadcast
        self._stopped = threading.Event()
        # received txs are admitted on a dedicated worker, NOT the p2p recv
        # thread (the reference uses CheckTxAsync for the same reason): a
        # CheckTx ABCI round-trip per tx on the recv thread makes every
        # consensus vote/proposal on that connection queue behind the tx
        # flood — under load the consensus thread starves and rounds fail
        self._rx_q: "queue.Queue[tuple]" = queue.Queue(maxsize=10000)
        self._rx_thread: threading.Thread | None = None

    def on_start(self) -> None:
        self._rx_thread = threading.Thread(target=self._admit_routine,
                                           daemon=True, name="mempool-admit")
        self._rx_thread.start()

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def on_stop(self) -> None:
        self._stopped.set()

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast or not peer.has_channel(MEMPOOL_CHANNEL):
            return
        t = threading.Thread(target=self._broadcast_routine, args=(peer,),
                             daemon=True,
                             name=f"mempool-bcast-{peer.node_id[:8]}")
        t.start()

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        m = TxsPB.decode(msg_bytes)
        for tx in m.txs:
            try:
                self._rx_q.put_nowait((bytes(tx), peer.node_id))
            except queue.Full:
                # backpressure: drop — the peer's broadcast routine will
                # offer it again via another peer or a later batch
                return

    def _admit_routine(self) -> None:
        while not self._stopped.is_set():
            try:
                tx, sender = self._rx_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.mempool.check_tx(tx, tx_info={"sender": sender})
            except (TxInMempoolError, MempoolFullError):
                self.mempool.mark_sender(tx, sender)
            except Exception:
                pass

    def _broadcast_routine(self, peer: Peer) -> None:
        """mempool/v0/reactor.go:148 broadcastTxRoutine — hold a CElement
        cursor into the mempool's concurrent list and block on wait-chans.
        Never rescans: O(1) per new tx regardless of mempool depth (the
        old full-reap-per-iteration loop went quadratic under load and
        starved CheckTx/reap of the mempool lock)."""
        el = None
        while peer.is_running() and not self._stopped.is_set():
            if el is None:
                el = self.mempool.wait_front(timeout=0.2)
                if el is None:
                    continue
            # collect a batch from the cursor forward, without waiting
            batch, cur, last = [], el, el
            while cur is not None and len(batch) < 100:
                v = cur.value
                if not cur.removed and peer.node_id not in v["senders"]:
                    batch.append(v["tx"])
                last = cur
                cur = cur.next
            if batch and not peer.send(MEMPOOL_CHANNEL,
                                       TxsPB(txs=batch).encode()):
                time.sleep(0.05)  # send queue full: retry same position
                continue
            # advance: block until `last` gains a successor or is removed
            nxt = last.next_wait(timeout=0.2)
            if nxt is not None:
                el = nxt
            elif last.removed:
                el = None  # tail removed: restart from the current front
            else:
                el = last  # timeout: re-wait (also re-checks peer liveness)
