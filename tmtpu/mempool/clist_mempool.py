"""Mempool v0 — FIFO with tx cache (reference: mempool/v0/clist_mempool.go).

CheckTx goes through the mempool ABCI connection; committed txs are removed
and the remainder re-checked on update (:435), exactly the reference's
lifecycle. Storage is the wait-chan concurrent list (``libs/clist.py``), exactly the
reference's core structure: broadcast routines hold a CElement cursor and
block on ``next_wait`` — no rescans, no mempool-lock contention with
CheckTx/reap on the hot path. A hash→element map provides O(1) dedup and
removal.

Throughput tier: admission is BATCHED. Concurrent ``check_tx`` calls
gather for a bounded window on a dedicated worker, signed-tx envelopes
(``mempool/signed_tx.py``) verify as ONE ``crypto/batch.py`` flush
(sigcache-fronted, breaker-protected, sidecar/mesh-capable), and the
surviving ABCI CheckTx round trips are pipelined through
``check_tx_batch_async`` + one flush instead of one synchronous round
trip per tx. ``check_tx_nowait`` is the enqueue-and-return surface the
p2p reactor uses so recv-side admission never blocks on the window.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional

from tmtpu.abci import types as abci
from tmtpu.crypto import tmhash
from tmtpu.libs import txlat
from tmtpu.libs.clist import CElement, CList


class TxInMempoolError(Exception):
    pass


class MempoolFullError(Exception):
    pass


class TxCache:
    """LRU of tx hashes (mempool/cache.go)."""

    def __init__(self, size: int):
        self.size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def push(self, tx: bytes) -> bool:
        key = tmhash.sum(tx)
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._map.pop(tmhash.sum(tx), None)


def pipelined_check_tx(proxy_app, reqs: List[abci.RequestCheckTx]
                       ) -> List[abci.ResponseCheckTx]:
    """N CheckTx round trips as one pipelined burst: enqueue every
    request, flush once, wait. Clients without the batch surface (e.g.
    gRPC) fall back to serial sync calls."""
    if not reqs:
        return []
    batch = getattr(proxy_app, "check_tx_batch_async", None)
    if batch is None:
        return [proxy_app.check_tx_sync(r) for r in reqs]
    reqres = batch(reqs)
    proxy_app.flush_sync()
    out = []
    for rr in reqres:
        res = rr.wait(timeout=60.0).check_tx
        if res is None:
            from tmtpu.abci.client import ClientError

            raise ClientError("CheckTx response missing (app conn failed)")
        out.append(res)
    return out


class AsyncRecheckMixin:
    """Shared async-recheck machinery (clist_mempool.go:435 recheckTxs
    fires async CheckTx requests — a synchronous loop would hold the
    consensus thread for mempool-size ABCI round-trips per commit).
    Subclasses implement ``_recheck_pass()``. The running/dirty flags are
    decided under one mutex so a scheduling racing a worker's exit can't
    be lost."""

    def _init_recheck(self) -> None:
        self._recheck_dirty = False
        self._recheck_running = False
        self._recheck_mtx = threading.Lock()

    def _schedule_recheck(self) -> None:
        with self._recheck_mtx:
            self._recheck_dirty = True
            if self._recheck_running:
                return
            self._recheck_running = True
        threading.Thread(target=self._recheck_worker, daemon=True,
                         name="mempool-recheck").start()

    def _recheck_worker(self) -> None:
        while True:
            with self._recheck_mtx:
                if not self._recheck_dirty:
                    self._recheck_running = False
                    return
                self._recheck_dirty = False
            try:
                self._recheck_pass()
            except Exception:
                with self._recheck_mtx:
                    self._recheck_running = False
                return  # app conn gone (shutdown)
            from tmtpu.libs import metrics as _m

            _m.mempool_size.set(self.size())

    def _recheck_pass(self) -> None:
        raise NotImplementedError


class _AdmitEntry:
    __slots__ = ("tx", "tx_info", "cb", "done", "result", "error",
                 "sig_failed")

    def __init__(self, tx: bytes, tx_info: dict, cb: Optional[Callable]):
        self.tx = tx
        self.tx_info = tx_info
        self.cb = cb
        self.done = threading.Event()
        self.result: Optional[abci.ResponseCheckTx] = None
        self.error: Optional[BaseException] = None
        self.sig_failed = False


class BatchCheckMixin:
    """Gather-window batched admission shared by both mempool versions.

    Subclasses provide ``_precheck_admit(tx)`` (synchronous full/dup/
    pre_check screens — these raise on the caller's thread, exactly the
    legacy contract) and ``_apply_check_tx_result(tx, res, tx_info)``
    (mempool bookkeeping for one resolved CheckTx). The worker is lazy:
    no thread exists until the first batched check_tx, and it retires
    after ~30s idle so short-lived test mempools don't leak pollers."""

    def _init_batch_check(self, batch_check: bool, gather_wait_s: float,
                          max_batch: int, verify_signatures: bool) -> None:
        self.batch_check = bool(batch_check)
        self.verify_signatures = bool(verify_signatures)
        self._gather_wait_s = max(0.0, float(gather_wait_s))
        self._batch_max_txs = max(1, int(max_batch))
        self._admit_q: "queue.Queue[_AdmitEntry]" = queue.Queue()
        self._admit_running = False
        self._admit_mtx = threading.Lock()
        # keys of recently committed txs: an admission that was in flight
        # (gather window, ABCI queue) when its tx committed must NOT be
        # inserted afterwards — the tx is in a block, and resurrecting it
        # gets it proposed (and applied) a second time. The tx cache alone
        # can't tell "seen because admission started" from "seen because
        # committed", so update() records commits here and the insert
        # paths drop late arrivals. Bounded LRU, caller holds self._lock.
        self._committed_keys: "OrderedDict[bytes, None]" = OrderedDict()
        self._committed_cap = 16384

    # -- public admission surface -------------------------------------------

    def check_tx(self, tx: bytes, cb: Optional[Callable] = None,
                 tx_info: Optional[dict] = None) -> None:
        """Admit one tx, blocking until its CheckTx verdict is applied
        (the RPC/broadcast surface). Raises dup/full/pre-check errors
        synchronously, like the reference."""
        tx = bytes(tx)
        self._precheck_admit(tx)
        if not self.batch_check:
            if self.verify_signatures and not self._verify_tx_signature(tx):
                from tmtpu.libs import metrics as _m

                _m.mempool_sig_rejects.inc()
                res = abci.ResponseCheckTx(code=1, log="invalid signature")
                self._apply_check_tx_result(tx, res, tx_info or {})
                if cb is not None:
                    cb(res)
                return
            res = self.proxy_app.check_tx_sync(abci.RequestCheckTx(
                tx=tx, type=abci.CHECK_TX_TYPE_NEW))
            self._apply_check_tx_result(tx, res, tx_info or {})
            if cb is not None:
                cb(res)
            return
        entry = _AdmitEntry(tx, tx_info or {}, cb)
        self._enqueue_admit(entry)
        if not entry.done.wait(timeout=60.0):
            from tmtpu.abci.client import ClientError

            raise ClientError("batched CheckTx timed out")
        if entry.error is not None:
            raise entry.error

    def check_tx_nowait(self, tx: bytes, cb: Optional[Callable] = None,
                        tx_info: Optional[dict] = None) -> None:
        """Enqueue-and-return admission for recv threads: the cheap
        synchronous screens (dup/full/pre-check) still raise here, but
        the ABCI round trip and any signature verification happen on the
        gather worker — the caller NEVER blocks on the gather window or
        the app conn."""
        tx = bytes(tx)
        self._precheck_admit(tx)
        self._enqueue_admit(_AdmitEntry(tx, tx_info or {}, cb))

    def _note_committed(self, key: bytes) -> None:
        self._committed_keys[key] = None
        self._committed_keys.move_to_end(key)
        while len(self._committed_keys) > self._committed_cap:
            self._committed_keys.popitem(last=False)

    def _already_committed(self, key: bytes) -> bool:
        return key in self._committed_keys

    def _verify_tx_signature(self, tx: bytes) -> bool:
        """Per-tx (unbatched) envelope screen for the legacy sync path —
        the signature contract must hold whether or not batching is on;
        only the cost profile may differ (one lane per tx here vs one
        flush per gather on the worker)."""
        from tmtpu.crypto import batch as _crypto_batch
        from tmtpu.mempool import signed_tx as _stx

        if not _stx.is_signed(tx):
            return True
        parsed = _stx.parse(tx)
        if parsed is None:
            return False
        pub, sig, payload = parsed
        return _crypto_batch.verify_one(pub, _stx.sign_bytes(payload), sig)

    # -- gather worker -------------------------------------------------------

    def _enqueue_admit(self, entry: _AdmitEntry) -> None:
        # gather-window wait starts here; the "flush" stamp closes it
        txlat.stamp_tx(entry.tx, "admit_enq")
        self._admit_q.put(entry)
        with self._admit_mtx:
            if not self._admit_running:
                self._admit_running = True
                threading.Thread(target=self._admit_worker, daemon=True,
                                 name="mempool-batch-check").start()

    def _admit_worker(self) -> None:
        idle_deadline = time.monotonic() + 30.0
        while True:
            try:
                first = self._admit_q.get(timeout=0.5)
            except queue.Empty:
                if time.monotonic() >= idle_deadline:
                    with self._admit_mtx:
                        if self._admit_q.empty():
                            self._admit_running = False
                            return
                continue
            idle_deadline = time.monotonic() + 30.0
            batch = [first]
            if self.batch_check:
                self._gather(batch)
            try:
                self._process_admit_batch(batch)
            except Exception as e:  # app conn gone / client error
                for en in batch:
                    if not en.done.is_set():
                        if en.error is None and en.result is None:
                            en.error = e
                        en.done.set()

    def _gather(self, batch: List[_AdmitEntry]) -> None:
        """Linger a bounded few ms so concurrent submitters share one
        signature flush and one pipelined ABCI burst. The adaptive
        crypto scheduler can extend the configured floor when device
        rate×RTT data says fuller flushes amortize better (it reports
        0.0 on CPU-only nodes, keeping the config window exact)."""
        from tmtpu.crypto import batch as _crypto_batch

        wait = max(self._gather_wait_s,
                   _crypto_batch.SCHEDULER.gather_wait_s(len(batch)))
        deadline = time.monotonic() + wait
        while len(batch) < self._batch_max_txs:
            left = deadline - time.monotonic()
            if left <= 0:
                try:
                    batch.append(self._admit_q.get_nowait())
                except queue.Empty:
                    break
                continue
            try:
                batch.append(self._admit_q.get(timeout=left))
            except queue.Empty:
                break

    def _process_admit_batch(self, batch: List[_AdmitEntry]) -> None:
        from tmtpu.libs import metrics as _m

        # 1) signature screen: every signed-tx envelope in the gather
        #    resolves through ONE batch-verifier flush — sigcache hits
        #    cost no lane, duplicates collapse, breakers guard the
        #    device path — and failures never reach the app at all
        if self.verify_signatures:
            from tmtpu.mempool import signed_tx as _stx

            lanes: List[_AdmitEntry] = []
            verifier = None
            for en in batch:
                if not _stx.is_signed(en.tx):
                    continue
                parsed = _stx.parse(en.tx)
                if parsed is None:
                    en.sig_failed = True
                    continue
                pub, sig, payload = parsed
                if verifier is None:
                    from tmtpu.crypto import batch as _crypto_batch

                    verifier = _crypto_batch.new_batch_verifier()
                verifier.add(pub, _stx.sign_bytes(payload), sig)
                lanes.append(en)
            if lanes:
                _ok, mask = verifier.verify()
                for en, ok in zip(lanes, mask):
                    if not ok:
                        en.sig_failed = True
        survivors: List[_AdmitEntry] = []
        for en in batch:
            if en.sig_failed:
                _m.mempool_sig_rejects.inc()
                self._finish_admit(en, abci.ResponseCheckTx(
                    code=1, log="invalid signature"))
            else:
                survivors.append(en)
        if not survivors:
            return
        # 2) pipelined ABCI: enqueue all CheckTx requests, one flush
        _m.mempool_batch_flushes.inc()
        _m.mempool_batch_txs.inc(len(survivors))
        if txlat.enabled():
            for en in survivors:
                txlat.stamp_tx(en.tx, "flush")
        responses = pipelined_check_tx(self.proxy_app, [
            abci.RequestCheckTx(tx=en.tx, type=abci.CHECK_TX_TYPE_NEW)
            for en in survivors])
        for en, res in zip(survivors, responses):
            self._finish_admit(en, res)

    def _finish_admit(self, en: _AdmitEntry,
                      res: abci.ResponseCheckTx) -> None:
        try:
            self._apply_check_tx_result(en.tx, res, en.tx_info)
        except Exception as e:  # e.g. v1 eviction failure
            en.error = e
            en.done.set()
            return
        en.result = res
        if en.cb is not None:
            try:
                en.cb(res)
            except Exception:
                pass  # a callback error must not poison the batch
        en.done.set()

    # -- subclass hooks ------------------------------------------------------

    def _precheck_admit(self, tx: bytes) -> None:
        raise NotImplementedError

    def _apply_check_tx_result(self, tx: bytes, res: abci.ResponseCheckTx,
                               tx_info: dict) -> None:
        raise NotImplementedError


class CListMempool(BatchCheckMixin, AsyncRecheckMixin):
    def __init__(self, proxy_app, max_txs: int = 5000,
                 max_txs_bytes: int = 1 << 30, cache_size: int = 10000,
                 keep_invalid_txs_in_cache: bool = False,
                 pre_check: Optional[Callable] = None,
                 batch_check: bool = True,
                 batch_gather_wait_s: float = 0.002,
                 batch_max_txs: int = 256,
                 verify_signatures: bool = True):
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self.pre_check = pre_check
        self.cache = TxCache(cache_size)
        self._list = CList()  # of info dicts, FIFO
        self._txs: "OrderedDict[bytes, CElement]" = OrderedDict()
        self._txs_bytes = 0
        self._init_recheck()
        self._init_batch_check(batch_check, batch_gather_wait_s,
                               batch_max_txs, verify_signatures)
        self._height = 0
        self._lock = threading.RLock()
        self._update_lock = threading.RLock()  # Lock()/Unlock() surface
        self._notify: List[Callable] = []

    # -- Mempool interface (mempool/mempool.go:30) --------------------------
    # check_tx / check_tx_nowait provided by BatchCheckMixin.

    @property
    def height(self) -> int:
        """Last height this mempool was updated against (0 pre-genesis);
        the gossip reactor tags tx batches with height+1's trace."""
        return self._height

    def _precheck_admit(self, tx: bytes) -> None:
        with self._lock:
            if len(self._txs) >= self.max_txs or \
                    self._txs_bytes + len(tx) > self.max_txs_bytes:
                raise MempoolFullError(
                    f"mempool is full: {len(self._txs)} txs")
            if not self.cache.push(tx):
                raise TxInMempoolError("tx already exists in cache")
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err is not None:
                self.cache.remove(tx)
                raise ValueError(f"pre-check failed: {err}")

    def _apply_check_tx_result(self, tx: bytes, res: abci.ResponseCheckTx,
                               tx_info: dict) -> None:
        key = tmhash.sum(tx)
        added = False
        with self._lock:
            if res.is_ok():
                if key not in self._txs and not self._already_committed(key):
                    info = {
                        "tx": tx, "hash": key, "gas_wanted": res.gas_wanted,
                        "height": self._height,
                        "senders": set(filter(None, [tx_info.get("sender")])),
                    }
                    self._txs[key] = self._list.push_back(info)
                    self._txs_bytes += len(tx)
                    added = True
                    txlat.stamp(key, "admit")
            else:
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
        if added:
            # callbacks run OUTSIDE self._lock: a txs-available listener
            # that re-enters the mempool (or grabs its own lock) must not
            # nest under the admission lock
            for fn in self._notify:
                fn()
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]:
        with self._lock:
            out, total_b, total_g = [], 0, 0
            for info in self._list:
                # amino/proto overhead bound per tx, as the reference reaps
                nb = total_b + len(info["tx"]) + 20
                ng = total_g + max(info["gas_wanted"], 0)
                if max_bytes > -1 and nb > max_bytes:
                    break
                if max_gas > -1 and ng > max_gas:
                    break
                total_b, total_g = nb, ng
                out.append(info["tx"])
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [i["tx"] for i in self._list]
            return txs if n < 0 else txs[:n]

    def front(self) -> Optional[CElement]:
        """Front element for cursor-based gossip (TxsFront)."""
        return self._list.front()

    def wait_front(self, timeout: float | None = None) -> Optional[CElement]:
        """Block until the mempool is non-empty (TxsWaitChan)."""
        return self._list.wait_chan(timeout)

    def lock(self) -> None:
        self._update_lock.acquire()

    def unlock(self) -> None:
        self._update_lock.release()

    def update(self, height: int, txs: List[bytes], deliver_tx_responses
               ) -> None:
        """Remove committed txs; recheck the rest (clist_mempool.go:435).
        Caller must hold lock()."""
        with self._lock:
            self._height = height
            for tx, res in zip(txs, deliver_tx_responses):
                key = tmhash.sum(tx)
                if res.is_ok():
                    self.cache.push(tx)  # committed: keep in cache forever-ish
                    self._note_committed(key)
                elif not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
                el = self._txs.pop(key, None)
                if el is not None:
                    self._list.remove(el)
                    self._txs_bytes -= len(el.value["tx"])
        # recheck runs on a background worker (clist_mempool.go:435
        # recheckTxs fires ASYNC CheckTx requests): a synchronous loop here
        # would hold the consensus thread — and the shared app mutex — for
        # mempool-size ABCI round-trips per commit, which under tx load
        # starves vote/proposal processing and livelocks rounds
        self._schedule_recheck()
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def _recheck_pass(self) -> None:
        """Re-validate survivors as ONE pipelined async batch (N queued
        requests + one flush) instead of N serial sync round trips — at
        5k txs the serial loop held the shared app mutex for the whole
        sweep and starved CheckTx admission."""
        with self._lock:
            remaining = [i["tx"] for i in self._list]
        if not remaining:
            return
        responses = pipelined_check_tx(self.proxy_app, [
            abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_RECHECK)
            for tx in remaining])
        for tx, res in zip(remaining, responses):
            if not res.is_ok():
                with self._lock:
                    el = self._txs.pop(tmhash.sum(tx), None)
                    if el is not None:
                        self._list.remove(el)
                        self._txs_bytes -= len(el.value["tx"])
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)

    def flush(self) -> None:
        with self._lock:
            for el in list(self._txs.values()):
                self._list.remove(el)
            self._txs.clear()
            self._txs_bytes = 0
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(0)

    def flush_app_conn(self) -> None:
        self.proxy_app.flush_sync()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._lock:
            return self._txs_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def txs_available(self, fn: Callable) -> None:
        """Register a new-tx notification (EnableTxsAvailable analogue)."""
        self._notify.append(fn)

    def mark_sender(self, tx: bytes, sender) -> None:
        with self._lock:
            el = self._txs.get(tmhash.sum(tx))
            if el is not None:
                el.value["senders"].add(sender)

    def senders(self, tx: bytes) -> set:
        with self._lock:
            el = self._txs.get(tmhash.sum(tx))
            return set(el.value["senders"]) if el else set()
