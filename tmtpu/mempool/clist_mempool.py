"""Mempool v0 — FIFO with tx cache (reference: mempool/v0/clist_mempool.go).

CheckTx goes through the mempool ABCI connection; committed txs are removed
and the remainder re-checked on update (:435), exactly the reference's
lifecycle. Storage is the wait-chan concurrent list (``libs/clist.py``), exactly the
reference's core structure: broadcast routines hold a CElement cursor and
block on ``next_wait`` — no rescans, no mempool-lock contention with
CheckTx/reap on the hot path. A hash→element map provides O(1) dedup and
removal.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, List, Optional

from tmtpu.abci import types as abci
from tmtpu.crypto import tmhash
from tmtpu.libs.clist import CElement, CList


class TxInMempoolError(Exception):
    pass


class MempoolFullError(Exception):
    pass


class TxCache:
    """LRU of tx hashes (mempool/cache.go)."""

    def __init__(self, size: int):
        self.size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def push(self, tx: bytes) -> bool:
        key = tmhash.sum(tx)
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._map.pop(tmhash.sum(tx), None)


class AsyncRecheckMixin:
    """Shared async-recheck machinery (clist_mempool.go:435 recheckTxs
    fires async CheckTx requests — a synchronous loop would hold the
    consensus thread for mempool-size ABCI round-trips per commit).
    Subclasses implement ``_recheck_pass()``. The running/dirty flags are
    decided under one mutex so a scheduling racing a worker's exit can't
    be lost."""

    def _init_recheck(self) -> None:
        self._recheck_dirty = False
        self._recheck_running = False
        self._recheck_mtx = threading.Lock()

    def _schedule_recheck(self) -> None:
        with self._recheck_mtx:
            self._recheck_dirty = True
            if self._recheck_running:
                return
            self._recheck_running = True
        threading.Thread(target=self._recheck_worker, daemon=True,
                         name="mempool-recheck").start()

    def _recheck_worker(self) -> None:
        while True:
            with self._recheck_mtx:
                if not self._recheck_dirty:
                    self._recheck_running = False
                    return
                self._recheck_dirty = False
            try:
                self._recheck_pass()
            except Exception:
                with self._recheck_mtx:
                    self._recheck_running = False
                return  # app conn gone (shutdown)
            from tmtpu.libs import metrics as _m

            _m.mempool_size.set(self.size())

    def _recheck_pass(self) -> None:
        raise NotImplementedError


class CListMempool(AsyncRecheckMixin):
    def __init__(self, proxy_app, max_txs: int = 5000,
                 max_txs_bytes: int = 1 << 30, cache_size: int = 10000,
                 keep_invalid_txs_in_cache: bool = False,
                 pre_check: Optional[Callable] = None):
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self.pre_check = pre_check
        self.cache = TxCache(cache_size)
        self._list = CList()  # of info dicts, FIFO
        self._txs: "OrderedDict[bytes, CElement]" = OrderedDict()
        self._txs_bytes = 0
        self._init_recheck()
        self._height = 0
        self._lock = threading.RLock()
        self._update_lock = threading.RLock()  # Lock()/Unlock() surface
        self._notify: List[Callable] = []

    # -- Mempool interface (mempool/mempool.go:30) --------------------------

    def check_tx(self, tx: bytes, cb: Optional[Callable] = None,
                 tx_info: Optional[dict] = None) -> None:
        tx = bytes(tx)
        with self._lock:
            if len(self._txs) >= self.max_txs or \
                    self._txs_bytes + len(tx) > self.max_txs_bytes:
                raise MempoolFullError(
                    f"mempool is full: {len(self._txs)} txs")
            if not self.cache.push(tx):
                raise TxInMempoolError("tx already exists in cache")
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err is not None:
                self.cache.remove(tx)
                raise ValueError(f"pre-check failed: {err}")
        res = self.proxy_app.check_tx_sync(abci.RequestCheckTx(
            tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        self._resolve_check_tx(tx, res, tx_info or {})
        if cb is not None:
            cb(res)

    def _resolve_check_tx(self, tx: bytes, res: abci.ResponseCheckTx,
                          tx_info: dict) -> None:
        key = tmhash.sum(tx)
        with self._lock:
            if res.is_ok():
                if key not in self._txs:
                    info = {
                        "tx": tx, "gas_wanted": res.gas_wanted,
                        "height": self._height,
                        "senders": set(filter(None, [tx_info.get("sender")])),
                    }
                    self._txs[key] = self._list.push_back(info)
                    self._txs_bytes += len(tx)
                    for fn in self._notify:
                        fn()
            else:
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]:
        with self._lock:
            out, total_b, total_g = [], 0, 0
            for info in self._list:
                # amino/proto overhead bound per tx, as the reference reaps
                nb = total_b + len(info["tx"]) + 20
                ng = total_g + max(info["gas_wanted"], 0)
                if max_bytes > -1 and nb > max_bytes:
                    break
                if max_gas > -1 and ng > max_gas:
                    break
                total_b, total_g = nb, ng
                out.append(info["tx"])
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [i["tx"] for i in self._list]
            return txs if n < 0 else txs[:n]

    def front(self) -> Optional[CElement]:
        """Front element for cursor-based gossip (TxsFront)."""
        return self._list.front()

    def wait_front(self, timeout: float | None = None) -> Optional[CElement]:
        """Block until the mempool is non-empty (TxsWaitChan)."""
        return self._list.wait_chan(timeout)

    def lock(self) -> None:
        self._update_lock.acquire()

    def unlock(self) -> None:
        self._update_lock.release()

    def update(self, height: int, txs: List[bytes], deliver_tx_responses
               ) -> None:
        """Remove committed txs; recheck the rest (clist_mempool.go:435).
        Caller must hold lock()."""
        with self._lock:
            self._height = height
            for tx, res in zip(txs, deliver_tx_responses):
                if res.is_ok():
                    self.cache.push(tx)  # committed: keep in cache forever-ish
                elif not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
                key = tmhash.sum(tx)
                el = self._txs.pop(key, None)
                if el is not None:
                    self._list.remove(el)
                    self._txs_bytes -= len(el.value["tx"])
        # recheck runs on a background worker (clist_mempool.go:435
        # recheckTxs fires ASYNC CheckTx requests): a synchronous loop here
        # would hold the consensus thread — and the shared app mutex — for
        # mempool-size ABCI round-trips per commit, which under tx load
        # starves vote/proposal processing and livelocks rounds
        self._schedule_recheck()
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def _recheck_pass(self) -> None:
        with self._lock:
            remaining = [i["tx"] for i in self._list]
        for tx in remaining:
            res = self.proxy_app.check_tx_sync(abci.RequestCheckTx(
                tx=tx, type=abci.CHECK_TX_TYPE_RECHECK))
            if not res.is_ok():
                with self._lock:
                    el = self._txs.pop(tmhash.sum(tx), None)
                    if el is not None:
                        self._list.remove(el)
                        self._txs_bytes -= len(el.value["tx"])
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)

    def flush(self) -> None:
        with self._lock:
            for el in list(self._txs.values()):
                self._list.remove(el)
            self._txs.clear()
            self._txs_bytes = 0
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(0)

    def flush_app_conn(self) -> None:
        self.proxy_app.flush_sync()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._lock:
            return self._txs_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def txs_available(self, fn: Callable) -> None:
        """Register a new-tx notification (EnableTxsAvailable analogue)."""
        self._notify.append(fn)

    def mark_sender(self, tx: bytes, sender) -> None:
        with self._lock:
            el = self._txs.get(tmhash.sum(tx))
            if el is not None:
                el.value["senders"].add(sender)

    def senders(self, tx: bytes) -> set:
        with self._lock:
            el = self._txs.get(tmhash.sum(tx))
            return set(el.value["senders"]) if el else set()
