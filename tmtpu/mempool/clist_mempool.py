"""Mempool v0 — FIFO with tx cache (reference: mempool/v0/clist_mempool.go).

CheckTx goes through the mempool ABCI connection; committed txs are removed
and the remainder re-checked on update (:435), exactly the reference's
lifecycle. The concurrent-linked-list becomes an OrderedDict under one lock
(Python's list/dict are already thread-safe under the GIL for our access
pattern; the lock covers compound ops).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, List, Optional

from tmtpu.abci import types as abci
from tmtpu.crypto import tmhash


class TxInMempoolError(Exception):
    pass


class MempoolFullError(Exception):
    pass


class TxCache:
    """LRU of tx hashes (mempool/cache.go)."""

    def __init__(self, size: int):
        self.size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def push(self, tx: bytes) -> bool:
        key = tmhash.sum(tx)
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._map.pop(tmhash.sum(tx), None)


class CListMempool:
    def __init__(self, proxy_app, max_txs: int = 5000,
                 max_txs_bytes: int = 1 << 30, cache_size: int = 10000,
                 keep_invalid_txs_in_cache: bool = False,
                 pre_check: Optional[Callable] = None):
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self.pre_check = pre_check
        self.cache = TxCache(cache_size)
        self._txs: "OrderedDict[bytes, dict]" = OrderedDict()  # hash -> info
        self._txs_bytes = 0
        self._height = 0
        self._lock = threading.RLock()
        self._update_lock = threading.RLock()  # Lock()/Unlock() surface
        self._notify: List[Callable] = []

    # -- Mempool interface (mempool/mempool.go:30) --------------------------

    def check_tx(self, tx: bytes, cb: Optional[Callable] = None,
                 tx_info: Optional[dict] = None) -> None:
        tx = bytes(tx)
        with self._lock:
            if len(self._txs) >= self.max_txs or \
                    self._txs_bytes + len(tx) > self.max_txs_bytes:
                raise MempoolFullError(
                    f"mempool is full: {len(self._txs)} txs")
            if not self.cache.push(tx):
                raise TxInMempoolError("tx already exists in cache")
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err is not None:
                self.cache.remove(tx)
                raise ValueError(f"pre-check failed: {err}")
        res = self.proxy_app.check_tx_sync(abci.RequestCheckTx(
            tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        self._resolve_check_tx(tx, res, tx_info or {})
        if cb is not None:
            cb(res)

    def _resolve_check_tx(self, tx: bytes, res: abci.ResponseCheckTx,
                          tx_info: dict) -> None:
        key = tmhash.sum(tx)
        with self._lock:
            if res.is_ok():
                if key not in self._txs:
                    self._txs[key] = {
                        "tx": tx, "gas_wanted": res.gas_wanted,
                        "height": self._height,
                        "senders": set(filter(None, [tx_info.get("sender")])),
                    }
                    self._txs_bytes += len(tx)
                    for fn in self._notify:
                        fn()
            else:
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]:
        with self._lock:
            out, total_b, total_g = [], 0, 0
            for info in self._txs.values():
                # amino/proto overhead bound per tx, as the reference reaps
                nb = total_b + len(info["tx"]) + 20
                ng = total_g + max(info["gas_wanted"], 0)
                if max_bytes > -1 and nb > max_bytes:
                    break
                if max_gas > -1 and ng > max_gas:
                    break
                total_b, total_g = nb, ng
                out.append(info["tx"])
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [i["tx"] for i in self._txs.values()]
            return txs if n < 0 else txs[:n]

    def lock(self) -> None:
        self._update_lock.acquire()

    def unlock(self) -> None:
        self._update_lock.release()

    def update(self, height: int, txs: List[bytes], deliver_tx_responses
               ) -> None:
        """Remove committed txs; recheck the rest (clist_mempool.go:435).
        Caller must hold lock()."""
        with self._lock:
            self._height = height
            for tx, res in zip(txs, deliver_tx_responses):
                if res.is_ok():
                    self.cache.push(tx)  # committed: keep in cache forever-ish
                elif not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
                key = tmhash.sum(tx)
                info = self._txs.pop(key, None)
                if info is not None:
                    self._txs_bytes -= len(info["tx"])
            remaining = [i["tx"] for i in self._txs.values()]
        # recheck outside the map lock (sync for simplicity; small mempools)
        for tx in remaining:
            res = self.proxy_app.check_tx_sync(abci.RequestCheckTx(
                tx=tx, type=abci.CHECK_TX_TYPE_RECHECK))
            if not res.is_ok():
                with self._lock:
                    info = self._txs.pop(tmhash.sum(tx), None)
                    if info is not None:
                        self._txs_bytes -= len(info["tx"])
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(tx)
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(self.size())

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._txs_bytes = 0
        from tmtpu.libs import metrics as _m

        _m.mempool_size.set(0)

    def flush_app_conn(self) -> None:
        self.proxy_app.flush_sync()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._lock:
            return self._txs_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def txs_available(self, fn: Callable) -> None:
        """Register a new-tx notification (EnableTxsAvailable analogue)."""
        self._notify.append(fn)

    def mark_sender(self, tx: bytes, sender) -> None:
        with self._lock:
            info = self._txs.get(tmhash.sum(tx))
            if info is not None:
                info["senders"].add(sender)

    def senders(self, tx: bytes) -> set:
        with self._lock:
            info = self._txs.get(tmhash.sum(tx))
            return set(info["senders"]) if info else set()
