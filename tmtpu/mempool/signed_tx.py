"""Signed-tx envelope for batched CheckTx verification.

The apps in this tree (kvstore/counter) do no signature checks, so
CheckTx signature cost historically didn't exist — and neither did the
throughput win of batching it. This envelope gives load generators and
signature-carrying workloads a standard wrapper the mempool verifies
BEFORE the ABCI round trip, through the same ``crypto/batch.py`` →
sidecar → mesh stack consensus votes use (sigcache-fronted,
breaker-protected, one flush per gather window instead of one
``verify_signature`` per tx on the admission path).

Wire layout (ed25519 only for now; the multi-curve registry can extend
the curve byte later)::

    MAGIC(4) | curve(1)=0x01 | pubkey(32) | sig(64) | payload

The signature covers ``sign_bytes(payload)`` — domain-separated so an
envelope signature can never be replayed as a vote/proposal signature.
Txs that don't start with MAGIC are plain txs and bypass verification
entirely; txs that start with MAGIC but don't parse are rejected at
admission (a malformed envelope is an attack surface, not a payload).
"""

from __future__ import annotations

from typing import Optional, Tuple

from tmtpu.crypto.ed25519 import (
    PUB_KEY_SIZE, SIGNATURE_SIZE, PrivKeyEd25519, PubKeyEd25519,
)
from tmtpu.crypto.keys import PubKey

MAGIC = b"\xd4TX1"
CURVE_ED25519 = 0x01
_HEADER = len(MAGIC) + 1 + PUB_KEY_SIZE + SIGNATURE_SIZE
_DOMAIN = b"tmtpu/signed-tx/v1\x00"


def sign_bytes(payload: bytes) -> bytes:
    """The message the envelope signature covers."""
    return _DOMAIN + payload


def is_signed(tx: bytes) -> bool:
    """True when the tx claims to be an envelope (starts with MAGIC) —
    it may still fail to parse, which is a rejection, not a plain tx."""
    return tx[:len(MAGIC)] == MAGIC


def encode(payload: bytes, priv: PrivKeyEd25519) -> bytes:
    pk = priv.pub_key().bytes()
    sig = priv.sign(sign_bytes(payload))
    return MAGIC + bytes([CURVE_ED25519]) + pk + sig + bytes(payload)


def parse(tx: bytes) -> Optional[Tuple[PubKey, bytes, bytes]]:
    """(pubkey, sig, payload) for a well-formed envelope, None for a
    malformed one. Callers gate on ``is_signed`` first; plain txs never
    reach here."""
    if len(tx) < _HEADER or tx[:len(MAGIC)] != MAGIC:
        return None
    if tx[len(MAGIC)] != CURVE_ED25519:
        return None
    off = len(MAGIC) + 1
    pk_bytes = tx[off:off + PUB_KEY_SIZE]
    sig = tx[off + PUB_KEY_SIZE:off + PUB_KEY_SIZE + SIGNATURE_SIZE]
    payload = tx[_HEADER:]
    try:
        pub = PubKeyEd25519(pk_bytes)
    except ValueError:
        return None
    return pub, bytes(sig), bytes(payload)


def payload(tx: bytes) -> bytes:
    """The app-visible payload: the envelope body for signed txs, the tx
    itself otherwise. (The ABCI app still receives the FULL tx bytes —
    block inclusion and tx hashes cover the envelope — this helper is
    for harnesses that want to reason about the inner payload.)"""
    if is_signed(tx) and len(tx) >= _HEADER:
        return tx[_HEADER:]
    return tx
