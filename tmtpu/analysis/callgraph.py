"""Interprocedural held-lock/call-graph engine for the deep analyzers.

The three deep rules (lock-order, blocking-under-lock,
replay-determinism) all need the same expensive facts:

- which ``self.attr`` (and module-level) names are locks, what kind
  (plain vs reentrant), and which Conditions alias which locks;
- what a method *transitively* does — locks acquired, interesting call
  sites hit — across same-class helpers, inherited mixin methods, typed
  attribute calls (``self.attr = ClassName(...)``), and name-unique
  method resolution when the receiver's type is unknown;
- which locks are held at each of those points.

``Analyzer`` computes memoized per-method event summaries over the
shared ``RepoIndex``. Events are (kind, label, held-locks, file, line,
call-chain) tuples; rules plug in a ``marker_fn`` that labels the AST
nodes they care about (ABCI sync calls, wall-clock reads, set
iteration, ...) and consume the transitive event stream.

Resolution is deliberately conservative-but-useful:

- ``self.m()`` resolves through the context class and its bases (so
  mixin methods analyze under the class that actually runs them);
- ``self.attr.m()`` resolves through ``attr``'s constructor type when
  ``__init__`` assigned a known class, else falls back to name lookup;
- any other ``x.m()`` / bare ``f()`` resolves only when at most
  ``max_candidates`` classes/functions define that name — common names
  (``get``, ``update``, ...) are skipped rather than guessed.

Cycles return empty summaries (no fixpoint needed for flagging) and
``max_depth`` bounds the chain. Findings therefore UNDER-approximate:
absence of a finding is not proof, but every finding has a concrete
witness chain.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from tmtpu.analysis.index import ClassInfo, RepoIndex

# constructors that produce locks: threading primitives and the
# libs/sync factories (Mutex -> Lock, RMutex -> RLock)
PLAIN_LOCK_CTORS = {"Lock", "Mutex", "Semaphore", "BoundedSemaphore"}
REENTRANT_LOCK_CTORS = {"RLock", "RMutex"}
CONDITION_CTORS = {"Condition"}


@dataclass(frozen=True)
class Event:
    kind: str                    # "acquire" | "marker"
    label: str                   # lock id, or marker_fn's label
    held: FrozenSet[str]         # lock ids held at this point
    rel: str
    line: int
    chain: Tuple[str, ...]       # call chain, outermost first

    def via(self) -> str:
        return " -> ".join(self.chain)


class Analyzer:
    def __init__(self, index: RepoIndex, prefixes: Tuple[str, ...] = ("tmtpu",),
                 marker_fn: Optional[Callable[[ast.AST], Optional[str]]] = None,
                 max_candidates: int = 3, max_depth: int = 10):
        self.index = index
        self.prefixes = prefixes
        self.marker_fn = marker_fn or (lambda node: None)
        self.max_candidates = max_candidates
        self.max_depth = max_depth
        self._classes = index.classes(*prefixes)
        self._functions_by_name = self._build_function_table()
        self._methods_by_name: Dict[str, List[ClassInfo]] = {}
        for cls in self._classes:
            for m in cls.methods:
                self._methods_by_name.setdefault(m, []).append(cls)
        self._lock_tables: Dict[int, Tuple[dict, dict]] = {}
        self._module_locks = self._build_module_locks()
        self._method_table: Dict[int, Dict[str, Tuple[ClassInfo,
                                                      ast.FunctionDef]]] = {}
        self._events_memo: Dict[Tuple[int, str], List[Event]] = {}
        self._in_progress: set = set()

    # ----------------------------------------------------------- tables

    def _build_function_table(self):
        out: Dict[str, List[Tuple[str, ast.FunctionDef]]] = {}
        for fi in self.index.files(*self.prefixes):
            if fi.tree is None:
                continue
            for node in fi.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.setdefault(node.name, []).append((fi.rel, node))
        return out

    @staticmethod
    def _ctor_name(call: ast.Call) -> str:
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return ""

    def _build_module_locks(self) -> Dict[Tuple[str, str], str]:
        """{(rel, name): kind} for module-level ``NAME = Lock()``."""
        out = {}
        for fi in self.index.files(*self.prefixes):
            if fi.tree is None:
                continue
            for node in fi.tree.body:
                if not (isinstance(node, ast.Assign) and
                        isinstance(node.value, ast.Call)):
                    continue
                ctor = self._ctor_name(node.value)
                kind = ("plain" if ctor in PLAIN_LOCK_CTORS else
                        "reentrant" if ctor in REENTRANT_LOCK_CTORS else
                        None)
                if kind is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[(fi.rel, tgt.id)] = kind
        return out

    def lock_table(self, cls: ClassInfo) -> Tuple[Dict[str, str],
                                                  Dict[str, str]]:
        """(locks, aliases) for a context class: ``locks`` maps lock
        attr -> kind ("plain"/"reentrant"/"condition"); ``aliases`` maps
        Condition attrs wrapping another lock attr to that attr. Base
        classes' assignments are folded in (mixin locks analyze under
        the running class)."""
        key = id(cls)
        if key in self._lock_tables:
            return self._lock_tables[key]
        locks: Dict[str, str] = {}
        aliases: Dict[str, str] = {}
        for owner in self._mro(cls):
            for fn in owner.methods.values():
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Assign) and
                            isinstance(node.value, ast.Call)):
                        continue
                    ctor = self._ctor_name(node.value)
                    attrs = [t.attr for t in node.targets
                             if isinstance(t, ast.Attribute) and
                             isinstance(t.value, ast.Name) and
                             t.value.id == "self"]
                    if not attrs:
                        continue
                    if ctor in PLAIN_LOCK_CTORS:
                        for a in attrs:
                            locks.setdefault(a, "plain")
                    elif ctor in REENTRANT_LOCK_CTORS:
                        for a in attrs:
                            locks.setdefault(a, "reentrant")
                    elif ctor in CONDITION_CTORS:
                        wrapped = None
                        if node.value.args:
                            arg = node.value.args[0]
                            if isinstance(arg, ast.Attribute) and \
                                    isinstance(arg.value, ast.Name) and \
                                    arg.value.id == "self":
                                wrapped = arg.attr
                        for a in attrs:
                            if wrapped:
                                aliases.setdefault(a, wrapped)
                            else:
                                locks.setdefault(a, "condition")
        self._lock_tables[key] = (locks, aliases)
        return locks, aliases

    def _mro(self, cls: ClassInfo) -> List[ClassInfo]:
        """Approximate MRO by simple base names, cycle-safe."""
        out, seen, frontier = [], set(), [cls]
        while frontier:
            c = frontier.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            out.append(c)
            for base in c.base_names:
                frontier.extend(self._classes_named(base))
        return out

    def _classes_named(self, name: str) -> List[ClassInfo]:
        return [c for c in self._classes if c.name == name]

    def methods_of(self, cls: ClassInfo
                   ) -> Dict[str, Tuple[ClassInfo, ast.FunctionDef]]:
        """Own + inherited methods by name; own definitions win."""
        key = id(cls)
        if key not in self._method_table:
            table: Dict[str, Tuple[ClassInfo, ast.FunctionDef]] = {}
            for owner in self._mro(cls):
                for name, fn in owner.methods.items():
                    table.setdefault(name, (owner, fn))
            self._method_table[key] = table
        return self._method_table[key]

    def lock_id(self, cls: ClassInfo, attr: str) -> str:
        return f"{cls.name}.{attr}"

    # -------------------------------------------------------- resolution

    def resolve_lock(self, cls: ClassInfo, rel: str, expr: ast.AST
                     ) -> Optional[str]:
        """Lock id a ``with``-context expression acquires, if known."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            locks, aliases = self.lock_table(cls)
            attr = aliases.get(expr.attr, expr.attr)
            if attr in locks:
                return self.lock_id(cls, attr)
        elif isinstance(expr, ast.Name):
            if (rel, expr.id) in self._module_locks:
                return f"{rel}::{expr.id}"
        return None

    def lock_kind(self, cls: ClassInfo, lock_id: str) -> Optional[str]:
        if "::" in lock_id:
            rel, name = lock_id.split("::", 1)
            return self._module_locks.get((rel, name))
        cname, _, attr = lock_id.partition(".")
        if cname == cls.name:
            return self.lock_table(cls)[0].get(attr)
        for c in self._classes_named(cname):
            kind = self.lock_table(c)[0].get(attr)
            if kind:
                return kind
        return None

    def resolve_call(self, cls: Optional[ClassInfo], call: ast.Call
                     ) -> List[Tuple[Optional[ClassInfo], ast.FunctionDef,
                                     str]]:
        """Callee frames for one call node: [(context class or None,
        fn node, rel)]. Empty when unknown/too ambiguous."""
        fn = call.func
        # self.m(...)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and cls is not None:
            target = self.methods_of(cls).get(fn.attr)
            if target is not None:
                owner, node = target
                return [(cls, node, owner.rel)]  # keep calling context
            return []
        # self.attr.m(...) with a constructor-typed attr
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Attribute) and \
                isinstance(fn.value.value, ast.Name) and \
                fn.value.value.id == "self" and cls is not None:
            ctor = cls.attr_ctors.get(fn.value.attr)
            if ctor:
                for c in self._classes_named(ctor):
                    target = self.methods_of(c).get(fn.attr)
                    if target is not None:
                        owner, node = target
                        return [(c, node, owner.rel)]
        # any other x.m(...): name-unique method resolution
        if isinstance(fn, ast.Attribute):
            cands = self._methods_by_name.get(fn.attr, [])
            if 1 <= len(cands) <= self.max_candidates:
                return [(c, c.methods[fn.attr], c.rel) for c in cands]
            return []
        # bare f(...): module-level functions, name-unique
        if isinstance(fn, ast.Name):
            cands = self._functions_by_name.get(fn.id, [])
            if 1 <= len(cands) <= self.max_candidates:
                return [(None, node, rel) for rel, node in cands]
        return []

    # ------------------------------------------------------------ events

    def events(self, cls: Optional[ClassInfo], method: str = "",
               fn: Optional[ast.FunctionDef] = None,
               rel: str = "") -> List[Event]:
        """Transitive event summary for a method (by name, resolved in
        ``cls``'s context) or a loose function node. Held sets and
        chains in the result are relative to this frame's entry."""
        if fn is None:
            assert cls is not None
            target = self.methods_of(cls).get(method)
            if target is None:
                return []
            owner, fn = target
            rel = owner.rel
        memo_key = (id(cls) if cls is not None else 0, fn.name, id(fn))
        if memo_key in self._events_memo:
            return self._events_memo[memo_key]
        if memo_key in self._in_progress or \
                len(self._in_progress) >= self.max_depth * 16:
            return []  # cycle / runaway: stop summarizing this path
        self._in_progress.add(memo_key)
        try:
            events = self._walk(cls, fn, rel)
        finally:
            self._in_progress.discard(memo_key)
        self._events_memo[memo_key] = events
        return events

    def _walk(self, cls: Optional[ClassInfo], fn: ast.FunctionDef,
              rel: str) -> List[Event]:
        frame = f"{cls.name}.{fn.name}" if cls is not None else fn.name
        out: List[Event] = []
        seen: set = set()

        def emit(kind, label, held, e_rel, line, chain):
            ev = Event(kind, label, frozenset(held), e_rel, line,
                       (frame,) + chain)
            dkey = (ev.kind, ev.label, ev.held, ev.rel, ev.line)
            if dkey not in seen:
                seen.add(dkey)
                out.append(ev)

        def handle_call(node: ast.Call, held: Tuple[str, ...]):
            label = self.marker_fn(node)
            if label is not None:
                emit("marker", label, held, rel, node.lineno, ())
                return
            # .acquire() on a known lock: record the edge (unscoped —
            # the held set is not extended past this statement)
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "acquire" and \
                    cls is not None:
                lid = self.resolve_lock(cls, rel, f.value)
                if lid is not None:
                    emit("acquire", lid, held, rel, node.lineno, ())
                    return
            for sub_cls, sub_fn, sub_rel in self.resolve_call(cls, node):
                if len(self._in_progress) >= self.max_depth:
                    continue
                for ev in self.events(sub_cls, fn=sub_fn, rel=sub_rel):
                    emit(ev.kind, ev.label, set(held) | set(ev.held),
                         ev.rel, ev.line, ev.chain)

        def visit(node: ast.AST, held: Tuple[str, ...]):
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    lid = self.resolve_lock(cls, rel, item.context_expr) \
                        if cls is not None else None
                    if lid is None and isinstance(item.context_expr,
                                                  ast.Name):
                        lid = self.resolve_lock(cls or _NO_CLS, rel,
                                                item.context_expr)
                    if lid is not None:
                        emit("acquire", lid, held, rel, node.lineno, ())
                        acquired.append(lid)
                    else:
                        visit(item.context_expr, held)
                inner = held + tuple(acquired)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                handle_call(node, held)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            if isinstance(node, (ast.For, ast.comprehension)):
                label = self.marker_fn(node)
                if label is not None:
                    emit("marker", label, held, rel,
                         getattr(node, "lineno",
                                 getattr(node.iter, "lineno", 0)), ())
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs run later, on unknown threads
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())
        return out


class _NoClass:
    """Sentinel context for module-level lock resolution."""
    name = ""
    attr_ctors: dict = {}


_NO_CLS = None  # module-lock resolution handles Name exprs without a class
