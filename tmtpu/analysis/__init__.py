"""Unified static-analysis engine.

One repo indexer (``index.py``: parsed ASTs, class/method tables, call
resolution, and the shared catalogs of fault sites / metrics / timeline
events / config knobs), a rule registry (``registry.py``) of small
plugins consuming that index and emitting structured ``Finding``s, and a
checked-in baseline (``baseline.py``, ``tools/lint_baseline.json``)
where every grandfathered violation lives with a written justification.

Entry points:

- ``python tools/lint.py`` — the CLI (``--json``, ``--rule``,
  ``--baseline``, ``--changed``).
- ``tests/test_lint.py`` — one indexed tier-1 pass over the full rule
  set plus per-rule synthetic-tree detection fixtures.
- The seven legacy ``tools/check_*.py`` CLIs are thin shims over their
  ported rules.

See docs/ANALYSIS.md for the rule catalog and how to write a rule.
"""

from __future__ import annotations

from tmtpu.analysis.findings import Finding  # noqa: F401
from tmtpu.analysis.index import RepoIndex, default_index  # noqa: F401
from tmtpu.analysis import registry  # noqa: F401


def run_rule(rule_id: str, index: "RepoIndex" = None,
             apply_baseline: bool = True):
    """Run one rule against the (default) repo index and return its NEW
    findings — after baseline suppressions, matching what the CLI would
    fail on. The legacy ``tools/check_*.py`` shims are this call."""
    from tmtpu.analysis import baseline as baseline_mod

    idx = index or default_index()
    results = registry.run(idx, [rule_id])
    findings = results.get(rule_id, [])
    if not apply_baseline:
        return findings
    base = baseline_mod.load(baseline_mod.default_path(idx.root))
    new, _suppressed, _stale = baseline_mod.apply(base, {rule_id: findings})
    return new.get(rule_id, [])
