"""Checked-in lint baseline: grandfathered findings with justifications.

``tools/lint_baseline.json`` holds one entry per rule:

    {"rules": {
        "<rule id>": {"status": "clean"},
        "<rule id>": {"status": "suppressions", "suppressions": [
            {"key": "<finding key>", "reason": "<why this is OK>"}]}}}

Semantics:

- A finding whose ``key`` appears in its rule's suppressions is
  *grandfathered*: tracked, reported under ``--json``, but not a
  failure. Every suppression carries a written reason — that IS the
  whitelist-with-justification workflow.
- A finding with no suppression is NEW and fails the lint.
- A suppression whose key no longer matches any finding is STALE and
  reported as a warning so dead entries get pruned.
- ``status: clean`` records the reviewed expectation that the rule has
  zero findings (the meta-rule requires every rule to carry either
  status).

``tools/lint.py --update-baseline`` rewrites the file from the current
tree, preserving reasons for keys that persist and stamping
``TODO: justify or fix`` on new ones — those must be edited into real
justifications (or fixed) before review.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from tmtpu.analysis.findings import Finding

TODO_REASON = "TODO: justify or fix"


def default_path(root: str) -> str:
    return os.path.join(root, "tools", "lint_baseline.json")


def load(path: str) -> dict:
    """Load a baseline file; a missing file is an empty baseline."""
    if not os.path.isfile(path):
        return {"rules": {}}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or not isinstance(
            data.get("rules", None), dict):
        raise ValueError(f"malformed baseline {path}: expected "
                         f'{{"rules": {{...}}}}')
    return data


def suppression_map(baseline: dict, rule_id: str) -> Dict[str, str]:
    entry = baseline.get("rules", {}).get(rule_id, {})
    return {s["key"]: s.get("reason", "") for s in
            entry.get("suppressions", []) if "key" in s}


def apply(baseline: dict, results: Dict[str, List[Finding]]
          ) -> Tuple[Dict[str, List[Finding]], Dict[str, List[Finding]],
                     Dict[str, List[str]]]:
    """Split raw rule results into (new, suppressed, stale-suppression
    keys) per rule."""
    new: Dict[str, List[Finding]] = {}
    suppressed: Dict[str, List[Finding]] = {}
    stale: Dict[str, List[str]] = {}
    for rid, findings in results.items():
        sup = suppression_map(baseline, rid)
        seen_keys = set()
        for f in findings:
            seen_keys.add(f.key)
            (suppressed if f.key in sup else new).setdefault(
                rid, []).append(f)
        missing = [k for k in sup if k not in seen_keys]
        if missing:
            stale[rid] = missing
    return new, suppressed, stale


def update(baseline: dict, results: Dict[str, List[Finding]]) -> dict:
    """Fold the current results into a fresh baseline: every rule that
    ran gets an entry; existing reasons survive for keys still found;
    new keys get the TODO reason; vanished keys are dropped."""
    out = {"rules": dict(baseline.get("rules", {}))}
    for rid, findings in results.items():
        old = suppression_map(baseline, rid)
        if not findings:
            out["rules"][rid] = {"status": "clean"}
            continue
        sups = []
        for f in sorted(findings, key=lambda f: f.key):
            sups.append({"key": f.key,
                         "reason": old.get(f.key, TODO_REASON)})
        out["rules"][rid] = {"status": "suppressions",
                             "suppressions": sups}
    return out


def save(baseline: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(baseline, fh, indent=2, sort_keys=True)
        fh.write("\n")
