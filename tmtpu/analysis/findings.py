"""Structured lint findings.

A ``Finding`` is one rule violation: rule id, file:line, human message,
severity, and a *stable key* — the identity the baseline matches on.
Keys deliberately omit line numbers (code above a violation moving it
down must not invalidate its suppression); rules build them from the
structural facts of the violation (qualnames, lock pairs, call names).
"""

from __future__ import annotations

from dataclasses import dataclass, field


SEVERITIES = ("error", "warn")


@dataclass
class Finding:
    rule: str
    file: str          # repo-relative, "/"-separated
    message: str
    line: int = 0
    severity: str = "error"
    key: str = ""      # stable identity for baseline matching

    def __post_init__(self):
        if not self.key:
            self.key = f"{self.rule}::{self.file}::{self.message}"
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    def location(self) -> str:
        return f"{self.file}:{self.line}" if self.line else self.file

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "file": self.file, "line": self.line,
            "message": self.message, "severity": self.severity,
            "key": self.key,
        }

    def __str__(self) -> str:
        return f"{self.location()}: [{self.rule}] {self.message}"
