"""Rule registry: each lint rule is a small plugin over the shared index.

A rule is a function ``fn(index: RepoIndex) -> list[Finding]`` registered
with the ``@rule(...)`` decorator. Registration declares:

- ``rule_id`` — stable id (baseline entries and ``--rule`` use it);
- ``doc`` — one-line description (the catalog in docs/ANALYSIS.md and
  ``tools/lint.py --list`` render it);
- ``triggers`` — path prefixes whose changes make the rule worth
  re-running (``tools/lint.py --changed`` intersects these with the
  ``git merge-base`` diff); ``("",)`` means "any change";
- ``requires_import`` — True for rules that import runtime registries
  (scenario library, sidecar protocol) and therefore only run against
  the real repo, never a synthetic fixture tree.

``run()`` executes rules against one index in-process — no per-rule
re-walk, no subprocess spawns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex


@dataclass
class Rule:
    rule_id: str
    fn: Callable[[RepoIndex], List[Finding]]
    doc: str
    triggers: Tuple[str, ...] = ("",)
    requires_import: bool = False


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, *, doc: str, triggers: Sequence[str] = ("",),
         requires_import: bool = False):
    """Register a rule plugin. Rules live in tmtpu/analysis/rules/."""
    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, fn, doc, tuple(triggers),
                              requires_import)
        return fn
    return deco


def load_rules() -> Dict[str, Rule]:
    """Import the rules package (idempotent) and return the registry."""
    from tmtpu.analysis import rules  # noqa: F401  (imports register)

    return RULES


def all_rule_ids() -> List[str]:
    return sorted(load_rules())


def run(index: RepoIndex, rule_ids: Optional[Sequence[str]] = None,
        cache=None, stats: Optional[dict] = None
        ) -> Dict[str, List[Finding]]:
    """Run the requested rules (default: all) against one shared index.
    Returns {rule_id: [findings]} with an entry for every rule that ran
    (empty list = clean). Rules needing runtime imports are skipped
    silently on non-repo indexes (synthetic fixture trees).

    ``cache`` (a ``tmtpu.analysis.cache.ResultCache``) short-circuits
    rules whose fingerprinted file set is unchanged; ``stats``, when a
    dict is passed, is filled with per-rule run metadata
    ``{rid: {"seconds", "findings", "cached"}}``."""
    import time

    rules = load_rules()
    ids = list(rule_ids) if rule_ids is not None else sorted(rules)
    unknown = [i for i in ids if i not in rules]
    if unknown:
        raise KeyError(
            f"unknown rule(s) {unknown}; known: {sorted(rules)}")
    out: Dict[str, List[Finding]] = {}
    for rid in ids:
        r = rules[rid]
        if r.requires_import and not index.importable:
            continue
        t0 = time.perf_counter()
        cached = None
        if cache is not None:
            cached = cache.lookup(rid, index, r.triggers)
        if cached is not None:
            findings = cached
        else:
            findings = list(r.fn(index))
            for f in findings:
                if f.rule != rid:
                    raise ValueError(
                        f"rule {rid!r} emitted a finding tagged "
                        f"{f.rule!r}")
            if cache is not None:
                cache.store(rid, index, r.triggers, findings)
        out[rid] = findings
        if stats is not None:
            stats[rid] = {
                "seconds": round(time.perf_counter() - t0, 4),
                "findings": len(findings),
                "cached": cached is not None,
            }
    return out


def affected_rules(changed_files: Sequence[str]) -> List[str]:
    """Rule ids whose trigger prefixes intersect the changed file set —
    the ``--changed`` pre-commit fast path."""
    rules = load_rules()
    changed = [c.replace("\\", "/") for c in changed_files]
    out = []
    for rid, r in sorted(rules.items()):
        for trig in r.triggers:
            if trig == "":
                out.append(rid)
                break
            if any(c == trig or c.startswith(trig.rstrip("/") + "/")
                   for c in changed):
                out.append(rid)
                break
    return out
