"""Interprocedural forward-dataflow (taint) layer over the callgraph.

The wire-taint rule needs a fact the lock engine cannot express: *which
values* a method touches, not just *what it calls*. This module adds a
taint engine on top of ``callgraph.Analyzer``'s resolution rules
(``self.m()`` via MRO, ``self.attr.m()`` via constructor types,
name-unique fallback — identical conservatism, identical witness
chains):

- **Summaries are label-polymorphic.** ``summarize(cls, fn, tainted)``
  analyzes one method with each tainted parameter carrying *its own
  name* as an abstract label. The memo key is (context class, function,
  tainted-param set), so one summary serves every call site; callers
  substitute the abstract labels with whatever concrete labels their
  arguments carry. Pseudo-params of the form ``self.attr`` seed
  attribute taint the same way (used for channel propagation).

- **Gen:** assignments, tuple unpacking, ``for`` targets, ``with ... as``,
  attribute/subscript/operator composition, and *unresolved* calls all
  propagate taint from operands to results (a decode helper we cannot
  resolve is assumed to return tainted bytes). Stores into ``self.attr``
  (plain assignment or ``.put/.append/.add/...`` on a self attribute)
  are recorded as **attr writes** so callers — and the channel fixpoint
  — can see taint crossing an object boundary.

- **Kill:** a call to a *sanitizer* (``validate_basic``, ``verify_one``,
  the batch-verify family) launders the **whole frame** from that
  statement on. Statically tracking the verified-mask indexing that
  follows a batch verify is out of reach; the invariant this enforces is
  the paper's actual one — *a verification call stands between the wire
  and the sink on every path* — and statement order is exactly how the
  code expresses it.

- **Sinks** are classified by a caller-supplied ``sink_fn(call)``; a
  sink call with tainted arguments (or a tainted receiver) emits a
  ``TaintHit`` with a witness chain, outermost frame first, just like
  the lock engine's events.

- **Channels:** ``propagate(seeds)`` runs the entry summaries, then
  iterates to a bounded fixpoint over a global channel map: any
  ``(class, attr)`` that received tainted writes
  (``self._queue.put(tainted_msg)``) makes every ``self.attr`` read in
  that class's methods yield those labels on the next round, and every
  method of a tainted class becomes an entry (thread loops are entered
  by the runtime, not by calls we can see) — so taint follows the
  reactor-thread → queue → state-thread handoff that every reactor in
  this codebase uses, including through helpers that *return* the
  drained messages. Labels only grow, so the fixpoint terminates.

Like the lock engine, findings UNDER-approximate: sequential processing
of branches means a sanitizer in an early branch launders later code,
and unresolved *receivers* drop attribute taint. Every hit carries a
hand-checkable witness chain.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import (Callable, Dict, FrozenSet, Iterable, List, Optional,
                    Set, Tuple)

from tmtpu.analysis.callgraph import Analyzer
from tmtpu.analysis.index import ClassInfo, RepoIndex

EMPTY: FrozenSet[str] = frozenset()

# receiver methods that store their argument into the receiver's
# collection: self.attr.put(x) taints (class, attr)
_STORE_METHODS = {"put", "put_nowait", "append", "appendleft", "add",
                  "extend", "push", "insert"}
# receiver methods that read an element back out of a collection
_LOAD_METHODS = {"get", "get_nowait", "pop", "popleft"}


@dataclass(frozen=True)
class TaintHit:
    sink: str                    # sink_fn's label, e.g. "tally:add_verified_vote"
    labels: FrozenSet[str]       # taint labels reaching the sink
    rel: str
    line: int
    chain: Tuple[str, ...]       # call chain, outermost first

    def via(self) -> str:
        return " -> ".join(self.chain)


@dataclass(frozen=True)
class Summary:
    """Label-polymorphic effect summary of one (class, fn, tainted) frame."""
    hits: Tuple[TaintHit, ...]                       # sinks reached
    ret: FrozenSet[str]                              # labels flowing to return
    attr_writes: Tuple[Tuple[str, str, FrozenSet[str]], ...]
    # (class_name, attr, labels) stored into self.attr somewhere below
    sanitizes: bool = False
    # frame (transitively) called a sanitizer: a call to this function
    # counts as a verification gate in the caller too


_EMPTY_SUMMARY = Summary((), EMPTY, ())


class TaintAnalyzer:
    """Forward taint propagation along the callgraph's resolution rules."""

    def __init__(self, index: RepoIndex,
                 sink_fn: Callable[[ast.Call], Optional[str]],
                 sanitizers: Set[str],
                 prefixes: Tuple[str, ...] = ("tmtpu",),
                 max_depth: int = 10):
        self.cg = Analyzer(index, prefixes=prefixes, max_depth=max_depth)
        self.sink_fn = sink_fn
        self.sanitizers = set(sanitizers)
        self.max_depth = max_depth
        # global channel taint: (class name, attr) -> concrete labels;
        # consulted at every self.attr read, grown by propagate()
        self.channels: Dict[Tuple[str, str], FrozenSet[str]] = {}
        self._memo: Dict[Tuple[int, int, FrozenSet[str]], Summary] = {}
        self._in_progress: set = set()

    # --------------------------------------------------------- summaries

    def summarize(self, cls: Optional[ClassInfo], fn: ast.FunctionDef,
                  rel: str, tainted: FrozenSet[str]) -> Summary:
        """Effect summary with each tainted param labeled by its own name
        (names of the form ``self.attr`` seed attribute taint)."""
        if not tainted:
            tainted = EMPTY
        key = (id(cls) if cls is not None else 0, id(fn), tainted)
        if key in self._memo:
            return self._memo[key]
        if key in self._in_progress or \
                len(self._in_progress) >= self.max_depth:
            return _EMPTY_SUMMARY      # cycle / depth: stop, under-approximate
        self._in_progress.add(key)
        try:
            summary = _FrameWalk(self, cls, fn, rel, tainted).run()
        finally:
            self._in_progress.discard(key)
        self._memo[key] = summary
        return summary

    def entry_hits(self, cls: Optional[ClassInfo], fn: ast.FunctionDef,
                   rel: str, seeds: Dict[str, str]
                   ) -> Tuple[List[TaintHit],
                              List[Tuple[str, str, FrozenSet[str]]]]:
        """Analyze an entry point with concrete labels: ``seeds`` maps a
        param name (or ``self.attr`` pseudo-param) to a concrete label.
        Returns (hits, attr_writes) with abstract labels substituted."""
        summary = self.summarize(cls, fn, rel, frozenset(seeds))
        subst = {p: frozenset([lbl]) for p, lbl in seeds.items()}
        hits = []
        for h in summary.hits:
            concrete = _substitute(h.labels, subst)
            if concrete:
                hits.append(TaintHit(h.sink, concrete, h.rel, h.line,
                                     h.chain))
        writes = []
        for cname, attr, labels in summary.attr_writes:
            concrete = _substitute(labels, subst)
            if concrete:
                writes.append((cname, attr, concrete))
        return hits, writes

    # ----------------------------------------------------- channel fixpoint

    def propagate(self, seeds: Iterable[Tuple[Optional[ClassInfo],
                                              ast.FunctionDef, str,
                                              Dict[str, str]]],
                  max_rounds: int = 4) -> List[TaintHit]:
        """Run entry seeds to a bounded fixpoint over the channel map.

        Each round analyzes the seeds plus every method of every class
        with a tainted channel (a drained queue may surface anywhere in
        the class — thread loops are entered by the runtime, not by
        calls the callgraph can see). Tainted writes grow the channel
        map; when it stops growing, the hit set is complete. Memoized
        summaries are invalidated between rounds because channel reads
        feed them."""
        seeds = list(seeds)
        hits: List[TaintHit] = []
        hit_keys: set = set()
        for _ in range(max_rounds):
            writes: Dict[Tuple[str, str], Set[str]] = {}
            entries = list(seeds)
            entered = {(id(c) if c else 0, id(f)) for c, f, _, _ in seeds}
            tainted_classes = {cname for (cname, _attr) in self.channels}
            enter_classes: List[ClassInfo] = []
            for cname in tainted_classes:
                enter_classes.extend(self.cg._classes_named(cname))
            # owners too: a class holding `self.pool = BlockPool(...)`
            # drains the pool's channels from its own thread loop
            for cls in self.cg._classes:
                if set(cls.attr_ctors.values()) & tainted_classes:
                    enter_classes.append(cls)
            for cls in enter_classes:
                for mname, (owner, fn) in self.cg.methods_of(cls).items():
                    ekey = (id(cls), id(fn))
                    if ekey in entered:
                        continue
                    entered.add(ekey)
                    entries.append((cls, fn, owner.rel, {}))
            for cls, fn, rel, labels in entries:
                h, w = self.entry_hits(cls, fn, rel, labels)
                for hit in h:
                    k = (hit.sink, hit.labels, hit.rel, hit.chain)
                    if k not in hit_keys:
                        hit_keys.add(k)
                        hits.append(hit)
                for cname, attr, ls in w:
                    writes.setdefault((cname, attr), set()).update(ls)
            grown = False
            for key, ls in writes.items():
                have = self.channels.get(key, EMPTY)
                if not set(ls) <= set(have):
                    self.channels[key] = frozenset(have | ls)
                    grown = True
            if not grown:
                break
            self._memo.clear()   # summaries read the channel map
        return hits


def _substitute(labels: FrozenSet[str],
                subst: Dict[str, FrozenSet[str]]) -> FrozenSet[str]:
    """Map abstract (param-name) labels through ``subst``; concrete
    labels (channel taint like ``wire``) pass through unchanged."""
    out: Set[str] = set()
    for lbl in labels:
        out.update(subst.get(lbl, frozenset((lbl,))))
    return frozenset(out)


def _reads_self_attr(fn: ast.FunctionDef, attr: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == attr and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return True
    return False


class _FrameWalk:
    """One summarization pass over a single function frame.

    ``env`` maps local names and ``self.attr`` paths to abstract label
    sets. Statements are processed in source order; branch bodies run
    sequentially (see module docstring for the soundness trade)."""

    def __init__(self, ta: TaintAnalyzer, cls: Optional[ClassInfo],
                 fn: ast.FunctionDef, rel: str, tainted: FrozenSet[str]):
        self.ta = ta
        self.cls = cls
        self.fn = fn
        self.rel = rel
        self.frame = f"{cls.name}.{fn.name}" if cls is not None else fn.name
        self.env: Dict[str, FrozenSet[str]] = {p: frozenset([p])
                                               for p in tainted}
        # class names whose channel taint this frame's self.attr reads see
        self.self_classes = tuple(c.name for c in ta.cg._mro(cls)) \
            if cls is not None else ()
        self.hits: List[TaintHit] = []
        self.ret: Set[str] = set()
        self.attr_writes: Dict[Tuple[str, str], Set[str]] = {}
        self.sanitized = False

    # ------------------------------------------------------------- run

    def run(self) -> Summary:
        for stmt in self.fn.body:
            self._stmt(stmt)
        writes = tuple((c, a, frozenset(ls))
                       for (c, a), ls in sorted(self.attr_writes.items()))
        return Summary(tuple(self.hits), frozenset(self.ret), writes,
                       self.sanitized)

    # ------------------------------------------------------ statements

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, ast.Assign):
            labels = self._expr(node.value)
            for tgt in node.targets:
                self._bind(tgt, labels)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._bind(node.target, self._expr(node.value))
        elif isinstance(node, ast.AugAssign):
            labels = self._expr(node.value) | self._read_target(node.target)
            self._bind(node.target, labels)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.ret.update(self._expr(node.value))
        elif isinstance(node, ast.Expr):
            self._expr(node.value)
        elif isinstance(node, ast.For):
            self._bind(node.target, self._expr(node.iter))
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, ast.While):
            self._expr(node.test)
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, ast.If):
            self._expr(node.test)
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self._stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
            for s in node.finalbody:
                self._stmt(s)
        elif isinstance(node, ast.With):
            for item in node.items:
                labels = self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, labels)
            for s in node.body:
                self._stmt(s)
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (callback/thread target): taint flows in via the
            # closure — walk its body against a throwaway copy of the env
            # so sinks inside are caught, but its local bindings stay local
            saved = dict(self.env)
            for s in node.body:
                self._stmt(s)
            self.env = saved
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)
                elif isinstance(child, ast.stmt):
                    self._stmt(child)

    def _bind(self, tgt: ast.expr, labels: FrozenSet[str]) -> None:
        if isinstance(tgt, ast.Name):
            if labels:
                self.env[tgt.id] = labels
            else:
                self.env.pop(tgt.id, None)       # kill on clean reassignment
        elif isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            key = f"self.{tgt.attr}"
            if labels:
                self.env[key] = self.env.get(key, EMPTY) | labels
                self._record_attr_write(tgt.attr, labels)
            else:
                self.env.pop(key, None)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._bind(elt, labels)          # coarse: every elt tainted
        elif isinstance(tgt, ast.Subscript):
            # x[k] = tainted: the container becomes tainted
            self._bind(tgt.value, labels | self._read_target(tgt.value))
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, labels)

    def _read_target(self, tgt: ast.expr) -> FrozenSet[str]:
        if isinstance(tgt, ast.Name):
            return self.env.get(tgt.id, EMPTY)
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            return self.env.get(f"self.{tgt.attr}", EMPTY)
        return EMPTY

    def _record_attr_write(self, attr: str, labels: FrozenSet[str]) -> None:
        cname = self.cls.name if self.cls is not None else self.rel
        self.attr_writes.setdefault((cname, attr), set()).update(labels)

    # ----------------------------------------------------- expressions

    def _expr(self, node: ast.expr) -> FrozenSet[str]:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, EMPTY)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                out = self.env.get(f"self.{node.attr}", EMPTY)
                for cname in self.self_classes:
                    out |= self.ta.channels.get((cname, node.attr), EMPTY)
                return out
            return self._expr(node.value)        # field of tainted is tainted
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Lambda,)):
            saved = dict(self.env)
            out = self._expr(node.body)
            self.env = saved
            return out
        out: Set[str] = set()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                out.update(self._expr(child))
        return frozenset(out)

    # ----------------------------------------------------------- calls

    @staticmethod
    def _call_name(call: ast.Call) -> str:
        f = call.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return ""

    def _call(self, call: ast.Call) -> FrozenSet[str]:
        name = self._call_name(call)
        arg_labels = [self._expr(a) for a in call.args]
        kw_labels = {kw.arg: self._expr(kw.value) for kw in call.keywords
                     if kw.arg is not None}
        for kw in call.keywords:
            if kw.arg is None:                   # **kwargs splat
                arg_labels.append(self._expr(kw.value))
        recv_labels = EMPTY
        recv = None
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            recv_labels = self._expr(recv)
        all_labels = frozenset().union(recv_labels, *arg_labels,
                                       *kw_labels.values()) \
            if (arg_labels or kw_labels or recv_labels) else EMPTY

        # sanitizer: verification happened — launder the whole frame from
        # here on (statement order IS the verify-before-sink invariant).
        # The frame-transitive flag is only set when the sanitizer saw
        # tainted data: verifying an unrelated object must not count as
        # a gate for the caller's taint.
        if name in self.ta.sanitizers:
            self.env.clear()
            if all_labels:
                self.sanitized = True
            return EMPTY

        # sink: tainted data reaching a protected mutation
        sink = self.ta.sink_fn(call)
        if sink is not None and all_labels:
            self.hits.append(TaintHit(sink, all_labels, self.rel,
                                      call.lineno, (self.frame,)))

        # collection store: x.put/append(tainted) taints the container —
        # a self attr becomes a channel write, a local just gets tainted
        if recv is not None and name in _STORE_METHODS:
            stored = frozenset().union(*arg_labels) if arg_labels else EMPTY
            if stored and isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                key = f"self.{recv.attr}"
                self.env[key] = self.env.get(key, EMPTY) | stored
                self._record_attr_write(recv.attr, stored)
            elif stored and isinstance(recv, ast.Name):
                self.env[recv.id] = self.env.get(recv.id, EMPTY) | stored

        # resolved callees: substitute through their polymorphic summary
        callees = self.ta.cg.resolve_call(self.cls, call)
        if callees:
            result: Set[str] = set(recv_labels)  # method of tainted object
            sanitizes = False
            for sub_cls, sub_fn, sub_rel in callees:
                labels, sub_sanitizes = self._apply_summary(
                    call, sub_cls, sub_fn, sub_rel, arg_labels, kw_labels)
                result.update(labels)
                sanitizes = sanitizes or sub_sanitizes
            if sanitizes:
                # the callee IS a verification gate (e.g. a wrapper over
                # verify_commits_light_batch): launder this frame too
                self.env.clear()
                self.sanitized = True
                return EMPTY
            return frozenset(result)

        # unresolved: conservative propagation — tainted in, tainted out
        return all_labels

    def _apply_summary(self, call: ast.Call, sub_cls: Optional[ClassInfo],
                       sub_fn: ast.FunctionDef, sub_rel: str,
                       arg_labels: List[FrozenSet[str]],
                       kw_labels: Dict[str, FrozenSet[str]]
                       ) -> Tuple[FrozenSet[str], bool]:
        params = [a.arg for a in sub_fn.args.args]
        is_method = sub_cls is not None and params and params[0] == "self"
        if is_method:
            params = params[1:]
        subst: Dict[str, FrozenSet[str]] = {}
        for i, labels in enumerate(arg_labels):
            if labels and i < len(params):
                subst[params[i]] = labels
        for pname, labels in kw_labels.items():
            if labels and pname in params:
                subst[pname] = subst.get(pname, EMPTY) | labels
        # summarize even with no tainted args: the callee can still pull
        # taint out of a channel (a drained queue) and return it
        summary = self.ta.summarize(sub_cls, sub_fn, sub_rel,
                                    frozenset(subst))
        for h in summary.hits:
            concrete = _substitute(h.labels, subst)
            if concrete:
                self.hits.append(TaintHit(
                    h.sink, concrete, h.rel, h.line,
                    (self.frame,) + h.chain))
        # attr writes below a self.m() call happen on OUR self
        same_self = (isinstance(call.func, ast.Attribute) and
                     isinstance(call.func.value, ast.Name) and
                     call.func.value.id == "self")
        for cname, attr, labels in summary.attr_writes:
            concrete = _substitute(labels, subst)
            if not concrete:
                continue
            self.attr_writes.setdefault((cname, attr), set()).update(concrete)
            if same_self:
                key = f"self.{attr}"
                self.env[key] = self.env.get(key, EMPTY) | concrete
        return _substitute(summary.ret, subst), summary.sanitizes
