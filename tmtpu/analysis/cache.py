"""Incremental lint result cache under ``.lint_cache/``.

Parsing the tree costs ~1s and the rule sweep ~3s; a pre-commit hook
that pays that on every invocation gets disabled by its users. The fix
is NOT caching ASTs (a pickled ``ast.Module`` forest loads *slower*
than re-parsing the source) but caching **per-rule results** keyed by
the per-file fingerprints of everything the rule can read:

- each source file contributes a ``"mtime_ns:size"`` key, recorded
  per (repo-relative) path;
- a rule's file set = the indexed files under its trigger prefixes
  (all files for catch-all triggers), plus the non-Python files on
  disk under those prefixes (the index only parses ``.py``, but rules
  like obs-docs read ``docs/*.md`` — a doc edit must invalidate just
  like a source edit), plus the *infra set* — the analysis framework
  itself (``tmtpu/analysis/``), the lint driver, the baseline, and
  ``docs/ANALYSIS.md`` — so engine or baseline edits invalidate
  everything, conservatively.

A rule's cached findings are reused only when every file key in its
recorded set matches the tree *exactly* (adds, deletes, and edits all
miss). A warm ``--changed`` re-run — same tree, cache populated — does
zero parsing and zero rule work.

The cache is advisory: corrupt or version-skewed files are ignored and
rewritten. It only ever engages for the real repo root (fixture trees
under ``tmp_path`` churn too fast to be worth fingerprinting and must
not write into the repo).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex

CACHE_DIRNAME = ".lint_cache"
CACHE_BASENAME = "results.json"
# bump when Finding serialization or fingerprint semantics change
CACHE_VERSION = 2

# files every rule implicitly depends on (prefixes and exact paths,
# repo-relative): the framework, the driver, the baseline, the docs
# the meta rule reads
INFRA_PREFIXES = ("tmtpu/analysis/",)
INFRA_FILES = ("docs/ANALYSIS.md", "tools/lint.py",
               "tools/lint_baseline.json", "tests/test_lint.py")


def _file_key(path: str) -> Optional[str]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return f"{st.st_mtime_ns}:{st.st_size}"


class ResultCache:
    """Load-once / save-once per-rule finding cache."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.path = os.path.join(self.root, CACHE_DIRNAME, CACHE_BASENAME)
        self._rules: Dict[str, dict] = {}
        self._dirty = False
        try:
            with open(self.path, encoding="utf-8") as fh:
                data = json.load(fh)
            if data.get("version") == CACHE_VERSION and \
                    isinstance(data.get("rules"), dict):
                self._rules = data["rules"]
        except (OSError, ValueError):
            pass

    # --------------------------------------------------------- fingerprint

    def _rule_files(self, index: RepoIndex, triggers) -> List[str]:
        rels = set()
        if "" in triggers:
            rels.update(fi.rel for fi in index.files())
        else:
            for trig in triggers:
                rels.update(fi.rel for fi in index.files(trig))
                rels.update(self._non_py_files(trig))
        for fi in index.files(*INFRA_PREFIXES):
            rels.add(fi.rel)
        rels.update(INFRA_FILES)
        return sorted(rels)

    def _non_py_files(self, trig: str) -> List[str]:
        """Non-``.py`` files on disk under a trigger prefix. The index
        only knows Python sources, but a rule whose trigger names
        ``docs`` reads the markdown there — those inputs must be part
        of the fingerprint or a doc edit serves stale findings."""
        top = os.path.join(self.root, trig)
        if os.path.isfile(top):
            return [] if trig.endswith(".py") else [trig]
        out = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if not d.startswith(".") and d != "__pycache__"]
            for name in filenames:
                if name.endswith(".py") or name.startswith("."):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name),
                                      self.root)
                out.append(rel)
        return out

    def _current_keys(self, index: RepoIndex, triggers) -> Dict[str, str]:
        out = {}
        for rel in self._rule_files(index, triggers):
            key = _file_key(os.path.join(self.root, rel))
            if key is not None:
                out[rel] = key
        return out

    # -------------------------------------------------------------- lookup

    def lookup(self, rule_id: str, index: RepoIndex,
               triggers) -> Optional[List[Finding]]:
        """Cached findings for ``rule_id`` iff its file set is unchanged
        (same paths, same mtime/size for each); None on any miss."""
        entry = self._rules.get(rule_id)
        if entry is None:
            return None
        if entry.get("files") != self._current_keys(index, triggers):
            return None
        try:
            return [Finding(**f) for f in entry["findings"]]
        except (TypeError, ValueError, KeyError):
            return None

    def store(self, rule_id: str, index: RepoIndex, triggers,
              findings: List[Finding]) -> None:
        self._rules[rule_id] = {
            "files": self._current_keys(index, triggers),
            "findings": [f.to_dict() for f in findings],
        }
        self._dirty = True

    # --------------------------------------------------------------- save

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"version": CACHE_VERSION, "rules": self._rules},
                          fh, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass                      # advisory: a read-only tree is fine
        self._dirty = False
