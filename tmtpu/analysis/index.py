"""Single-pass repo index shared by every lint rule.

One walk of the tree reads every ``.py`` source exactly once; ASTs parse
lazily and cache per file. On top of that sit:

- a class/method table (``classes()``, ``classes_by_name``,
  ``methods_by_name``) — the raw material for call-graph walks;
- the shared catalogs that used to live scattered across the one-off
  ``tools/check_*.py`` scripts and the scenario engine:
  fault-injection sites (``fault_sites()``), metric definitions parsed
  statically out of ``tmtpu/libs/metrics.py`` (``metric_defs()``),
  timeline event names (``timeline_events()``), trace span names
  (``span_names()``), and config knobs (``config_knobs()``).

The scenario engine's contract checks (tools/scenario_run.py
``--validate`` and the ``scenarios`` rule) and the lint rules all read
these catalogs, so a metric/fault-site/event rename is caught by one
source of truth instead of three regexes drifting apart.

An index is rooted anywhere: ``RepoIndex(tmp_path)`` over a synthetic
tree is how tests/test_lint.py proves each rule detects its failure
mode. ``default_index()`` memoizes the real repo's index per process so
the CLI, the tier-1 test, and the seven shim CLIs share one parse.
"""

from __future__ import annotations

import ast
import os
import re
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SCAN = ("tmtpu", "tools", "tests", "bench.py")

# ---------------------------------------------------------------- catalogs
# (regexes ported verbatim from tools/check_failpoints.py /
#  check_scenarios.py / check_metrics.py so catalog semantics are
#  unchanged by the move)

# unique-name fault registrations (duplicates are findings)
FAULT_REGISTER_RE = re.compile(r"faultinject\.register\(\s*[\"']([^\"']+)[\"']")
# idempotent fault names: repeats fine, coverage still required
FAULT_ENSURE_RE = re.compile(
    r"(?:faultinject\.ensure|fail\.fail_point|(?<![.\w])fail_point)"
    r"\(\s*[\"']([^\"']+)[\"']")
_METRIC_DEF_RE = re.compile(
    r"DEFAULT\.(?:counter|gauge|histogram)\(\s*[\"'](\w+)[\"'],"
    r"\s*[\"'](\w+)[\"']", re.S)
_TIMELINE_CONST_RE = re.compile(r"EVENT_\w+\s*=\s*[\"']([\w.]+)[\"']")
_TIMELINE_RECORD_RE = re.compile(
    r"record\(\s*[^,()]+,\s*[\"']([\w.]+)[\"']", re.S)
_SPAN_RE = re.compile(
    r"""\btrace\.(?:traced|span)\(\s*["']([a-z0-9_.]+)["']""")
METRIC_WRITE_RE = r"\.(?:inc|set|add|observe)\("


class FileInfo:
    """One source file: relpath (/-separated), raw source, lazy AST."""

    __slots__ = ("rel", "path", "source", "_tree", "_parse_error")

    def __init__(self, rel: str, path: str, source: str):
        self.rel = rel
        self.path = path
        self.source = source
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[SyntaxError] = None

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.source)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree  # force the parse attempt
        return self._parse_error

    def line_of(self, pos: int) -> int:
        return self.source.count("\n", 0, pos) + 1


class ClassInfo:
    """One class definition with its method table and simple attr facts."""

    __slots__ = ("rel", "node", "name", "base_names", "methods",
                 "_attr_ctors")

    def __init__(self, rel: str, node: ast.ClassDef):
        self.rel = rel
        self.node = node
        self.name = node.name
        self.base_names: Set[str] = set()
        for base in node.bases:
            if isinstance(base, ast.Name):
                self.base_names.add(base.id)
            elif isinstance(base, ast.Attribute):
                self.base_names.add(base.attr)
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self._attr_ctors: Optional[Dict[str, str]] = None

    @property
    def attr_ctors(self) -> Dict[str, str]:
        """{attr: CtorName} for every ``self.attr = Name(...)``
        assignment anywhere in the class — the type hints the deep
        analyzers use to follow ``self.attr.method()`` calls."""
        if self._attr_ctors is None:
            out: Dict[str, str] = {}
            for fn in self.methods.values():
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not isinstance(node.value, ast.Call):
                        continue
                    ctor = node.value.func
                    ctor_name = ctor.id if isinstance(ctor, ast.Name) \
                        else (ctor.attr if isinstance(ctor, ast.Attribute)
                              else "")
                    if not ctor_name:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            out[tgt.attr] = ctor_name
            self._attr_ctors = out
        return self._attr_ctors

    def is_subclass_of(self, name: str, index: "RepoIndex") -> bool:
        """Transitive subclass check by simple name (``name`` may also be
        a suffix match like ``Reactor`` matching ``PexReactor`` bases —
        the same contract tools/check_recv_sync.py used)."""
        seen: Set[str] = set()
        frontier = list(self.base_names)
        while frontier:
            base = frontier.pop()
            if base in seen:
                continue
            seen.add(base)
            if base == name or base.endswith(name):
                return True
            for cls in index.classes_by_name.get(base, []):
                frontier.extend(cls.base_names)
        return False


class RepoIndex:
    def __init__(self, root: str = REPO_ROOT,
                 scan: Tuple[str, ...] = DEFAULT_SCAN):
        self.root = os.path.abspath(root)
        self.scan = tuple(scan)
        self._files: Dict[str, FileInfo] = {}
        self._cache: dict = {}
        for entry in self.scan:
            path = os.path.join(self.root, entry)
            if os.path.isfile(path):
                self._load(path)
                continue
            for dirpath, _dirs, files in os.walk(path):
                for f in sorted(files):
                    if f.endswith(".py"):
                        self._load(os.path.join(dirpath, f))

    def _load(self, path: str) -> None:
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                self._files[rel] = FileInfo(rel, path, fh.read())
        except OSError:
            pass

    # ------------------------------------------------------------- files

    def files(self, *prefixes: str) -> Iterator[FileInfo]:
        """Iterate files, optionally filtered to top-level entries or
        path prefixes ("tmtpu", "tmtpu/consensus", "bench.py")."""
        for rel in sorted(self._files):
            fi = self._files[rel]
            if not prefixes:
                yield fi
            elif any(rel == p or rel.startswith(p.rstrip("/") + "/")
                     for p in prefixes):
                yield fi

    def get(self, rel: str) -> Optional[FileInfo]:
        return self._files.get(rel.replace(os.sep, "/"))

    @property
    def importable(self) -> bool:
        """True when this index covers the real repo (rules that must
        import runtime registries — scenario library, sidecar protocol —
        only run then)."""
        try:
            return os.path.samefile(self.root, REPO_ROOT)
        except OSError:
            return False

    # ----------------------------------------------------------- classes

    def classes(self, *prefixes: str) -> List[ClassInfo]:
        key = ("classes", prefixes)
        if key not in self._cache:
            out = []
            for fi in self.files(*prefixes):
                if fi.tree is None:
                    continue
                for node in ast.walk(fi.tree):
                    if isinstance(node, ast.ClassDef):
                        out.append(ClassInfo(fi.rel, node))
            self._cache[key] = out
        return self._cache[key]

    @property
    def classes_by_name(self) -> Dict[str, List[ClassInfo]]:
        if "classes_by_name" not in self._cache:
            out: Dict[str, List[ClassInfo]] = defaultdict(list)
            for cls in self.classes("tmtpu"):
                out[cls.name].append(cls)
            self._cache["classes_by_name"] = dict(out)
        return self._cache["classes_by_name"]

    @property
    def methods_by_name(self) -> Dict[str, List[ClassInfo]]:
        """{method name: [classes defining it]} over tmtpu/ — the
        name-unique call-resolution table the deep analyzers use when a
        receiver's type is unknown."""
        if "methods_by_name" not in self._cache:
            out: Dict[str, List[ClassInfo]] = defaultdict(list)
            for cls in self.classes("tmtpu"):
                for m in cls.methods:
                    out[m].append(cls)
            self._cache["methods_by_name"] = dict(out)
        return self._cache["methods_by_name"]

    # ---------------------------------------------------------- catalogs

    def fault_sites(self) -> Tuple[Dict[str, List[str]],
                                   Dict[str, List[str]]]:
        """(registered, ensured): {site name: ["rel:line", ...]} over
        tmtpu/ — the catalog check_failpoints and the scenario rule
        share. ``register()`` names must be unique; ``ensure``/
        ``fail_point`` names are idempotent but still count toward (and
        are held to) test coverage."""
        if "fault_sites" not in self._cache:
            registered: Dict[str, List[str]] = defaultdict(list)
            ensured: Dict[str, List[str]] = defaultdict(list)
            for fi in self.files("tmtpu"):
                for m in FAULT_REGISTER_RE.finditer(fi.source):
                    registered[m.group(1)].append(
                        f"{fi.rel}:{fi.line_of(m.start())}")
                for m in FAULT_ENSURE_RE.finditer(fi.source):
                    ensured[m.group(1)].append(
                        f"{fi.rel}:{fi.line_of(m.start())}")
            self._cache["fault_sites"] = (dict(registered), dict(ensured))
        return self._cache["fault_sites"]

    def fault_site_names(self) -> Set[str]:
        registered, ensured = self.fault_sites()
        return set(registered) | set(ensured)

    def metric_defs(self) -> Dict[str, str]:
        """{module attr: prometheus name} for every metric bound to a
        module-level name through the DEFAULT registry factories in
        tmtpu/libs/metrics.py — parsed statically (no import), so the
        catalog also works on synthetic trees."""
        if "metric_defs" not in self._cache:
            out: Dict[str, str] = {}
            fi = self.get("tmtpu/libs/metrics.py")
            if fi is not None and fi.tree is not None:
                for node in fi.tree.body:
                    if not (isinstance(node, ast.Assign) and
                            isinstance(node.value, ast.Call)):
                        continue
                    fn = node.value.func
                    if not (isinstance(fn, ast.Attribute) and
                            fn.attr in ("counter", "gauge", "histogram")):
                        continue
                    args = node.value.args
                    if len(args) < 2 or not all(
                            isinstance(a, ast.Constant) and
                            isinstance(a.value, str) for a in args[:2]):
                        continue
                    prom = f"tendermint_{args[0].value}_{args[1].value}"
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = prom
            self._cache["metric_defs"] = out
        return self._cache["metric_defs"]

    def metric_names(self) -> Set[str]:
        """The prometheus-name catalog (``tendermint_<sub>_<name>``) the
        scenario metric oracles must resolve against."""
        if "metric_names" not in self._cache:
            fi = self.get("tmtpu/libs/metrics.py")
            src = fi.source if fi is not None else ""
            self._cache["metric_names"] = {
                f"tendermint_{sub}_{name}"
                for sub, name in _METRIC_DEF_RE.findall(src)}
        return self._cache["metric_names"]

    def timeline_events(self) -> Set[str]:
        """Every timeline event name some code path records (EVENT_*
        constants in libs/timeline.py plus dotted literals at record()
        call sites) — what ``timeline_saw`` oracles may wait for."""
        if "timeline_events" not in self._cache:
            events: Set[str] = set()
            for fi in self.files("tmtpu"):
                if fi.rel.endswith("libs/timeline.py"):
                    events.update(_TIMELINE_CONST_RE.findall(fi.source))
                if "timeline" in fi.source:
                    events.update(
                        e for e in _TIMELINE_RECORD_RE.findall(fi.source)
                        if "." in e)
            self._cache["timeline_events"] = events
        return self._cache["timeline_events"]

    def consensus_step_events(self) -> List[str]:
        """The declared timeline.CONSENSUS_STEP_EVENTS tuple, statically."""
        if "step_events" not in self._cache:
            out: List[str] = []
            fi = self.get("tmtpu/libs/timeline.py")
            if fi is not None and fi.tree is not None:
                for node in fi.tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and
                            t.id == "CONSENSUS_STEP_EVENTS"
                            for t in node.targets):
                        if isinstance(node.value, (ast.Tuple, ast.List)):
                            out = [e.value for e in node.value.elts
                                   if isinstance(e, ast.Constant) and
                                   isinstance(e.value, str)]
            self._cache["step_events"] = out
        return self._cache["step_events"]

    def span_names(self) -> Set[str]:
        """trace.traced("...") / trace.span("...") literals under tmtpu/."""
        if "span_names" not in self._cache:
            names: Set[str] = set()
            for fi in self.files("tmtpu"):
                names.update(_SPAN_RE.findall(fi.source))
            self._cache["span_names"] = names
        return self._cache["span_names"]

    def timeline_record_sites(self) -> Dict[str, str]:
        """{event name: first rel recording it} at record() call sites."""
        if "timeline_record_sites" not in self._cache:
            out: Dict[str, str] = {}
            for fi in self.files("tmtpu"):
                for ev in re.findall(
                        r"""\b(?:timeline|_tl)\.record\(\s*[^,]+,"""
                        r"""\s*["']([a-z0-9_.]+)["']""", fi.source):
                    out.setdefault(ev, fi.rel)
            self._cache["timeline_record_sites"] = out
        return self._cache["timeline_record_sites"]

    def config_knobs(self) -> Dict[str, Set[str]]:
        """{ConfigClass: {attr, ...}} — every ``self.x = ...`` knob in
        tmtpu/config/config.py's *Config classes. Rules (and docs
        tooling) resolve config-key references against this instead of
        re-parsing the file."""
        if "config_knobs" not in self._cache:
            out: Dict[str, Set[str]] = {}
            fi = self.get("tmtpu/config/config.py")
            if fi is not None and fi.tree is not None:
                for node in fi.tree.body:
                    if not (isinstance(node, ast.ClassDef) and
                            node.name.endswith("Config")):
                        continue
                    attrs: Set[str] = set()
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute) and \
                                isinstance(sub.value, ast.Name) and \
                                sub.value.id == "self" and \
                                isinstance(sub.ctx, ast.Store):
                            attrs.add(sub.attr)
                    out[node.name] = attrs
            self._cache["config_knobs"] = out
        return self._cache["config_knobs"]

    def test_corpus(self) -> str:
        """Concatenated tests/ source — coverage checks grep this."""
        if "test_corpus" not in self._cache:
            self._cache["test_corpus"] = "\n".join(
                fi.source for fi in self.files("tests"))
        return self._cache["test_corpus"]


_default: Optional[RepoIndex] = None


def default_index() -> RepoIndex:
    """The memoized real-repo index every entry point shares."""
    global _default
    if _default is None:
        _default = RepoIndex(REPO_ROOT)
    return _default


def reset_default_index() -> None:
    """Drop the memoized index (tests that mutate the tree call this)."""
    global _default
    _default = None
