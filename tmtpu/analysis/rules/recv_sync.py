"""recv-sync rule: no ABCI ``*_sync`` call reachable from Reactor.receive.

Port of tools/check_recv_sync.py. ``receive()`` runs on the peer
connection's recv thread — a synchronous ABCI round trip there queues
every subsequent message from that peer (consensus votes included)
behind the app. The rule walks each Reactor subclass's ``receive`` and
every same-class helper it transitively calls, and flags ABCI sync call
sites.

The old module's hardcoded WHITELIST (the two statesync snapshot-serving
sites) now lives in tools/lint_baseline.json as suppressions — same
keys, same reviewed reasons, one mechanism for every rule.
"""

from __future__ import annotations

import ast
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import ClassInfo, RepoIndex
from tmtpu.analysis.registry import rule

# the ABCI client's synchronous surface (abci/client.py Client) — these
# block for the app's response
ABCI_SYNC_METHODS = {
    "echo_sync", "info_sync", "init_chain_sync", "query_sync",
    "begin_block_sync", "check_tx_sync", "deliver_tx_sync",
    "end_block_sync", "commit_sync", "flush_sync", "list_snapshots_sync",
    "offer_snapshot_sync", "load_snapshot_chunk_sync",
    "apply_snapshot_chunk_sync",
}


def _is_reactor(cls: ClassInfo) -> bool:
    return any(b == "Reactor" or b.endswith("Reactor")
               for b in cls.base_names)


def _self_calls(fn: ast.FunctionDef) -> set:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            out.add(node.func.attr)
    return out


def _sync_sites(fn: ast.FunctionDef) -> list:
    return [(n.func.attr, n.lineno) for n in ast.walk(fn)
            if isinstance(n, ast.Call) and
            isinstance(n.func, ast.Attribute) and
            n.func.attr in ABCI_SYNC_METHODS]


@rule("recv-sync",
      doc="no synchronous ABCI round trip reachable from a Reactor's "
          "receive() (the peer recv thread must enqueue and return)",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    findings = []
    for fi in index.files("tmtpu"):
        if fi.parse_error is not None:
            findings.append(Finding(
                "recv-sync", fi.rel,
                f"syntax error: {fi.parse_error}",
                key=f"recv-sync::syntax::{fi.rel}"))
    for cls in index.classes("tmtpu"):
        if not _is_reactor(cls) or "receive" not in cls.methods:
            continue
        seen, frontier = {"receive"}, ["receive"]
        while frontier:
            name = frontier.pop()
            fn = cls.methods.get(name)
            if fn is None:
                continue  # inherited / dynamic — the blocking-lock
                # rule's interprocedural walk covers those paths
            for attr, lineno in _sync_sites(fn):
                site = f"{cls.rel}::{cls.name}.{name}::{attr}"
                findings.append(Finding(
                    "recv-sync", cls.rel,
                    f"recv-thread sync ABCI call: {site} is reachable "
                    f"from {cls.name}.receive() — enqueue to a worker "
                    f"(e.g. mempool check_tx_nowait) or suppress in the "
                    f"baseline with a reviewed reason",
                    line=lineno, key=site))
            for callee in _self_calls(fn):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
    return sorted(findings, key=lambda f: f.key)
