"""meta rule: every rule is documented, tested, and baselined.

The framework's own hygiene: a rule that exists in the registry but has
no entry in docs/ANALYSIS.md is undiscoverable; one never mentioned in
tests/test_lint.py has no proof it detects its failure mode; one absent
from tools/lint_baseline.json has no reviewed expectation (clean vs
suppressed). Baseline entries for rule ids that no longer exist are
dead weight and flagged too.

Runs against the real repo only (``requires_import``): synthetic
fixture trees legitimately lack docs/tests/baseline.
"""

from __future__ import annotations

import os
from typing import List

from tmtpu.analysis import baseline as baseline_mod
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import all_rule_ids, rule

DOC_PATH = "docs/ANALYSIS.md"
TEST_PATH = "tests/test_lint.py"


@rule("meta",
      doc="every registered rule has a docs/ANALYSIS.md entry, a "
          "tests/test_lint.py mention, and a baseline status; no "
          "baseline entry names an unknown rule",
      triggers=("tmtpu/analysis", "docs", "tools", "tests"),
      requires_import=True)
def check(index: RepoIndex) -> List[Finding]:
    ids = all_rule_ids()
    findings = []

    doc_file = os.path.join(index.root, DOC_PATH)
    doc_src = ""
    if os.path.isfile(doc_file):
        with open(doc_file, encoding="utf-8") as fh:
            doc_src = fh.read()
    else:
        findings.append(Finding(
            "meta", DOC_PATH,
            f"{DOC_PATH} is missing — the rule catalog has no home",
            key="meta::no-doc"))

    test_fi = index.get(TEST_PATH)
    test_src = test_fi.source if test_fi is not None else ""
    if test_fi is None:
        findings.append(Finding(
            "meta", TEST_PATH,
            f"{TEST_PATH} is missing — no rule has detection proof",
            key="meta::no-test"))

    bl = baseline_mod.load(baseline_mod.default_path(index.root))
    bl_rules = bl.get("rules", {})

    for rid in ids:
        if doc_src and f"`{rid}`" not in doc_src:
            findings.append(Finding(
                "meta", DOC_PATH,
                f"rule {rid!r} has no entry in {DOC_PATH} — document "
                f"what it checks and why",
                key=f"meta::doc::{rid}"))
        if test_src and rid not in test_src:
            findings.append(Finding(
                "meta", TEST_PATH,
                f"rule {rid!r} is never mentioned in {TEST_PATH} — add "
                f"a fixture proving it detects its failure mode (or at "
                f"least that it runs clean on the real tree)",
                key=f"meta::test::{rid}"))
        if rid not in bl_rules:
            findings.append(Finding(
                "meta", "tools/lint_baseline.json",
                f"rule {rid!r} has no baseline entry — run tools/"
                f"lint.py --update-baseline and review its status",
                key=f"meta::baseline::{rid}"))
    for rid in sorted(set(bl_rules) - set(ids)):
        findings.append(Finding(
            "meta", "tools/lint_baseline.json",
            f"baseline names unknown rule {rid!r} — the rule was "
            f"removed or renamed; prune the entry",
            key=f"meta::unknown-baseline::{rid}"))
    return findings
