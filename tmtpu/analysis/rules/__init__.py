"""Rule plugins. Importing this package registers every rule.

Each module holds one rule (plus its constants); the registration side
effect happens at import, so ``registry.load_rules()`` importing this
package is the single activation point. Adding a rule = adding a module
here + one import line below (the meta rule then insists on its doc
entry, test coverage, and baseline status).
"""

from tmtpu.analysis.rules import (  # noqa: F401
    blocking_lock,
    determinism,
    exception_safety,
    failpoints,
    jax_hygiene,
    lightserve,
    lock_order,
    meta,
    metrics,
    obs_docs,
    recv_sync,
    scenarios,
    sidecar,
    sigcache,
    timeline,
    wire_taint,
)
