"""exception-safety rule: resources that leak when a call raises.

Four syntactic checks, all per-function/per-class (no interprocedural
walk needed — the leak is visible in the frame that owns the resource):

- **lock-across-raise** — ``x.acquire()`` paired with an ``x.release()``
  that is *not* in a ``finally`` block, with call sites in between that
  can raise: one exception and the lock is held forever. (The ``with``
  statement form is invisible here by construction — that's the fix.)
- **unjoined-thread** — a class stores a worker thread on ``self``
  (``self.x = Thread(...)``), has a shutdown-path method (``stop`` /
  ``on_stop`` / ``close`` / ...), and no method ever joins that thread:
  shutdown returns while the worker still runs, racing teardown.
- **unclosed-resource** — ``open(...)`` / ``socket.socket(...)`` bound
  to a local that is never closed, never returned, never stored, and
  never handed to another call — a guaranteed fd leak on any path.
- **breaker-leak** — a function drives a circuit breaker probe
  (``.allow()`` ... ``.record_success()``) with no failure path
  (``record_failure`` / ``note_failure``): an exception between the two
  strands the breaker half-open. Sites whose *caller* owns the failure
  accounting are baselined with that justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

SHUTDOWN_METHODS = {"stop", "on_stop", "close", "shutdown", "teardown",
                    "stop_sync", "__exit__"}
THREAD_CTORS = {"Thread"}
RESOURCE_CTORS = {"open", "socket"}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001
        return ""


def _functions(index: RepoIndex, prefix: str = "tmtpu"):
    """(rel, qualname, fn) for every module-level function and method."""
    for fi in index.files(prefix):
        if fi.tree is None:
            continue
        for node in fi.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield fi.rel, node.name, node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield fi.rel, f"{node.name}.{sub.name}", sub


# ------------------------------------------------------ lock-across-raise

def _finally_nodes(fn: ast.AST) -> Set[int]:
    """ids of every node nested under a ``finally`` block in ``fn``."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    out.add(id(sub))
    return out


def _check_lock_across_raise(index: RepoIndex) -> List[Finding]:
    findings = []
    for rel, qual, fn in _functions(index):
        in_finally = _finally_nodes(fn)
        acquires: Dict[str, int] = {}        # receiver -> first lineno
        releases: Dict[str, List[Tuple[int, bool]]] = {}
        calls: List[int] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute)):
                continue
            recv = _unparse(node.func.value)
            if node.func.attr == "acquire":
                acquires.setdefault(recv, node.lineno)
            elif node.func.attr == "release":
                releases.setdefault(recv, []).append(
                    (node.lineno, id(node) in in_finally))
            else:
                calls.append(node.lineno)
        for recv, acq_line in acquires.items():
            rels = releases.get(recv)
            if not rels:
                continue                     # split acquire/release API
            if any(protected for _, protected in rels):
                continue
            rel_line = max(line for line, _ in rels)
            if not any(acq_line < c < rel_line for c in calls):
                continue                     # nothing can raise in between
            findings.append(Finding(
                "exception-safety", rel,
                f"{qual} holds {recv}.acquire() across raising calls with "
                f"release() at line {rel_line} outside finally — use "
                f"`with` or try/finally",
                line=acq_line,
                key=f"exception-safety::lock-across-raise::{rel}::{qual}"
                    f"::{recv}"))
    return findings


# -------------------------------------------------------- unjoined-thread

def _joined_attrs(fn: ast.AST) -> Set[str]:
    """self attrs whose threads get ``.join()``ed in this function,
    directly (``self.x.join()``), via a local alias (``t = self.x``),
    or via iteration (``for t in self.xs``)."""
    aliases: Dict[str, str] = {}
    joined: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Attribute) and \
                isinstance(node.value.value, ast.Name) and \
                node.value.value.id == "self":
            aliases[node.targets[0].id] = node.value.attr
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, ast.Attribute) and \
                isinstance(node.iter.value, ast.Name) and \
                node.iter.value.id == "self":
            aliases[node.target.id] = node.iter.attr
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            recv = node.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                joined.add(recv.attr)
            elif isinstance(recv, ast.Name) and recv.id in aliases:
                joined.add(aliases[recv.id])
    return joined


def _check_unjoined_threads(index: RepoIndex) -> List[Finding]:
    findings = []
    for cls in index.classes("tmtpu"):
        thread_attrs: Dict[str, int] = {}
        for fn in cls.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    f = node.value.func
                    ctor = f.attr if isinstance(f, ast.Attribute) else \
                        f.id if isinstance(f, ast.Name) else ""
                    if ctor not in THREAD_CTORS:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            thread_attrs.setdefault(tgt.attr, node.lineno)
        if not thread_attrs:
            continue
        if not (set(cls.methods) & SHUTDOWN_METHODS):
            continue                         # no shutdown path to audit
        joined: Set[str] = set()
        for fn in cls.methods.values():
            joined |= _joined_attrs(fn)
        for attr, line in sorted(thread_attrs.items()):
            if attr in joined:
                continue
            findings.append(Finding(
                "exception-safety", cls.rel,
                f"{cls.name}.{attr} worker thread is never joined — "
                f"shutdown returns while it still runs, racing teardown",
                line=line,
                key=f"exception-safety::unjoined-thread::{cls.rel}"
                    f"::{cls.name}.{attr}"))
    return findings


# ------------------------------------------------------ unclosed-resource

def _with_nodes(fn: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    out.add(id(sub))
    return out


def _check_unclosed_resources(index: RepoIndex) -> List[Finding]:
    findings = []
    for rel, qual, fn in _functions(index):
        in_with = _with_nodes(fn)
        opened: Dict[str, Tuple[int, str]] = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and
                    isinstance(node.value, ast.Call) and
                    id(node.value) not in in_with and
                    len(node.targets) == 1 and
                    isinstance(node.targets[0], ast.Name)):
                continue
            f = node.value.func
            ctor = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if ctor in RESOURCE_CTORS:
                opened[node.targets[0].id] = (node.lineno, ctor)
        if not opened:
            continue
        escaped: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # `f = open(...)` then `with f:` — closed on block exit
                for item in node.items:
                    if isinstance(item.context_expr, ast.Name):
                        escaped.add(item.context_expr.id)
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name):
                    if node.func.attr == "close":
                        escaped.add(node.func.value.id)
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            escaped.add(sub.id)
            elif isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        escaped.add(sub.id)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) and \
                            isinstance(node.value, ast.Name):
                        escaped.add(node.value.id)
                    elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        for sub in ast.walk(node.value):
                            if isinstance(sub, ast.Name):
                                escaped.add(sub.id)
        for name, (line, ctor) in sorted(opened.items()):
            if name in escaped:
                continue
            findings.append(Finding(
                "exception-safety", rel,
                f"{qual} opens `{name} = {ctor}(...)` outside `with` and "
                f"never closes, returns, or stores it — fd leak",
                line=line,
                key=f"exception-safety::unclosed-resource::{rel}::{qual}"
                    f"::{name}"))
    return findings


# ----------------------------------------------------------- breaker-leak

def _check_breaker_leak(index: RepoIndex) -> List[Finding]:
    findings = []
    for rel, qual, fn in _functions(index):
        attrs = {n.attr for n in ast.walk(fn)
                 if isinstance(n, ast.Attribute)}
        names = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        if "allow" not in attrs or "record_success" not in attrs:
            continue
        # any *failure* token counts — the accounting may be delegated
        # (note_pallas_failure(pbr, e) routes through the breaker policy)
        if "trip_permanent" in attrs or \
                any("failure" in tok for tok in attrs | names):
            continue
        line = next((n.lineno for n in ast.walk(fn)
                     if isinstance(n, ast.Attribute) and
                     n.attr == "allow"), fn.lineno)
        findings.append(Finding(
            "exception-safety", rel,
            f"{qual} runs a breaker probe (allow→record_success) with no "
            f"record_failure path — an exception strands the breaker "
            f"half-open",
            line=line,
            key=f"exception-safety::breaker-leak::{rel}::{qual}"))
    return findings


@rule("exception-safety",
      doc="no lock held across a raise outside finally, no worker thread "
          "unjoined on shutdown, no fd opened without a closing guard, "
          "no breaker probe without a failure path",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_lock_across_raise(index)
    findings += _check_unjoined_threads(index)
    findings += _check_unclosed_resources(index)
    findings += _check_breaker_leak(index)
    return findings
