"""determinism rule: replayed consensus paths must be deterministic.

WAL replay re-drives ``_replay_msg_info`` / ``_handle_msgs`` /
``_handle_timeout`` and block re-application re-runs
``BlockExecutor.apply_block``; any wall-clock read, unseeded randomness,
or iteration over an unordered set on those paths can make the replayed
node diverge from its pre-crash self (different vote timestamp,
different proposal, different app hash). This rule computes the
call-graph closure from those seed methods and flags:

- wall clock: ``time.time`` / ``time.time_ns`` / ``datetime.now`` /
  ``datetime.utcnow`` (``time.monotonic`` / ``perf_counter`` are
  observability-only and deliberately exempt);
- randomness: module-level ``random.*``, ``os.urandom``, ``uuid.uuid4``
  (a seeded ``Random`` instance is fine — only the shared module RNG
  and OS entropy are flagged);
- unordered iteration: ``for x in {...}`` / ``for x in set(...)`` and
  their comprehension forms (dict/list preserve order; sets don't).

The protocol-timestamp sites (vote/proposal times, timeout scheduling)
are real wall-clock reads that are SAFE because the message is WAL'd
before processing and replay reads the recorded value — each is
suppressed in tools/lint_baseline.json with that justification.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tmtpu.analysis.callgraph import Analyzer, Event
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

# (class name, method) seeds: the consensus message/timeout handlers
# (everything the WAL replays) and the block application path
SEEDS = (
    ("ConsensusState", "_handle_msgs"),
    ("ConsensusState", "_handle_timeout"),
    ("ConsensusState", "_replay_msg_info"),
    ("BlockExecutor", "apply_block"),
)

_WALLCLOCK = {"time", "time_ns"}
_DATETIME = {"now", "utcnow", "today"}
_RANDOM_FNS = {"random", "randint", "choice", "choices", "shuffle",
               "uniform", "randrange", "getrandbits", "sample",
               "randbytes"}


def _is_set_expr(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Set) or (
        isinstance(expr, ast.Call) and
        isinstance(expr.func, ast.Name) and expr.func.id == "set")


def determinism_marker(node: ast.AST) -> Optional[str]:
    """Label nondeterminism hazards; None for everything else."""
    if isinstance(node, (ast.For, ast.comprehension)):
        return "set-iter" if _is_set_expr(node.iter) else None
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return None
    recv = fn.value.id if isinstance(fn.value, ast.Name) else ""
    if recv == "time" and fn.attr in _WALLCLOCK:
        return f"wallclock:time.{fn.attr}"
    if recv == "datetime" and fn.attr in _DATETIME:
        return f"wallclock:datetime.{fn.attr}"
    if recv == "random" and fn.attr in _RANDOM_FNS:
        return f"random:random.{fn.attr}"
    if recv == "os" and fn.attr == "urandom":
        return "random:os.urandom"
    if recv == "uuid" and fn.attr in ("uuid1", "uuid4"):
        return f"random:uuid.{fn.attr}"
    return None


@rule("determinism",
      doc="no wall clock, unseeded randomness, or set-order iteration "
          "reachable from the WAL-replayed consensus handlers or "
          "apply_block",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    az = Analyzer(index, marker_fn=determinism_marker)
    findings = []
    seen = set()
    for cls_name, method in SEEDS:
        for cls in index.classes_by_name.get(cls_name, []):
            for ev in az.events(cls, method):
                if ev.kind != "marker":
                    continue
                key = (f"determinism::{ev.label}::{ev.rel}"
                       f"::{ev.chain[-1]}")
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "determinism", ev.rel,
                    f"nondeterminism on a replayed path: {ev.label} at "
                    f"{ev.rel}:{ev.line} is reachable from "
                    f"{cls_name}.{method} (via {ev.via()}) — a "
                    f"replaying node can diverge from its pre-crash "
                    f"self; derive the value from WAL'd state or "
                    f"suppress with a justification",
                    line=ev.line, key=key))
    return sorted(findings, key=lambda f: f.key)
