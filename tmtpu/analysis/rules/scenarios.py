"""scenarios rule: every scenario spec must be runnable and judgeable.

Port of tools/check_scenarios.py. A scenario naming an unregistered
fault site, a nonexistent oracle, or a metric the node never emits fails
at RUN time — twenty seconds into a subprocess localnet, or silently (a
misspelled metric reads 0.0 and "passes" a floor of 0). This rule
front-loads those contract checks.

The fault-site / metric / timeline-event catalogs now come from the
shared index (the same ones failpoints/metrics/timeline consume), so an
engine-side rename is caught by one source of truth. The rule itself
imports the scenario library + oracle registry, hence
``requires_import`` — it runs against the real repo only.
"""

from __future__ import annotations

import inspect
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

# oracle param keys whose value is a metric name / timeline event name
METRIC_PARAM_ORACLES = {"metric_min", "metric_max"}
TIMELINE_PARAM_ORACLES = {"timeline_saw"}

_LIB = "tmtpu/scenario/library.py"


@rule("scenarios",
      doc="scenario specs validate; fault sites, oracles, oracle "
          "params, metric and timeline names all resolve",
      triggers=("tmtpu",), requires_import=True)
def check(index: RepoIndex) -> List[Finding]:
    from tmtpu.scenario import library
    from tmtpu.scenario import oracles as oracle_mod

    findings = []

    def add(message, key):
        findings.append(Finding("scenarios", _LIB, message, key=key))

    sites = index.fault_site_names()
    metrics = index.metric_names()
    events = index.timeline_events()

    for fast in library.FAST:
        if fast not in library.SCENARIOS:
            add(f"FAST names unknown scenario {fast!r} — the tier-1 "
                f"marker would collect nothing",
                f"scenarios::fast::{fast}")

    for comp in library.COMPOSED:
        if comp not in library.SCENARIOS:
            add(f"COMPOSED names unknown scenario {comp!r}",
                f"scenarios::composed::{comp}")
        elif not library.get(comp).layers:
            add(f"COMPOSED lists {comp!r} but its spec has no layers "
                f"— it is a plain spec, not a composition",
                f"scenarios::composed-flat::{comp}")

    for name in library.names():
        spec = library.get(name)
        where = f"scenario {name!r}"
        for problem in spec.validate():
            add(f"{where}: {problem}",
                f"scenarios::validate::{name}::{problem}")
        if spec.layers:
            # composed-spec contract: validate() already re-derives
            # cross-layer merge collisions from the provenance; here we
            # pin the attribution surface — an UNTAGGED fault in a
            # composed timeline executes fine but its failure can never
            # be attributed to a layer in the verdict
            for action in spec.faults:
                if not action.layer:
                    add(f"{where}: composed fault {action.op!r} at "
                        f"t={action.at_s} carries no layer tag — its "
                        f"verdict attribution is lost",
                        f"scenarios::untagged::{name}::{action.op}"
                        f"::{action.at_s}")
            untagged_oracles = [o.name for o in spec.oracles
                                if not o.layer]
            if untagged_oracles:
                add(f"{where}: composed oracles {untagged_oracles} "
                    f"carry no layer tag — a FAIL would name no layer",
                    f"scenarios::untagged-oracle::{name}")
        for action in spec.faults:
            if action.op == "inject":
                site = action.params.get("site", "")
                if site not in sites:
                    add(f"{where}: inject at t={action.at_s} targets "
                        f"unregistered fault site {site!r} — known: "
                        f"{sorted(sites)}",
                        f"scenarios::inject::{name}::{site}")
        for ospec in spec.oracles:
            try:
                fn = oracle_mod.get(ospec.name)
            except KeyError:
                add(f"{where}: unknown oracle {ospec.name!r} — known: "
                    f"{oracle_mod.names()}",
                    f"scenarios::oracle::{name}::{ospec.name}")
                continue
            try:
                inspect.signature(fn).bind(None, **ospec.params)
            except TypeError as e:
                add(f"{where}: oracle {ospec.name!r} params "
                    f"{sorted(ospec.params)} do not bind: {e}",
                    f"scenarios::params::{name}::{ospec.name}")
            if ospec.name in METRIC_PARAM_ORACLES:
                metric = ospec.params.get("name", "")
                if metric not in metrics:
                    add(f"{where}: oracle {ospec.name!r} reads metric "
                        f"{metric!r} which libs/metrics.py never "
                        f"defines — the oracle would judge 0.0 forever",
                        f"scenarios::metric::{name}::{metric}")
            if ospec.name in TIMELINE_PARAM_ORACLES:
                event = ospec.params.get("event", "")
                if event not in events:
                    add(f"{where}: oracle {ospec.name!r} waits for "
                        f"timeline event {event!r} which no code path "
                        f"records — known: {sorted(events)}",
                        f"scenarios::event::{name}::{event}")
    return findings
