"""metrics rule: every registered metric is written, every write resolves.

Port of tools/check_metrics.py, made fully static: the metric catalog
comes from ``index.metric_defs()`` (an AST parse of libs/metrics.py's
module-level ``DEFAULT.counter/gauge/histogram`` assignments) instead of
importing the module — so the rule also runs against synthetic fixture
trees.

1. A registered-but-never-written metric renders as a permanent zero on
   /metrics — it looks monitored while measuring nothing.
2. A write to a subsystem-prefixed attribute that is not registered
   raises AttributeError only on the code path that hits it.
3. A Counter/Gauge/Histogram constructed directly (outside the DEFAULT
   registry) accepts writes forever but never renders.
"""

from __future__ import annotations

import re
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import METRIC_WRITE_RE, RepoIndex
from tmtpu.analysis.registry import rule

_WRITE_PAT = re.compile(
    r"\b(?:metrics\.|_m\.)?([a-z][a-z0-9_]*)" + METRIC_WRITE_RE)

# subsystem prefixes whose writes must resolve against the catalog
_KNOWN_PREFIXES = ("consensus_", "p2p_", "mempool_", "crypto_")

_DIRECT_CTOR = re.compile(
    r"\b(?:metrics\.)?(Counter|Gauge|Histogram)\(\s*[\"']")

_METRICS_MOD = "tmtpu/libs/metrics.py"


@rule("metrics",
      doc="registered metrics have write sites, writes name registered "
          "metrics, and no metric bypasses the DEFAULT registry",
      triggers=("tmtpu", "tools", "tests", "bench.py"))
def check(index: RepoIndex) -> List[Finding]:
    attrs = index.metric_defs()
    written = set()
    referenced = {}  # attr-like name -> first rel it was written in
    for fi in index.files():
        for m in _WRITE_PAT.finditer(fi.source):
            name = m.group(1)
            if name in attrs:
                written.add(name)
            elif name.startswith(_KNOWN_PREFIXES):
                referenced.setdefault(name, fi.rel)
    findings = []
    for attr in sorted(set(attrs) - written):
        findings.append(Finding(
            "metrics", _METRICS_MOD,
            f"dead metric: {attr} ({attrs[attr]}) is registered in "
            f"{_METRICS_MOD} but never written anywhere",
            key=f"metrics::dead::{attr}"))
    for name, rel in sorted(referenced.items()):
        findings.append(Finding(
            "metrics", rel,
            f"unknown metric: {name} is written in {rel} but not "
            f"registered in {_METRICS_MOD}",
            key=f"metrics::unknown::{name}"))
    for fi in index.files():
        if fi.rel == _METRICS_MOD or fi.rel.startswith("tests/"):
            continue  # the registry itself; tests build throwaways
        for m in _DIRECT_CTOR.finditer(fi.source):
            findings.append(Finding(
                "metrics", fi.rel,
                f"unrendered metric: {fi.rel} constructs a {m.group(1)} "
                f"directly — it bypasses the DEFAULT registry and never "
                f"appears on /metrics; use DEFAULT.{m.group(1).lower()}"
                f"(...)",
                line=fi.line_of(m.start()),
                key=f"metrics::ctor::{fi.rel}::{m.group(1)}"))
    return findings
