"""sigcache rule: every hot-path signature check rides the batch layer.

Port of tools/check_sigcache.py:

1. No direct ``.verify_signature(`` call outside the oracle/fallback
   layer — a raw call bypasses the verified-signature cache AND the
   batch/dedup layer. Allowed: the crypto key implementations, the TPU/
   native oracle code, and the per-connection cold paths.
2. Every ``verify_commit*`` function in types/commit_verify.py
   constructs its lanes through the batch layer; the declared entry
   points must all exist (else this rule's coverage map is stale).
"""

from __future__ import annotations

import ast
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

# the oracle/fallback layer: the ONLY tmtpu/ files allowed to call
# .verify_signature( directly (prefixes end with "/", exact paths don't)
SERIAL_ALLOWED = (
    "tmtpu/crypto/",    # key impls + batch fallback
    "tmtpu/tpu/",       # device kernels vs oracle
    "tmtpu/native/",    # host-prep oracle notes
    # cold paths: one verify per connection / per harness run, no batch
    # to amortize against and nothing a cache would ever hit twice
    "tmtpu/p2p/conn/secret_connection.py",
    "tmtpu/p2p/conn/plain_connection.py",
    "tmtpu/privval/harness.py",
)

# commit verification entry points that must batch (rule 2)
COMMIT_FNS = ("verify_commit", "verify_commit_light",
              "verify_commit_light_trusting", "verify_commits_light_batch")
COMMIT_IMPL = "tmtpu/types/commit_verify.py"


@rule("sigcache",
      doc="no serial .verify_signature() outside the oracle layer; "
          "every verify_commit* goes through the batch verifier",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    findings = []
    for fi in index.files("tmtpu"):
        if fi.rel.startswith(SERIAL_ALLOWED) or fi.rel in SERIAL_ALLOWED:
            continue
        if ".verify_signature" not in fi.source:
            continue
        if fi.tree is None:
            findings.append(Finding(
                "sigcache", fi.rel,
                f"syntax error parsing {fi.rel}: {fi.parse_error}",
                key=f"sigcache::syntax::{fi.rel}"))
            continue
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "verify_signature":
                findings.append(Finding(
                    "sigcache", fi.rel,
                    f"serial verify in hot path: {fi.rel}:{node.lineno} "
                    f"calls .verify_signature() directly — route it "
                    f"through crypto/batch.py (new_batch_verifier / "
                    f"verify_one) so the verified-signature cache and "
                    f"batch dedup apply",
                    line=node.lineno,
                    key=f"sigcache::serial::{fi.rel}"))

    impl = index.get(COMMIT_IMPL)
    if impl is None or impl.tree is None:
        findings.append(Finding(
            "sigcache", COMMIT_IMPL,
            f"{COMMIT_IMPL} missing or unparseable — commit "
            f"verification moved without updating this rule",
            key="sigcache::no-commit-impl"))
        return findings
    all_names = {n.name for n in ast.walk(impl.tree)
                 if isinstance(n, ast.FunctionDef)}
    for node in ast.walk(impl.tree):
        if not (isinstance(node, ast.FunctionDef) and
                node.name.startswith("verify_commit")):
            continue
        body_src = ast.dump(node)
        helper_calls = [c.func.id for c in ast.walk(node)
                        if isinstance(c, ast.Call) and
                        isinstance(c.func, ast.Name)]
        if "new_batch_verifier" not in body_src and \
                "BatchVerifier" not in body_src and \
                not any(n.startswith("_verify") for n in helper_calls):
            findings.append(Finding(
                "sigcache", COMMIT_IMPL,
                f"unbatched commit verify: {COMMIT_IMPL} {node.name}() "
                f"never constructs a BatchVerifier — commit lanes "
                f"would bypass the cache-aware batch path",
                line=node.lineno,
                key=f"sigcache::unbatched::{node.name}"))
    for fn in COMMIT_FNS:
        if fn not in all_names:
            findings.append(Finding(
                "sigcache", COMMIT_IMPL,
                f"missing commit verify entry point: {fn} not found in "
                f"{COMMIT_IMPL} — the rule's coverage map is stale; "
                f"update COMMIT_FNS",
                key=f"sigcache::missing::{fn}"))
    return sorted(findings, key=lambda f: (f.file, f.line, f.key))
