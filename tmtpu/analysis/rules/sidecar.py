"""sidecar rule: the wire protocol and its telemetry stay fully covered.

Port of tools/check_sidecar.py:

1. Every class in ``protocol.MESSAGE_TYPES`` has a round-trip sample in
   tests/test_sidecar_protocol.py's SAMPLES dict (and no stale samples).
2. Every ``sidecar_*`` metric carries the ``tendermint_sidecar_`` prefix
   and renders through the DEFAULT registry.
3. Every sidecar metric has a write site somewhere in the tree, and
   every sidecar write names a registered metric.

Imports the protocol module and metrics registry (render check needs the
real renderer), hence ``requires_import``.
"""

from __future__ import annotations

import re
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import METRIC_WRITE_RE, RepoIndex
from tmtpu.analysis.registry import rule

PROTOCOL_TEST = "tests/test_sidecar_protocol.py"
_PROTO_MOD = "tmtpu/sidecar/protocol.py"
_METRICS_MOD = "tmtpu/libs/metrics.py"

_SAMPLE_RE = re.compile(r"proto\.([A-Za-z_][A-Za-z0-9_]*)\s*:")
_SIDECAR_WRITE = re.compile(
    r"\b(?:metrics\.|_m\.)?(sidecar_[a-z0-9_]*)" + METRIC_WRITE_RE)


def _protocol_findings(index: RepoIndex) -> List[Finding]:
    from tmtpu.sidecar import protocol as proto

    fi = index.get(PROTOCOL_TEST)
    if fi is None:
        return [Finding("sidecar", PROTOCOL_TEST,
                        f"missing protocol test file: {PROTOCOL_TEST}",
                        key="sidecar::no-test-file")]
    findings = []
    if "SAMPLES" not in fi.source:
        return [Finding("sidecar", PROTOCOL_TEST,
                        f"{PROTOCOL_TEST} has no SAMPLES dict — the "
                        f"round-trip coverage this rule asserts is gone",
                        key="sidecar::no-samples")]
    if "def test_frame_round_trip" not in fi.source:
        findings.append(Finding(
            "sidecar", PROTOCOL_TEST,
            f"{PROTOCOL_TEST} lost test_frame_round_trip — samples "
            f"exist but nothing round-trips them",
            key="sidecar::no-round-trip-test"))
    sampled = set(_SAMPLE_RE.findall(fi.source))
    registered = {cls.__name__ for cls in proto.MESSAGE_TYPES.values()}
    for name in sorted(registered - sampled):
        findings.append(Finding(
            "sidecar", _PROTO_MOD,
            f"untested wire message: protocol.{name} is registered in "
            f"MESSAGE_TYPES but has no encode/decode round-trip sample "
            f"in {PROTOCOL_TEST}",
            key=f"sidecar::unsampled::{name}"))
    for name in sorted(sampled - registered):
        findings.append(Finding(
            "sidecar", PROTOCOL_TEST,
            f"stale sample: {PROTOCOL_TEST} samples proto.{name}, "
            f"which is not in MESSAGE_TYPES",
            key=f"sidecar::stale-sample::{name}"))
    return findings


def _metric_findings(index: RepoIndex) -> List[Finding]:
    from tmtpu.libs import metrics

    sidecar_attrs = {
        attr: obj for attr, obj in vars(metrics).items()
        if isinstance(obj, metrics._Metric) and
        attr.startswith("sidecar_")}
    if not sidecar_attrs:
        return [Finding(
            "sidecar", _METRICS_MOD,
            "no sidecar_* metrics found in tmtpu/libs/metrics.py — the "
            "sidecar metric set was removed or renamed",
            key="sidecar::no-metrics")]
    findings = []
    rendered = metrics.render_prometheus()
    for attr, obj in sorted(sidecar_attrs.items()):
        if not obj.name.startswith("tendermint_sidecar_"):
            findings.append(Finding(
                "sidecar", _METRICS_MOD,
                f"misfiled metric: {attr} renders as {obj.name!r}, "
                f"outside the tendermint_sidecar_ subsystem",
                key=f"sidecar::misfiled::{attr}"))
        if f"# TYPE {obj.name} " not in rendered:
            findings.append(Finding(
                "sidecar", _METRICS_MOD,
                f"unrendered metric: {attr} ({obj.name}) does not "
                f"appear in render_prometheus() — it bypassed the "
                f"DEFAULT registry and neither the daemon /metrics nor "
                f"the node exposition will serve it",
                key=f"sidecar::unrendered::{attr}"))
    written = set()
    for fi in index.files():
        written.update(_SIDECAR_WRITE.findall(fi.source))
    for attr in sorted(set(sidecar_attrs) - written):
        findings.append(Finding(
            "sidecar", _METRICS_MOD,
            f"dead metric: {attr} ({sidecar_attrs[attr].name}) is "
            f"registered but never written anywhere in the tree",
            key=f"sidecar::dead::{attr}"))
    for name in sorted(written - set(sidecar_attrs)):
        findings.append(Finding(
            "sidecar", _METRICS_MOD,
            f"unknown metric: sidecar metric {name} is written "
            f"somewhere in the tree but not registered in "
            f"tmtpu/libs/metrics.py",
            key=f"sidecar::unknown::{name}"))
    return findings


@rule("sidecar",
      doc="every sidecar wire message round-trips in a test; every "
          "sidecar metric is prefixed, rendered, and written",
      triggers=("tmtpu/sidecar", "tmtpu/libs", "tests"),
      requires_import=True)
def check(index: RepoIndex) -> List[Finding]:
    return _protocol_findings(index) + _metric_findings(index)
