"""wire-taint rule: no unverified wire bytes reach a consensus sink.

The system's core safety invariant — untrusted network bytes become a
trusted on-device tally only *through* signature verification — is
enforced here with the interprocedural taint engine
(``analysis/dataflow.py``):

**Sources** (taint labels):

- ``wire`` — the payload param of every ``Reactor.receive()`` (peer
  gossip: votes, proposals, block parts, snapshots, evidence, txs);
- ``rpc`` — every parameter of every public JSON-RPC handler (the
  nested route functions of ``rpc/core.build_routes``);
- ``statesync`` — snapshot chunk bytes entering ``Syncer.add_chunk``.

**Sinks**: tally mutation (``add_verified_vote``), WAL writes
(``.write/.write_sync`` on a WAL-ish receiver), privval signing
(``sign_vote``/``sign_proposal``), and block execution
(``apply_block``).

**Sanitizers**: ``validate_basic``, ``verify_one`` and the
batch-verify family. A sanitizer call launders the frame from that
statement on — the mask-indexing that follows a batch verify is beyond
static reach, so the invariant checked is "a verification call stands
between the wire and the sink on every path", which is exactly how the
code expresses it.

Taint crosses the reactor-thread -> queue -> state-thread handoff via
the engine's channel fixpoint (``self._q.put(tainted)`` re-seeds the
methods reading ``self._q``), so the classic Tendermint shape
(receive enqueues, ``_handle_msgs`` drains) is still covered.

Grandfathered flows (WAL-before-process writes the *unverified* message
by design) carry written justifications in the baseline.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tmtpu.analysis.dataflow import TaintAnalyzer, TaintHit
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule
from tmtpu.analysis.rules.recv_sync import _is_reactor

# verification calls that launder a frame (see module docstring)
SANITIZERS = {
    "validate_basic", "verify_one", "verify", "verify_tally",
    "verify_signature", "batch_verify_items",
    "verify_commit", "verify_commit_light", "verify_commit_light_trusting",
    "verify_commits_light_batch",
}

# payload-ish parameter names; fallback is the last positional param
PAYLOAD_PARAMS = ("msg_bytes", "payload", "data", "chunk", "tx")

SINK_METHODS = {
    "add_verified_vote": "tally",
    "sign_vote": "privval-sign",
    "sign_proposal": "privval-sign",
    "apply_block": "apply-block",
}
WAL_WRITE_METHODS = {"write", "write_sync"}


def _sink_fn(call: ast.Call) -> Optional[str]:
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    label = SINK_METHODS.get(f.attr)
    if label is not None:
        return label
    if f.attr in WAL_WRITE_METHODS:
        try:
            recv = ast.unparse(f.value).lower()
        except Exception:  # noqa: BLE001 - unparse of odd nodes
            recv = ""
        if "wal" in recv:
            return "wal-write"
    return None


def _payload_params(fn: ast.FunctionDef, label: str) -> Dict[str, str]:
    params = [a.arg for a in fn.args.args if a.arg != "self"]
    if not params:
        return {}
    named = [p for p in params if p in PAYLOAD_PARAMS]
    return {p: label for p in (named or params[-1:])}


def _seeds(index: RepoIndex):
    # 1. reactor receive payloads
    for cls in index.classes("tmtpu"):
        if _is_reactor(cls) and "receive" in cls.methods:
            fn = cls.methods["receive"]
            labels = _payload_params(fn, "wire")
            if labels:
                yield cls, fn, cls.rel, labels
    # 2. public JSON-RPC handler params (nested defs in build_routes)
    for fi in index.files("tmtpu/rpc"):
        if fi.tree is None:
            continue
        for node in fi.tree.body:
            if isinstance(node, ast.FunctionDef) and \
                    node.name == "build_routes":
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        params = {a.arg: "rpc" for a in sub.args.args}
                        if params:
                            yield None, sub, fi.rel, params
    # 3. statesync snapshot chunk bytes
    for cls in index.classes("tmtpu/statesync"):
        for name in ("add_chunk", "add_snapshot"):
            fn = cls.methods.get(name)
            if fn is not None:
                labels = _payload_params(fn, "statesync")
                if labels:
                    yield cls, fn, cls.rel, labels


def _finding(index: RepoIndex, hit: TaintHit) -> Finding:
    labels = "+".join(sorted(hit.labels))
    return Finding(
        "wire-taint", hit.rel,
        f"unverified {labels} bytes reach {hit.sink} at "
        f"{hit.rel}:{hit.line} via {hit.via()} — insert a "
        f"validate_basic/verify gate before the sink",
        line=hit.line,
        key=f"wire-taint::{hit.sink}::{labels}::{hit.rel}::{hit.chain[-1]}")


@rule("wire-taint",
      doc="no unverified wire/rpc/statesync bytes reach a tally, WAL, "
          "signing, or apply_block sink (interprocedural taint)",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    ta = TaintAnalyzer(index, _sink_fn, SANITIZERS)
    findings, seen = [], set()
    for hit in ta.propagate(_seeds(index)):
        f = _finding(index, hit)
        if f.key not in seen:
            seen.add(f.key)
            findings.append(f)
    return findings
