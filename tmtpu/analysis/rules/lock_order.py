"""lock-order rule: no lock pair acquired in both orders, no plain-lock
self-nesting.

Walks every method of every tmtpu/ class through the interprocedural
held-lock engine (callgraph.Analyzer) and collects acquisition edges
``held -> acquired``. Two findings:

1. **Order inversion**: locks A and B where some path acquires B while
   holding A and another acquires A while holding B — the classic
   two-thread deadlock. Condition(lock) aliasing is resolved first so
   ``with self._height_cv`` counts as its wrapped mutex.
2. **Self-deadlock**: a non-reentrant lock (threading.Lock / sync.Mutex)
   acquired while already held on the same path — guaranteed hang, no
   second thread needed. RLocks are exempt by construction.

Both witnesses (call chain + file:line) ride along in the message so a
finding is checkable without re-running the analysis.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from tmtpu.analysis.callgraph import Analyzer, Event
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule


def _witness(ev: Event) -> str:
    return f"{ev.rel}:{ev.line} via {ev.via()}"


@rule("lock-order",
      doc="no lock pair is acquired in both orders across the call "
          "graph, and no non-reentrant lock nests under itself",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    az = Analyzer(index)
    # (held, acquired) -> first witness event + its context class
    edges: Dict[Tuple[str, str], Tuple[Event, object]] = {}
    self_nests: Dict[str, Tuple[Event, object]] = {}

    for cls in az._classes:
        for name in az.methods_of(cls):
            for ev in az.events(cls, name):
                if ev.kind != "acquire":
                    continue
                for held in ev.held:
                    if held == ev.label:
                        self_nests.setdefault(ev.label, (ev, cls))
                    else:
                        edges.setdefault((held, ev.label), (ev, cls))

    findings = []
    for (a, b) in sorted(edges):
        if a < b and (b, a) in edges:
            ev_ab, _ = edges[(a, b)]
            ev_ba, _ = edges[(b, a)]
            findings.append(Finding(
                "lock-order", ev_ab.rel,
                f"lock order inversion between {a} and {b}: "
                f"{a} -> {b} at {_witness(ev_ab)}; "
                f"{b} -> {a} at {_witness(ev_ba)} — two threads taking "
                f"these paths concurrently deadlock",
                line=ev_ab.line,
                key=f"lock-order::cycle::{a}<->{b}"))
    for lock, (ev, cls) in sorted(self_nests.items()):
        if az.lock_kind(cls, lock) != "plain":
            continue  # RLock/RMutex re-entry is fine
        findings.append(Finding(
            "lock-order", ev.rel,
            f"self-deadlock: non-reentrant lock {lock} is acquired at "
            f"{_witness(ev)} while already held on the same path — "
            f"this hangs without any second thread",
            line=ev.line,
            key=f"lock-order::self::{lock}"))
    return findings
