"""timeline rule: timeline step names and trace span names stay aligned.

Port of tools/check_timeline.py, made fully static: the declared
``CONSENSUS_STEP_EVENTS`` tuple is parsed out of libs/timeline.py by the
index instead of imported. The journal (per-height ordering) and the
span ring (durations) are two views of the same step; they only
correlate if the names are byte-identical.
"""

from __future__ import annotations

from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

_TIMELINE_MOD = "tmtpu/libs/timeline.py"


@rule("timeline",
      doc="every consensus step event recorded into the timeline has a "
          "byte-identical trace span name, and vice versa",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    span_names = index.span_names()
    recorded = index.timeline_record_sites()
    step_events = index.consensus_step_events()

    findings = []
    for ev in step_events:
        if ev not in span_names:
            findings.append(Finding(
                "timeline", _TIMELINE_MOD,
                f"timeline step {ev!r} (timeline.CONSENSUS_STEP_EVENTS)"
                f" has no matching trace span name under tmtpu/",
                key=f"timeline::step-span::{ev}"))
    for ev, rel in sorted(recorded.items()):
        if not ev.startswith("consensus."):
            continue  # only step events must mirror span names
        if ev not in span_names:
            findings.append(Finding(
                "timeline", rel,
                f"timeline records consensus step {ev!r} in {rel} but "
                f"no trace.traced/trace.span literal uses that name",
                key=f"timeline::recorded-span::{ev}"))
        if ev not in step_events:
            findings.append(Finding(
                "timeline", rel,
                f"timeline records consensus step {ev!r} in {rel} but "
                f"it is missing from timeline.CONSENSUS_STEP_EVENTS",
                key=f"timeline::undeclared::{ev}"))
    return findings
