"""failpoints rule: the chaos surface must stay testable and unambiguous.

Port of tools/check_failpoints.py onto the shared index:

1. No duplicate ``faultinject.register`` names (injection by name must
   be unambiguous), and no name used both by ``register()`` and the
   idempotent ``ensure``/``fail_point`` forms.
2. Every fault site appears in at least one test — a fail point nobody
   injects in CI is untested recovery code wearing a tested name.
"""

from __future__ import annotations

from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule


def _split(site: str):
    rel, _, line = site.rpartition(":")
    return rel, int(line) if line.isdigit() else 0


@rule("failpoints",
      doc="fault-injection sites are unique by name and each is "
          "exercised by at least one test",
      triggers=("tmtpu", "tests"))
def check(index: RepoIndex) -> List[Finding]:
    registered, ensured = index.fault_sites()
    findings = []
    for name, sites in sorted(registered.items()):
        rel, line = _split(sites[0])
        if len(sites) > 1:
            findings.append(Finding(
                "failpoints", rel,
                f"duplicate fault site {name!r}: registered at "
                f"{', '.join(sites)} — injection by name is ambiguous",
                line=line, key=f"failpoints::dup::{name}"))
        if name in ensured:
            findings.append(Finding(
                "failpoints", rel,
                f"duplicate fault site {name!r}: register() at "
                f"{sites[0]} also used as a fail_point/ensure name at "
                f"{ensured[name][0]}",
                line=line, key=f"failpoints::mixed::{name}"))
    all_sites = {**{n: s[0] for n, s in ensured.items()},
                 **{n: s[0] for n, s in registered.items()}}
    corpus = index.test_corpus()
    for name, where in sorted(all_sites.items()):
        if name not in corpus:
            rel, line = _split(where)
            findings.append(Finding(
                "failpoints", rel,
                f"untested fault site {name!r} ({where}): no test "
                f"mentions it — inject it at least once (script()/"
                f"TMTPU_FAULTS) so the recovery path it guards runs "
                f"in CI",
                line=line, key=f"failpoints::untested::{name}"))
    return findings
