"""blocking-lock rule: no blocking operation under a hot lock or on a
recv thread.

The consensus receive loop holds ``ConsensusState._mtx`` for a whole
message batch and every gossip/query thread contends on it; the mempool
locks gate CheckTx admission. A blocking call inside those regions (or
on a peer connection's recv thread) stalls the pipeline for its full
duration. This rule walks the interprocedural held-lock engine over
every tmtpu/ method and flags *markers* — operations known to block —
reachable while a hot lock is held, plus any marker reachable from a
Reactor's ``receive()`` regardless of locks.

Markers: ABCI ``*_sync`` round trips, ``time.sleep``, file I/O
(``open``/``fsync``/``write_sync``/``flush*``), socket traffic,
subprocess spawns, and crypto dispatch (``new_batch_verifier`` — every
construction site in this tree is immediately followed by
``.verify()``, a TPU/sidecar dispatch — and ``verify_one``).

Deliberate blocking (the WAL-before-process fsync, serial-mode
ApplyBlock, the in-window vote-batch dispatch) is suppressed in
tools/lint_baseline.json with its justification; anything new fails.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Optional

from tmtpu.analysis.callgraph import Analyzer, Event
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule
from tmtpu.analysis.rules.recv_sync import ABCI_SYNC_METHODS, _is_reactor

# (class glob, lock attr) pairs naming the hot locks: the consensus
# state mutex and the mempool admission/update locks
HOT_LOCK_PATTERNS = (
    ("*State", "_mtx"),
    ("*Mempool*", "_lock"),
    ("*Mempool*", "_update_lock"),
)

_IO_ATTRS = {"fsync", "write_sync", "flush_sync", "flush_and_sync"}
_SOCKET_ATTRS = {"sendall", "recv", "connect", "accept",
                 "create_connection"}
_SUBPROCESS_ATTRS = {"run", "Popen", "check_output", "check_call",
                     "call"}
_DISPATCH_NAMES = {"new_batch_verifier", "verify_one"}


def _recv_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


def blocking_marker(node: ast.AST) -> Optional[str]:
    """Label blocking operations; None for everything else."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "file-io:open"
        if fn.id in _DISPATCH_NAMES:
            return f"dispatch:{fn.id}"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    recv = _recv_name(fn.value)
    if fn.attr in ABCI_SYNC_METHODS:
        return f"abci-sync:{fn.attr}"
    if fn.attr == "sleep" and recv == "time":
        return "sleep:time.sleep"
    if fn.attr in _IO_ATTRS:
        return f"file-io:{fn.attr}"
    if fn.attr in _DISPATCH_NAMES:
        return f"dispatch:{fn.attr}"
    if fn.attr in _SUBPROCESS_ATTRS and recv == "subprocess":
        return f"subprocess:{fn.attr}"
    if fn.attr in _SOCKET_ATTRS and (
            recv == "socket" or "sock" in recv.lower() or
            recv.lower().endswith("conn")):
        return f"socket:{fn.attr}"
    return None


def _hot_locks(held) -> List[str]:
    out = []
    for lock in held:
        if "::" in lock:
            continue  # module-level locks are never the hot set
        cls_name, _, attr = lock.partition(".")
        for cpat, lattr in HOT_LOCK_PATTERNS:
            if attr == lattr and fnmatch.fnmatch(cls_name, cpat):
                out.append(lock)
                break
    return out


@rule("blocking-lock",
      doc="no sleep/IO/ABCI round trip/crypto dispatch reachable while "
          "holding a hot lock or on a reactor recv thread",
      triggers=("tmtpu",))
def check(index: RepoIndex) -> List[Finding]:
    az = Analyzer(index, marker_fn=blocking_marker)
    findings = []
    seen = set()

    def add(ev: Event, context: str, key: str):
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            "blocking-lock", ev.rel,
            f"blocking op {ev.label} at {ev.rel}:{ev.line} is reachable "
            f"{context} (via {ev.via()}) — move it outside the critical "
            f"section / hand it to a worker, or suppress with a "
            f"justification",
            line=ev.line, key=key))

    for cls in az._classes:
        for name in az.methods_of(cls):
            for ev in az.events(cls, name):
                if ev.kind != "marker":
                    continue
                for lock in _hot_locks(ev.held):
                    # key on the innermost frame so one marker reached
                    # from many entry points is one finding
                    add(ev, f"while holding {lock}",
                        f"blocking-lock::{lock}::{ev.label}"
                        f"::{ev.rel}::{ev.chain[-1]}")

    for cls in az._classes:
        if not _is_reactor(cls) or "receive" not in cls.methods:
            continue
        for ev in az.events(cls, "receive"):
            if ev.kind != "marker":
                continue
            add(ev, f"on {cls.name}'s recv thread",
                f"blocking-lock::recv::{cls.name}::{ev.label}"
                f"::{ev.rel}::{ev.chain[-1]}")

    return sorted(findings, key=lambda f: f.key)
