"""lightserve rule: the serving-tier protocol and telemetry stay covered.

The lightserve daemon borrows the sidecar's frame codec but owns its own
wire namespace and metric family, so it gets the same hygiene the
``sidecar`` rule enforces there:

1. Every class in ``tmtpu.lightserve.protocol.MESSAGE_TYPES`` has a
   round-trip sample in tests/test_lightserve_protocol.py's SAMPLES
   dict (and no stale samples linger).
2. Every ``lightserve_*`` metric carries the ``tendermint_lightserve_``
   prefix and renders through the DEFAULT registry.
3. Every lightserve metric has a write site somewhere in the tree, and
   every lightserve metric write names a registered metric.

Imports the protocol module and metrics registry (the render check needs
the real renderer), hence ``requires_import``.
"""

from __future__ import annotations

import re
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import METRIC_WRITE_RE, RepoIndex
from tmtpu.analysis.registry import rule

PROTOCOL_TEST = "tests/test_lightserve_protocol.py"
_PROTO_MOD = "tmtpu/lightserve/protocol.py"
_METRICS_MOD = "tmtpu/libs/metrics.py"

_SAMPLE_RE = re.compile(r"proto\.([A-Za-z_][A-Za-z0-9_]*)\s*:")
_LIGHTSERVE_WRITE = re.compile(
    r"\b(?:metrics\.|_m\.)?(lightserve_[a-z0-9_]*)" + METRIC_WRITE_RE)


def _protocol_findings(index: RepoIndex) -> List[Finding]:
    from tmtpu.lightserve import protocol as proto

    fi = index.get(PROTOCOL_TEST)
    if fi is None:
        return [Finding("lightserve", PROTOCOL_TEST,
                        f"missing protocol test file: {PROTOCOL_TEST}",
                        key="lightserve::no-test-file")]
    findings = []
    if "SAMPLES" not in fi.source:
        return [Finding("lightserve", PROTOCOL_TEST,
                        f"{PROTOCOL_TEST} has no SAMPLES dict — the "
                        f"round-trip coverage this rule asserts is gone",
                        key="lightserve::no-samples")]
    if "def test_frame_round_trip" not in fi.source:
        findings.append(Finding(
            "lightserve", PROTOCOL_TEST,
            f"{PROTOCOL_TEST} lost test_frame_round_trip — samples "
            f"exist but nothing round-trips them",
            key="lightserve::no-round-trip-test"))
    sampled = set(_SAMPLE_RE.findall(fi.source))
    registered = {cls.__name__ for cls in proto.MESSAGE_TYPES.values()}
    for name in sorted(registered - sampled):
        findings.append(Finding(
            "lightserve", _PROTO_MOD,
            f"untested wire message: protocol.{name} is registered in "
            f"MESSAGE_TYPES but has no encode/decode round-trip sample "
            f"in {PROTOCOL_TEST}",
            key=f"lightserve::unsampled::{name}"))
    for name in sorted(sampled - registered):
        findings.append(Finding(
            "lightserve", PROTOCOL_TEST,
            f"stale sample: {PROTOCOL_TEST} samples proto.{name}, "
            f"which is not in MESSAGE_TYPES",
            key=f"lightserve::stale-sample::{name}"))
    return findings


def _metric_findings(index: RepoIndex) -> List[Finding]:
    from tmtpu.libs import metrics

    ls_attrs = {
        attr: obj for attr, obj in vars(metrics).items()
        if isinstance(obj, metrics._Metric) and
        attr.startswith("lightserve_")}
    if not ls_attrs:
        return [Finding(
            "lightserve", _METRICS_MOD,
            "no lightserve_* metrics found in tmtpu/libs/metrics.py — "
            "the serving-tier metric set was removed or renamed",
            key="lightserve::no-metrics")]
    findings = []
    rendered = metrics.render_prometheus()
    for attr, obj in sorted(ls_attrs.items()):
        if not obj.name.startswith("tendermint_lightserve_"):
            findings.append(Finding(
                "lightserve", _METRICS_MOD,
                f"misfiled metric: {attr} renders as {obj.name!r}, "
                f"outside the tendermint_lightserve_ subsystem",
                key=f"lightserve::misfiled::{attr}"))
        if f"# TYPE {obj.name} " not in rendered:
            findings.append(Finding(
                "lightserve", _METRICS_MOD,
                f"unrendered metric: {attr} ({obj.name}) does not "
                f"appear in render_prometheus() — it bypassed the "
                f"DEFAULT registry and neither the daemon /metrics nor "
                f"the node exposition will serve it",
                key=f"lightserve::unrendered::{attr}"))
    written = set()
    for fi in index.files():
        written.update(_LIGHTSERVE_WRITE.findall(fi.source))
    for attr in sorted(set(ls_attrs) - written):
        findings.append(Finding(
            "lightserve", _METRICS_MOD,
            f"dead metric: {attr} ({ls_attrs[attr].name}) is "
            f"registered but never written anywhere in the tree",
            key=f"lightserve::dead::{attr}"))
    for name in sorted(written - set(ls_attrs)):
        findings.append(Finding(
            "lightserve", _METRICS_MOD,
            f"unknown metric: lightserve metric {name} is written "
            f"somewhere in the tree but not registered in "
            f"tmtpu/libs/metrics.py",
            key=f"lightserve::unknown::{name}"))
    return findings


@rule("lightserve",
      doc="every lightserve wire message round-trips in a test; every "
          "lightserve metric is prefixed, rendered, and written",
      triggers=("tmtpu/lightserve", "tmtpu/libs", "tests"),
      requires_import=True)
def check(index: RepoIndex) -> List[Finding]:
    return _protocol_findings(index) + _metric_findings(index)
