"""obs-docs rule: the tx-lifecycle + tracing observability surface is
documented.

The per-tx journey ring (libs/txlat), the causal-trace span names
(libs/trace), and the per-validator forensics ledger (libs/valstats)
are only useful if an operator can read their output, and every name
they export is an API: the checkpoint stages in ``TX_STAGES`` (they
appear verbatim in ``txlat`` RPC snapshots and fleet reports), the
causal milestone/hop marks in ``TRACE_MARKS`` (served by the
``traces`` RPC and joined by tools/critical_path.py), the
``tendermint_tx_latency_*`` / ``tendermint_health_latency_*`` /
``tendermint_trace_*`` / ``tendermint_validator_*`` /
``tendermint_lightserve_*`` metric families,
the ``tx_latency`` timeline event kind, and the forensics timeline
events in ``VALSTATS_EVENTS``. Each one must have a row in
docs/OBSERVABILITY.md — a stage, mark, event or metric added without
documentation is a dashboard nobody can interpret.

Everything is resolved statically (metric catalog via
``index.metric_defs()``, the stage/mark tuples parsed out of
libs/txlat.py / libs/trace.py), so the rule also runs on synthetic
fixture trees; a tree with no tx-lifecycle surface at all has nothing
to document and passes vacuously.
"""

from __future__ import annotations

import ast
import os
from typing import List

from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

DOC_PATH = "docs/OBSERVABILITY.md"
_TXLAT_MOD = "tmtpu/libs/txlat.py"
_TRACE_MOD = "tmtpu/libs/trace.py"
_METRICS_MOD = "tmtpu/libs/metrics.py"
_VALSTATS_MOD = "tmtpu/libs/valstats.py"
_PREFIXES = ("tendermint_tx_latency", "tendermint_health_latency",
             "tendermint_trace", "tendermint_validator",
             "tendermint_lightserve")


def _str_tuple(index: RepoIndex, mod: str, var: str) -> List[str]:
    """A module-level tuple/list of string constants, statically."""
    fi = index.get(mod)
    if fi is None or fi.tree is None:
        return []
    for node in fi.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return [e.value for e in node.value.elts
                        if isinstance(e, ast.Constant) and
                        isinstance(e.value, str)]
    return []


@rule("obs-docs",
      doc="every tx-lifecycle/tracing/validator-forensics observability "
          "name — TX_STAGES checkpoint stages, TRACE_MARKS causal marks, "
          "tendermint_tx_latency_*/tendermint_health_latency_*/"
          "tendermint_trace_*/tendermint_validator_*/"
          "tendermint_lightserve_* metrics, the "
          "tx_latency timeline event, VALSTATS_EVENTS forensics events "
          "— has a docs/OBSERVABILITY.md row",
      triggers=("tmtpu/libs", "docs"))
def check(index: RepoIndex) -> List[Finding]:
    required = []  # (kind, name, source rel)
    for prom in sorted(set(index.metric_defs().values())):
        if prom.startswith(_PREFIXES):
            required.append(("metric", prom, _METRICS_MOD))
    stages = _str_tuple(index, _TXLAT_MOD, "TX_STAGES")
    for s in stages:
        required.append(("stage", s, _TXLAT_MOD))
    for m in _str_tuple(index, _TRACE_MOD, "TRACE_MARKS"):
        required.append(("mark", m, _TRACE_MOD))
    if stages:
        # the event kind exists exactly when the journey ring does
        required.append(("event", "tx_latency", "tmtpu/libs/timeline.py"))
    for e in _str_tuple(index, _VALSTATS_MOD, "VALSTATS_EVENTS"):
        required.append(("event", e, _VALSTATS_MOD))
    if not required:
        return []  # no tx-lifecycle surface in this tree

    doc_file = os.path.join(index.root, DOC_PATH)
    if not os.path.isfile(doc_file):
        return [Finding(
            "obs-docs", DOC_PATH,
            f"{DOC_PATH} is missing but the tree exports a tx-lifecycle "
            f"observability surface ({len(required)} documented names "
            f"required)",
            key="obs-docs::no-doc")]
    with open(doc_file, encoding="utf-8") as fh:
        doc_src = fh.read()

    findings = []
    for kind, name, src in required:
        if f"`{name}`" not in doc_src:
            findings.append(Finding(
                "obs-docs", DOC_PATH,
                f"{kind} {name!r} ({src}) has no `{name}` entry in "
                f"{DOC_PATH} — document what it measures and when it "
                f"fires",
                key=f"obs-docs::{kind}::{name}"))
    return findings
