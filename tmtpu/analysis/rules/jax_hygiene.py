"""jax-hygiene rule: keep the dispatch hot path async, bucketed, guarded.

Three checks over the TPU dispatch tier — each guards one of the
batching wins behind the throughput headline:

- **host-sync** (interprocedural, via ``callgraph.Analyzer``): a
  device→host synchronization point — ``.item()``, ``device_get``,
  ``np.asarray`` readback, ``block_until_ready``, ``float()`` of a
  computed value — reachable from a hot flush path
  (``*BatchVerifier._verify_pending``, the mesh dispatch twins, the
  sidecar ``Coalescer._dispatch``). Each flush needs exactly ONE
  deliberate readback of the verdict mask; those sites are baselined
  with that justification, and anything else stalls the pipeline.
- **bucket-bypass** (per-file): a call to a ``@jax.jit``-compiled
  kernel from a function that never references the shape quantizer
  (``_pad_to_bucket`` / ``pad_args_to_bucket`` / ``padded_lanes`` /
  ``DEFAULT_TILE``) — raw batch sizes mean one fresh multi-second XLA
  compile per odd size (a recompile storm).
- **unguarded-dispatch**: a call site of the public ``batch_verify*``
  family outside ``tmtpu/tpu/`` whose enclosing function shows no
  breaker/fault discipline (no ``breaker``/``allow``/``guard``/
  ``_dispatch`` wrapper, no fault-injection site) — a device failure
  there escapes the `crypto.*` breaker state machine and has no chaos
  coverage.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tmtpu.analysis.callgraph import Analyzer
from tmtpu.analysis.findings import Finding
from tmtpu.analysis.index import RepoIndex
from tmtpu.analysis.registry import rule

# hot flush entry points: (class-name-or-None, method/function name)
HOT_SEEDS: Tuple[Tuple[Optional[str], str], ...] = (
    (None, "_verify_pending"),           # every *BatchVerifier flush
    ("Coalescer", "_dispatch"),          # sidecar batching loop
    (None, "batch_verify_mesh"),         # mesh dispatch twins
    (None, "batch_verify_tally_mesh"),
)
# markers only count inside the dispatch tier — a float() in some cold
# config helper reached through a deep chain is noise, not a stall
HOT_RELS = ("tmtpu/crypto/", "tmtpu/tpu/", "tmtpu/sidecar/")

QUANTIZER_TOKENS = {"_pad_to_bucket", "pad_args_to_bucket", "padded_lanes",
                    "pad_packed", "DEFAULT_TILE"}
DISPATCH_FNS = {"batch_verify", "batch_verify_sr", "batch_verify_k1",
                "batch_verify_tally", "batch_verify_mesh",
                "batch_verify_tally_mesh"}
GUARD_TOKENS = {"breaker", "allow", "guard", "fire", "_dispatch",
                "note_failure", "with_fallback"}


# ------------------------------------------------------------- host-sync

def _sync_marker(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return "host-sync:item"
        if f.attr == "block_until_ready":
            return "host-sync:block_until_ready"
        if f.attr == "device_get":
            return "host-sync:device_get"
        if f.attr == "asarray" and isinstance(f.value, ast.Name) and \
                f.value.id in ("np", "numpy"):
            return "host-sync:np.asarray"
    elif isinstance(f, ast.Name):
        if f.id == "block_until_ready":
            return "host-sync:block_until_ready"
        if f.id == "device_get":
            return "host-sync:device_get"
        if f.id == "float" and node.args and \
                isinstance(node.args[0], (ast.Subscript, ast.Call)):
            # float(arr[0]) / float(jnp.sum(...)) force a device fence;
            # float(name)/float(const) is host arithmetic and exempt
            return "host-sync:float"
    return None


def _check_host_sync(index: RepoIndex) -> List[Finding]:
    an = Analyzer(index, marker_fn=_sync_marker)
    findings, seen = [], set()
    entries = []
    for cls_name, meth in HOT_SEEDS:
        if cls_name is None and meth.startswith("batch_"):
            for rel, fn in an._functions_by_name.get(meth, []):
                entries.append((None, fn, rel, meth))
        else:
            for cls in an._methods_by_name.get(meth, []):
                if cls_name is not None and cls.name != cls_name:
                    continue
                entries.append((cls, cls.methods[meth], cls.rel, meth))
    for cls, fn, rel, meth in entries:
        entry = f"{cls.name}.{meth}" if cls is not None else meth
        for ev in an.events(cls, fn=fn, rel=rel):
            if ev.kind != "marker" or \
                    not ev.label.startswith("host-sync:"):
                continue
            if not ev.rel.startswith(HOT_RELS):
                continue
            key = f"jax-hygiene::{ev.label}::{entry}::{ev.rel}" \
                  f"::{ev.chain[-1]}"
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "jax-hygiene", ev.rel,
                f"{ev.label.split(':', 1)[1]} on the hot flush path "
                f"{entry}: {ev.rel}:{ev.line} via {ev.via()} — each "
                f"flush should sync the device exactly once, on the "
                f"verdict mask",
                line=ev.line, key=key))
    return findings


# --------------------------------------------------------- bucket-bypass

def _is_jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = node.attr if isinstance(node, ast.Attribute) else \
            node.id if isinstance(node, ast.Name) else ""
        if name == "jit":
            return True
        if isinstance(dec, ast.Call):          # partial(jax.jit, ...)
            for arg in dec.args:
                n = arg.attr if isinstance(arg, ast.Attribute) else \
                    arg.id if isinstance(arg, ast.Name) else ""
                if n == "jit":
                    return True
    return False


def _fn_tokens(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _top_level_functions(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _check_bucket_bypass(index: RepoIndex) -> List[Finding]:
    findings = []
    for fi in index.files("tmtpu"):
        if fi.tree is None:
            continue
        jit_fns = {name for name, fn in _top_level_functions(fi.tree)
                   if _is_jit_decorated(fn)}
        if not jit_fns:
            continue
        for qual, fn in _top_level_functions(fi.tree):
            if fn.name in jit_fns:
                continue                      # jit fns may chain to each other
            called = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in jit_fns:
                    called.add((node.func.id, node.lineno))
            if not called:
                continue
            if _fn_tokens(fn) & QUANTIZER_TOKENS:
                continue
            for callee, line in sorted(called):
                findings.append(Finding(
                    "jax-hygiene", fi.rel,
                    f"{qual} dispatches jit kernel {callee}() without "
                    f"quantizing lane shapes through _pad_to_bucket — "
                    f"every odd batch size triggers a fresh XLA compile",
                    line=line,
                    key=f"jax-hygiene::bucket-bypass::{fi.rel}::{qual}"
                        f"::{callee}"))
    return findings


# ----------------------------------------------------- unguarded-dispatch

def _check_unguarded_dispatch(index: RepoIndex) -> List[Finding]:
    findings = []
    for fi in index.files("tmtpu"):
        if fi.tree is None or fi.rel.startswith("tmtpu/tpu/"):
            continue                          # definitions live there
        for qual, fn in _top_level_functions(fi.tree):
            sites = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else ""
                if name in DISPATCH_FNS:
                    sites.append((name, node.lineno))
            if not sites:
                continue
            if _fn_tokens(fn) & GUARD_TOKENS:
                continue
            for name, line in sorted(sites):
                findings.append(Finding(
                    "jax-hygiene", fi.rel,
                    f"{qual} calls {name}() outside any crypto.* breaker "
                    f"or fault site — a device failure here escapes the "
                    f"breaker state machine",
                    line=line,
                    key=f"jax-hygiene::unguarded-dispatch::{fi.rel}"
                        f"::{qual}::{name}"))
    return findings


@rule("jax-hygiene",
      doc="no stray host-sync on hot flush paths, no jit dispatch "
          "bypassing the _pad_to_bucket shape quantizer, no batch_verify* "
          "call outside a crypto.* breaker or fault site",
      triggers=("tmtpu/crypto", "tmtpu/tpu", "tmtpu/sidecar", "tmtpu"))
def check(index: RepoIndex) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_host_sync(index)
    findings += _check_bucket_bypass(index)
    findings += _check_unguarded_dispatch(index)
    return findings
