"""CLI (reference: cmd/tendermint/main.go:15-56) —
``python -m tmtpu.cmd <command>``.

Commands: init, start, testnet, rollback, replay, version, show-node-id,
show-validator, gen-validator, unsafe-reset-all.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

from tmtpu import version as ver
from tmtpu.config.config import Config


def _load_config(home: str) -> Config:
    """config.toml (reference layout) wins; legacy config.json still
    loads; env TMTPU_<SECTION>_<FIELD> overrides either."""
    from tmtpu.config import toml as cfg_toml

    toml_path = os.path.join(os.path.expanduser(home), "config",
                             "config.toml")
    if os.path.exists(toml_path):
        cfg = cfg_toml.load_config(toml_path)
        cfg.base.home = home
        return cfg
    cfg = Config.default()
    cfg.base.home = home
    cfg_path = os.path.join(os.path.expanduser(home), "config",
                            "config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            data = json.load(f)
        for section, vals in data.items():
            obj = getattr(cfg, section, None)
            if obj is None:
                continue
            for k, v in vals.items():
                if hasattr(obj, k):
                    setattr(obj, k, v)
    cfg_toml._apply_env_overrides(cfg)  # env wins on every config path
    return cfg


def cmd_init(args) -> int:
    """init — private validator, node key, genesis (commands/init.go)."""
    from tmtpu.privval.file_pv import FilePV
    from tmtpu.types.genesis import GenesisDoc, GenesisValidator

    cfg = _load_config(args.home)
    home = os.path.expanduser(args.home)
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.load_or_generate(
        cfg.rooted(cfg.base.priv_validator_key_file),
        cfg.rooted(cfg.base.priv_validator_state_file))
    gen_path = cfg.genesis_path
    if not os.path.exists(gen_path):
        doc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=time.time_ns(),
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        )
        doc.save_as(gen_path)
        print(f"Generated genesis file: {gen_path}")
    else:
        print(f"Found genesis file: {gen_path}")
    # write default config.toml if absent (config/toml.go writer)
    cfg_path = os.path.join(home, "config", "config.toml")
    if not os.path.exists(cfg_path):
        from tmtpu.config import toml as cfg_toml

        cfg_toml.write_config(cfg, cfg_path)
        print(f"Generated config file: {cfg_path}")
    print(f"Validator address: {pv.address().hex().upper()}")
    return 0


def _rpc_dumps(rpc_laddr: str, out_dir: str) -> None:
    """Fetch the standard debug RPC dumps into ``out_dir``
    (debug/util.go dumpStatus/dumpNetInfo/dumpConsensusState)."""
    import urllib.request

    base = rpc_laddr.replace("tcp://", "http://")
    for name in ("status", "consensus_state", "dump_consensus_state",
                 "net_info", "num_unconfirmed_txs"):
        try:
            with urllib.request.urlopen(f"{base}/{name}", timeout=10) as r:
                body = r.read()
            with open(os.path.join(out_dir, f"{name}.json"), "wb") as f:
                f.write(body)
        except Exception as e:  # noqa: BLE001
            print(f"  {name}: {e}", file=sys.stderr)


def _copy_home_debug(home: str, out_dir: str) -> None:
    """WAL + config copies for a debug archive (debug/kill.go
    copyWAL/copyConfig)."""
    cfg = _load_config(home)
    wal_dir = os.path.dirname(cfg.rooted(cfg.consensus.wal_file))
    if os.path.isdir(wal_dir):
        shutil.copytree(wal_dir, os.path.join(out_dir, "cs.wal"),
                        dirs_exist_ok=True)
    conf_dir = cfg.rooted("config")
    if os.path.isdir(conf_dir):
        os.makedirs(os.path.join(out_dir, "config"), exist_ok=True)
        # never exfiltrate PRIVATE KEYS into a debug archive that gets
        # shared around — resolve the configured paths, not hardcoded
        # names (priv_validator_key_file is operator-settable)
        secret_paths = {
            os.path.realpath(cfg.rooted(cfg.base.priv_validator_key_file)),
            os.path.realpath(cfg.rooted(cfg.base.node_key_file)),
        }
        for fn in os.listdir(conf_dir):
            src = os.path.join(conf_dir, fn)
            if os.path.realpath(src) in secret_paths:
                continue
            if os.path.isfile(src):
                shutil.copy2(src, os.path.join(out_dir, "config", fn))


def cmd_debug_dump(args) -> int:
    """debug dump [dir] — poll a node's state every --frequency seconds
    into timestamped archives (commands/debug/dump.go); --iterations
    bounds the loop (the reference polls forever)."""
    import tempfile
    import zipfile

    out_root = os.path.expanduser(args.output_dir)
    os.makedirs(out_root, exist_ok=True)
    it = 0
    while True:
        it += 1
        stamp = time.strftime("%Y%m%d-%H%M%S")
        with tempfile.TemporaryDirectory() as tmp:
            _rpc_dumps(args.rpc_laddr, tmp)
            # iteration suffix: sub-second --frequency must not
            # overwrite the previous archive (same-second stamp)
            archive = os.path.join(out_root, f"{stamp}-{it:04d}.zip")
            with zipfile.ZipFile(archive, "w",
                                 zipfile.ZIP_DEFLATED) as z:
                for fn in sorted(os.listdir(tmp)):
                    z.write(os.path.join(tmp, fn), fn)
        print(f"Wrote debug archive {archive}")
        if args.iterations and it >= args.iterations:
            return 0
        time.sleep(args.frequency)


def cmd_debug_kill(args) -> int:
    """debug kill <pid> <out.zip> — aggregate node state (RPC dumps +
    WAL + config), archive it, then SIGABRT the process
    (commands/debug/kill.go)."""
    import signal as _signal
    import tempfile
    import zipfile

    with tempfile.TemporaryDirectory() as tmp:
        _rpc_dumps(args.rpc_laddr, tmp)
        try:
            _copy_home_debug(args.home, tmp)
        except Exception as e:  # noqa: BLE001
            print(f"  home copy: {e}", file=sys.stderr)
        out = os.path.expanduser(args.out_file)
        with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _dirs, files in os.walk(tmp):
                for fn in files:
                    p = os.path.join(root, fn)
                    z.write(p, os.path.relpath(p, tmp))
    print(f"Wrote debug archive {out}")
    try:
        os.kill(args.pid, _signal.SIGABRT)
        print(f"Sent SIGABRT to {args.pid}")
    except ProcessLookupError:
        print(f"no such process {args.pid}", file=sys.stderr)
        return 1
    return 0


def cmd_start(args) -> int:
    """start — run the node (commands/run_node.go:100)."""
    import faulthandler

    from tmtpu.node.node import Node

    cfg = _load_config(args.home)
    # deadlock observability (the reference's deadlock build tag + debug
    # kill): SIGUSR1 dumps every thread's stack to stderr
    try:
        faulthandler.register(signal.SIGUSR1, all_threads=True)
    except (AttributeError, ValueError):
        pass
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.crypto_backend:
        cfg.base.crypto_backend = args.crypto_backend
    if getattr(args, "misbehaviors", ""):
        cfg.base.misbehaviors = args.misbehaviors
    node = Node(cfg)
    node.start()
    rpc = node.rpc_server
    print(f"Node started. chain_id={node.chain_id}"
          + (f" rpc=127.0.0.1:{rpc.port}" if rpc else ""))
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        print("Stopping node...")
        node.stop()
    return 0


def cmd_sidecar(args) -> int:
    """sidecar — run the standalone verification daemon: one process
    owns the JAX device and serves batched verify (+ on-device tally)
    to every node on the host; nodes select it with
    ``crypto_backend=sidecar``. Address resolution: --addr flag,
    [sidecar] addr, TMTPU_SIDECAR_ADDR, then <home>/data/sidecar.sock."""
    from tmtpu.sidecar.client import default_addr
    from tmtpu.sidecar.server import SidecarServer

    cfg = _load_config(args.home)
    addr = (args.addr or cfg.sidecar.addr or
            default_addr(os.path.expanduser(args.home)))
    if args.backend:
        cfg.sidecar.backend = args.backend
    os.makedirs(os.path.join(os.path.expanduser(args.home), "data"),
                exist_ok=True)
    # the daemon's engine shares crypto/batch.py with a node process, so
    # the [crypto] resilience knobs (breaker, deadlines, sigcache) apply
    from tmtpu.crypto import batch as crypto_batch

    crypto_batch.configure(cfg.crypto)
    server = SidecarServer(
        addr,
        backend=cfg.sidecar.backend,
        max_queue_lanes=cfg.sidecar.max_queue_lanes,
        max_lanes_per_dispatch=cfg.sidecar.max_lanes_per_dispatch,
        max_frame_bytes=cfg.sidecar.max_frame_bytes,
        request_deadline_s=cfg.sidecar.request_deadline_ns / 1e9,
        health_laddr=args.health_laddr or cfg.sidecar.health_laddr,
        mesh_devices=cfg.sidecar.mesh_devices,
        shard_min_lanes=cfg.sidecar.shard_min_lanes)
    warm = cfg.sidecar.warm_on_start and not args.no_warm
    server.start()
    if warm:
        print("Warming verify kernels (one-time compile)...",
              flush=True)
        warm_s = server.warm()
        print(f"Warm-up done in {warm_s:.1f}s "
              f"(backend={server.backend_name()})")
    print(f"Sidecar listening on {server.addr} "
          f"backend={server.backend_name()} id={server.server_id}")
    # SIGINT stops immediately (operator ^C); SIGTERM drains first —
    # stop accepting, answer OVERLOADED (clients fall back in-process
    # penalty-free), finish in-flight joint dispatches, exit 0
    stop, term = [], []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: term.append(1))
    try:
        while not stop and not term:
            time.sleep(0.2)
        if term and not stop:
            print("SIGTERM: draining sidecar "
                  "(new requests get OVERLOADED)...", flush=True)
            clean = server.drain(
                timeout=cfg.sidecar.request_deadline_ns / 1e9 + 5.0)
            print("Drain complete" if clean
                  else "Drain timed out; stopping anyway")
    finally:
        print("Stopping sidecar...")
        server.stop()
    return 0


def cmd_lightserve(args) -> int:
    """lightserve — run the light-client commit-proof serving daemon:
    one process terminates many concurrent light-client sessions
    against a full node's RPC, answering from a trust-period-aware
    verified-fact cache and coalescing same-height cold misses into
    single joint resolves. Address resolution: --addr flag,
    [lightserve] addr, TMTPU_LIGHTSERVE_ADDR, then
    <home>/data/lightserve.sock."""
    from tmtpu.light.client import TrustOptions
    from tmtpu.light.provider import HTTPProvider
    from tmtpu.lightserve.client import default_addr
    from tmtpu.lightserve.server import LightserveServer

    cfg = _load_config(args.home)
    ls = cfg.lightserve
    addr = (args.addr or ls.addr or
            default_addr(os.path.expanduser(args.home)))
    upstream = (args.upstream or ls.upstream).rstrip("/")
    chain_id = args.chain_id or ls.chain_id
    trust_height = args.trust_height or ls.trust_height
    trust_hash = args.trust_hash or ls.trust_hash
    if not chain_id:
        print("lightserve needs a chain id (--chain-id or "
              "[lightserve] chain_id)")
        return 1
    if trust_height <= 0 or not trust_hash:
        print("lightserve needs a social-consensus trust anchor "
              "(--trust-height/--trust-hash or the [lightserve] pair)")
        return 1
    backend = args.backend or ls.backend
    os.makedirs(os.path.join(os.path.expanduser(args.home), "data"),
                exist_ok=True)
    # commit checks share crypto/batch.py, so [crypto] resilience knobs
    # apply; backend "sidecar" additionally coalesces them with every
    # other host process's lanes in the verification daemon
    from tmtpu.crypto import batch as crypto_batch

    crypto_batch.configure(cfg.crypto)
    if backend == "sidecar":
        crypto_batch.configure_sidecar(
            cfg.sidecar, home=os.path.expanduser(args.home))
    server = LightserveServer(
        addr, HTTPProvider(chain_id, upstream),
        TrustOptions(period_ns=ls.trusting_period_ns,
                     height=trust_height,
                     hash=bytes.fromhex(trust_hash)),
        chain_id,
        backend=None if backend == "auto" else backend,
        max_clock_drift_ns=ls.max_clock_drift_ns,
        max_client_skew_ns=ls.max_client_skew_ns,
        reply_workers=ls.reply_workers,
        cache_max_facts=ls.cache_max_facts,
        store_max_blocks=ls.store_max_blocks,
        max_queue_sessions=ls.max_queue_sessions,
        max_frame_bytes=ls.max_frame_bytes,
        request_deadline_s=ls.request_deadline_ns / 1e9,
        backwards_limit=ls.backwards_limit,
        health_laddr=args.health_laddr or ls.health_laddr,
        hit_rate_floor=ls.hit_rate_floor,
        hit_rate_min_lookups=ls.hit_rate_min_lookups,
        backlog_ceiling=ls.backlog_ceiling)
    server.start()  # fetches + verifies the trust anchor
    print(f"Lightserve listening on {server.addr} chain={chain_id} "
          f"anchor={trust_height} upstream={upstream} "
          f"id={server.server_id}")
    # SIGINT stops immediately; SIGTERM drains (new sessions answered
    # OVERLOADED, queued joint resolves finish) then exits 0
    stop, term = [], []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: term.append(1))
    try:
        while not stop and not term:
            time.sleep(0.2)
        if term and not stop:
            print("SIGTERM: draining lightserve "
                  "(new sessions get OVERLOADED)...", flush=True)
            clean = server.drain(
                timeout=ls.request_deadline_ns / 1e9 + 5.0)
            print("Drain complete" if clean
                  else "Drain timed out; stopping anyway")
    finally:
        print("Stopping lightserve...")
        server.stop()
    return 0


def cmd_version(args) -> int:
    print(ver.TMCoreSemVer)
    return 0


def cmd_show_validator(args) -> int:
    from tmtpu.privval.file_pv import FilePV

    cfg = _load_config(args.home)
    pv = FilePV.load(cfg.rooted(cfg.base.priv_validator_key_file),
                     cfg.rooted(cfg.base.priv_validator_state_file))
    from tmtpu.libs import amino_json

    pub = pv.get_pub_key()
    # reference `tendermint show-validator` prints the amino JSON form
    print(json.dumps(amino_json.marshal_pub_key(pub)))
    return 0


def cmd_gen_validator(args) -> int:
    from tmtpu.crypto import ed25519
    from tmtpu.libs import amino_json

    priv = ed25519.gen_priv_key()
    pub = priv.pub_key()
    # amino JSON shape (cmd/tendermint/commands/gen_validator.go) so the
    # output pastes into a reference genesis/priv_validator_key file
    print(json.dumps({
        "address": pub.address().hex().upper(),
        "pub_key": amino_json.marshal_pub_key(pub),
        "priv_key": amino_json.marshal_priv_key(priv),
    }, indent=2))
    return 0


def _reset_file_pv(key_file: str, state_file: str) -> None:
    """reset.go resetFilePV: existing key keeps its identity but the
    sign state returns to genesis (a FRESH zero state file — FilePV.load
    refuses to start without one); no key means generate both."""
    import json as _json

    from tmtpu.libs import amino_json
    from tmtpu.privval.file_pv import FilePV

    if os.path.exists(key_file):
        with open(key_file) as f:
            kd = _json.load(f)
        pv = FilePV(amino_json.unmarshal_priv_key(kd["priv_key"]),
                    key_file, state_file)
        os.makedirs(os.path.dirname(state_file) or ".", exist_ok=True)
        pv.save()
        print("Reset private validator file to genesis state")
    else:
        os.makedirs(os.path.dirname(key_file) or ".", exist_ok=True)
        os.makedirs(os.path.dirname(state_file) or ".", exist_ok=True)
        FilePV.generate(key_file, state_file)
        print("Generated private validator file")


def cmd_unsafe_reset_all(args) -> int:
    """Wipe data dir + addrbook, reset validator sign state to genesis
    (commands/reset.go resetAll)."""
    cfg = _load_config(args.home)
    if not getattr(args, "keep_addr_book", False):
        ab = cfg.rooted("config/addrbook.json")  # node.py:258 path
        if os.path.exists(ab):
            os.unlink(ab)
            print(f"Removed address book {ab}")
    else:
        print("The address book remains intact")
    data = cfg.rooted(cfg.base.db_dir)
    if os.path.isdir(data):
        shutil.rmtree(data)
        os.makedirs(data)
        print(f"Removed all data in {data}")
    _reset_file_pv(cfg.rooted(cfg.base.priv_validator_key_file),
                   cfg.rooted(cfg.base.priv_validator_state_file))
    return 0


def cmd_reset_state(args) -> int:
    """Remove the chain databases + WAL, keep keys AND validator sign
    state (commands/reset.go resetState)."""
    cfg = _load_config(args.home)
    data = cfg.rooted(cfg.base.db_dir)
    for name in ("blockstore.db", "state.db", "evidence.db",
                 "tx_index.db"):
        p = os.path.join(data, name)
        if os.path.exists(p):
            shutil.rmtree(p) if os.path.isdir(p) else os.unlink(p)
            print(f"Removed {p}")
    # the WAL lives wherever consensus.wal_file points (config.py:27) —
    # a stale WAL after a state wipe bricks startup with "#ENDHEIGHT >=
    # current height"
    wal_path = cfg.rooted(cfg.consensus.wal_file)
    wal_dir = os.path.dirname(wal_path)
    if os.path.basename(wal_dir) == "cs.wal":
        if os.path.isdir(wal_dir):
            shutil.rmtree(wal_dir)
            print(f"Removed {wal_dir}")
    else:
        # custom location: remove the group head + rotated segments only
        base = os.path.basename(wal_path)
        for fn in sorted(os.listdir(wal_dir)) if os.path.isdir(wal_dir) \
                else []:
            if fn == base or fn.startswith(base + "."):
                os.unlink(os.path.join(wal_dir, fn))
                print(f"Removed {os.path.join(wal_dir, fn)}")
    return 0


def cmd_unsafe_reset_priv_validator(args) -> int:
    """Reset this node's validator sign state to genesis
    (commands/reset.go ResetPrivValidatorCmd)."""
    cfg = _load_config(args.home)
    _reset_file_pv(cfg.rooted(cfg.base.priv_validator_key_file),
                   cfg.rooted(cfg.base.priv_validator_state_file))
    return 0


def cmd_gen_node_key(args) -> int:
    """Generate the node key and print its ID
    (commands/gen_node_key.go — errors if one already exists)."""
    from tmtpu.p2p.key import NodeKey

    cfg = _load_config(args.home)
    path = cfg.rooted(cfg.base.node_key_file)
    if os.path.exists(path):
        print(f"node key at {path!r} already exists", file=sys.stderr)
        return 1
    nk = NodeKey.load_or_gen(path)
    print(nk.node_id)
    return 0


def cmd_probe_upnp(args) -> int:
    """Probe the LAN for a UPnP IGD and report its external IP
    (commands/probe_upnp.go)."""
    import json as _json

    from tmtpu.p2p import upnp

    gw = upnp.discover(timeout_s=args.timeout)
    if gw is None:
        print(_json.dumps({"success": False}))
        return 1
    out = {"success": True, "control_url": gw.control_url,
           "service": gw.service}
    try:
        out["external_ip"] = gw.external_ip()
    except Exception as e:  # noqa: BLE001 — gateway present, call failed
        out["external_ip_error"] = repr(e)
    print(_json.dumps(out))
    return 0


def cmd_replay_console(args) -> int:
    """replay-console — step through the consensus WAL's in-progress
    height one message at a time (commands/replay.go replay-console):
    app replay via handshake first, then each WAL message is printed and
    applied on Enter (or immediately with --no-input)."""
    import json as _json

    from tmtpu.node.node import Node

    cfg = _load_config(args.home)
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    node = Node(cfg)  # handshake replays the app to the store height

    def on_msg(m):
        print("--> " + _json.dumps(_proto_to_jsonable(m)))
        if not args.no_input:
            input("press Enter to apply...")

    try:
        cs = node.consensus
        cs.do_wal_catchup = False  # we drive it ourselves
        # mirror on_start's recovery sequence (state.py:148-151), minus
        # the live round re-drive: an inspection tool must never sign or
        # append to the WAL it is examining
        cs._reconstruct_last_commit()
        cs.catchup_replay(on_msg=on_msg, live_redrive=False)
        rs = cs.rs
        print(f"Replayed console to height {rs.height}, round {rs.round}, "
              f"step {rs.step}")
    finally:
        # the node was never start()ed, so node.stop() would no-op
        # (libs/service.py guards on _started) — shut the pieces that
        # Node.__init__ opened down explicitly
        if node.consensus.wal is not None:
            node.consensus.wal.close()
        node.proxy_app.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from tmtpu.p2p.key import NodeKey

    cfg = _load_config(args.home)
    nk = NodeKey.load_or_gen(cfg.rooted(cfg.base.node_key_file))
    print(nk.node_id)
    return 0


def cmd_rollback(args) -> int:
    """rollback — state back one height (commands/rollback.go)."""
    from tmtpu.state.rollback import RollbackError, rollback
    from tmtpu.state.store import StateStore
    from tmtpu.store.block_store import BlockStore
    from tmtpu.libs.db import SQLiteDB

    cfg = _load_config(args.home)
    if cfg.base.db_backend != "sqlite":
        print("rollback requires a persistent (sqlite) db_backend",
              file=sys.stderr)
        return 1
    data = cfg.rooted(cfg.base.db_dir)
    bs = BlockStore(SQLiteDB(os.path.join(data, "blockstore.sqlite")))
    ss = StateStore(SQLiteDB(os.path.join(data, "state.sqlite")))
    try:
        height, app_hash = rollback(bs, ss)
    except RollbackError as e:
        print(f"rollback failed: {e}", file=sys.stderr)
        return 1
    print(f"Rolled back state to height {height} and hash "
          f"{app_hash.hex().upper()}")
    return 0


def cmd_replay(args) -> int:
    """replay — re-sync the app from the block store via handshake
    (commands/replay.go)."""
    from tmtpu.node.node import Node

    cfg = _load_config(args.home)
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    node = Node(cfg)  # the constructor's handshake IS the replay
    print(f"Replayed to height {node.state.last_block_height}, app hash "
          f"{node.state.app_hash.hex().upper()}")
    node.stop()
    return 0


def cmd_testnet(args) -> int:
    """testnet — N validator home dirs wired full-mesh
    (commands/testnet.go)."""
    from tmtpu.config import toml as cfg_toml
    from tmtpu.privval.file_pv import FilePV
    from tmtpu.p2p.key import NodeKey
    from tmtpu.types.genesis import GenesisDoc, GenesisValidator

    out = os.path.expanduser(args.output_dir)
    n = args.validators
    base_p2p, base_rpc = args.starting_port, args.starting_port + 1000
    pvs, node_ids = [], []
    homes = []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        homes.append(home)
        cfg = Config.default()
        cfg.base.home = home
        pvs.append(FilePV.load_or_generate(
            cfg.rooted(cfg.base.priv_validator_key_file),
            cfg.rooted(cfg.base.priv_validator_state_file)))
        node_ids.append(NodeKey.load_or_gen(
            cfg.rooted(cfg.base.node_key_file)).node_id)
    gen = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=time.time_ns(),
        validators=[GenesisValidator(pv.get_pub_key(), 1) for pv in pvs],
    )
    peers = [f"{node_ids[i]}@127.0.0.1:{base_p2p + i}" for i in range(n)]
    for i, home in enumerate(homes):
        cfg = Config.default()
        cfg.base.home = home
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_p2p + i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_rpc + i}"
        cfg.p2p.persistent_peers = ",".join(
            p for j, p in enumerate(peers) if j != i)
        gen.save_as(cfg.genesis_path)
        cfg_toml.write_config(
            cfg, os.path.join(home, "config", "config.toml"))
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_reindex_event(args) -> int:
    """reindex-event — rebuild tx/block-event indexes from the stores
    (commands/reindex_event.go)."""
    from tmtpu.libs.db import SQLiteDB
    from tmtpu.state.store import StateStore
    from tmtpu.state.txindex import (
        KVBlockIndexer, KVTxIndexer, reindex_events,
    )
    from tmtpu.store.block_store import BlockStore

    cfg = _load_config(args.home)

    def db(name):
        return SQLiteDB(cfg.rooted(os.path.join(cfg.base.db_dir,
                                                f"{name}.sqlite")))

    n = reindex_events(BlockStore(db("blockstore")), StateStore(db("state")),
                       KVTxIndexer(db("txindex")),
                       KVBlockIndexer(db("blockindex")),
                       first=args.start_height, last=args.end_height)
    print(f"Reindexed {n} heights")
    return 0


def cmd_compact_db(args) -> int:
    """experimental-compact-goleveldb analogue — VACUUM every sqlite DB in
    the data dir to reclaim space after pruning."""
    import sqlite3

    cfg = _load_config(args.home)
    data = cfg.rooted(cfg.base.db_dir)
    total = 0
    for fname in sorted(os.listdir(data) if os.path.isdir(data) else []):
        if not fname.endswith(".sqlite"):
            continue
        path = os.path.join(data, fname)
        before = os.path.getsize(path)
        conn = sqlite3.connect(path)
        conn.execute("VACUUM")
        conn.close()
        after = os.path.getsize(path)
        total += before - after
        print(f"{fname}: {before} -> {after} bytes")
    print(f"Reclaimed {total} bytes")
    return 0


def cmd_light(args) -> int:
    """light — run a light-client-backed RPC proxy daemon
    (commands/light.go)."""
    import threading

    from tmtpu.light.client import Client, TrustOptions
    from tmtpu.light.provider import HTTPProvider
    from tmtpu.light.proxy import LightProxy
    from tmtpu.light.store import LightStore
    from tmtpu.libs.db import SQLiteDB

    primary = args.primary.rstrip("/")
    witnesses = [w for w in (args.witnesses or "").split(",") if w]
    home = os.path.expanduser(args.home)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    store = LightStore(SQLiteDB(os.path.join(home, "data", "light.sqlite")))
    lc = Client(
        args.chain_id,
        TrustOptions(period_ns=int(args.trusting_period * 1e9),
                     height=args.trusted_height,
                     hash=bytes.fromhex(args.trusted_hash)),
        HTTPProvider(args.chain_id, primary),
        witnesses=[HTTPProvider(args.chain_id, w) for w in witnesses],
        store=store,
    )
    proxy = LightProxy(lc, primary, laddr=args.laddr)
    proxy.start()
    print(f"light proxy for {args.chain_id} listening on {proxy.laddr} "
          f"(primary {primary}, {len(witnesses)} witnesses)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        proxy.stop()
    return 0


def _proto_to_jsonable(m):
    """Generic ProtoMessage -> JSON-able dict (bytes as hex, nested
    messages recursed, absent fields omitted) — the wal2json view."""
    from tmtpu.libs.protoio import ProtoMessage

    if isinstance(m, ProtoMessage):
        out = {}
        for _, name, _spec in m.FIELDS:
            v = getattr(m, name)
            if v is not None:
                out[name] = _proto_to_jsonable(v)
        return out
    if isinstance(m, (bytes, bytearray)):
        return bytes(m).hex()
    if isinstance(m, list):
        return [_proto_to_jsonable(x) for x in m]
    return m


def _jsonable_to_proto(cls, data):
    """Inverse of _proto_to_jsonable for a known message class."""
    kw = {}
    for _, name, spec in cls.FIELDS:
        if name not in data:
            continue
        v = data[name]
        kind = spec[0] if isinstance(spec, tuple) else spec
        if kind in ("msg", "msg!"):
            kw[name] = _jsonable_to_proto(spec[1], v)
        elif kind == "rep":
            inner = spec[1]
            if isinstance(inner, tuple):  # ("msg"/"msg!", cls)
                kw[name] = [_jsonable_to_proto(inner[1], x) for x in v]
            elif inner == "bytes":
                kw[name] = [bytes.fromhex(x) for x in v]
            else:
                kw[name] = list(v)
        elif kind == "bytes":
            kw[name] = bytes.fromhex(v)
        else:
            kw[name] = v
    return cls(**kw)


def cmd_wal2json(args) -> int:
    """wal2json — decode a consensus WAL to JSON lines (reference
    scripts/wal2json/main.go). Tolerates a torn tail unless --strict."""
    import json as _json

    from tmtpu.consensus.wal import WAL

    for msg in WAL.iter_messages(args.wal_file, strict=args.strict):
        print(_json.dumps(_proto_to_jsonable(msg)))
    return 0


def cmd_json2wal(args) -> int:
    """json2wal — rebuild a WAL file from wal2json output (reference
    scripts/json2wal/main.go; used to craft replay/corruption fixtures)."""
    import json as _json
    import struct
    import zlib

    from tmtpu.consensus.wal import WALMessagePB
    from tmtpu.libs import protoio

    with open(args.json_file) as jf, open(args.wal_file, "wb") as wf:
        for line in jf:
            line = line.strip()
            if not line:
                continue
            msg = _jsonable_to_proto(WALMessagePB, _json.loads(line))
            payload = msg.encode()
            wf.write(struct.pack(">I", zlib.crc32(payload))
                     + protoio.encode_uvarint(len(payload)) + payload)
    return 0


def cmd_signer_harness(args) -> int:
    """signer-harness — remote-signer conformance checks
    (tools/tm-signer-harness/main.go)."""
    from tmtpu.privval.harness import HarnessFailure, run_harness

    expect = bytes.fromhex(args.expect_pubkey) if args.expect_pubkey else None
    try:
        return run_harness(args.laddr, args.chain_id,
                           accept_deadline_s=args.accept_deadline,
                           expect_pubkey=expect)
    except HarnessFailure as e:
        print(f"FAIL {e}")
        return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tmtpu",
                                description="TPU-native BFT consensus node")
    p.add_argument("--home", default=os.environ.get("TMHOME", "~/.tmtpu"))
    _sub = p.add_subparsers(dest="cmd", required=True)

    class _Sub:
        """--home is accepted before OR after the subcommand, like the
        reference's cobra persistent flag; SUPPRESS keeps the subparser
        from clobbering a pre-subcommand --home with its default."""

        @staticmethod
        def add_parser(*a, **kw):
            sp = _sub.add_parser(*a, **kw)
            sp.add_argument("--home", default=argparse.SUPPRESS)
            return sp

    sub = _Sub()

    sp = sub.add_parser("init", help="initialize home dir")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy-app", default="")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp.add_argument("--crypto-backend", default="",
                    choices=["", "auto", "cpu", "tpu", "sidecar"])
    sp.add_argument("--misbehaviors", default="",
                    help="maverick-style schedule 'double-prevote@3,...' "
                         "(byzantine test nets only)")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("sidecar",
                        help="run the shared batch-verify daemon")
    sp.add_argument("--addr", default="",
                    help="listen address (unix:///path.sock or "
                         "tcp://host:port); default [sidecar] addr / "
                         "TMTPU_SIDECAR_ADDR / <home>/data/sidecar.sock")
    sp.add_argument("--backend", default="",
                    choices=["", "auto", "cpu", "tpu"],
                    help="daemon-side verify engine")
    sp.add_argument("--health-laddr", dest="health_laddr", default="",
                    help="HTTP host:port for /healthz + /metrics")
    sp.add_argument("--no-warm", action="store_true",
                    help="skip the startup kernel warm-up compile")
    sp.set_defaults(fn=cmd_sidecar)

    sp = sub.add_parser("lightserve",
                        help="run the light-client commit-proof "
                             "serving daemon")
    sp.add_argument("--addr", default="",
                    help="listen address (unix:///path.sock or "
                         "tcp://host:port); default [lightserve] addr / "
                         "TMTPU_LIGHTSERVE_ADDR / "
                         "<home>/data/lightserve.sock")
    sp.add_argument("--upstream", default="",
                    help="full node RPC URL feeding the verified spine")
    sp.add_argument("--chain-id", dest="chain_id", default="")
    sp.add_argument("--trust-height", dest="trust_height", type=int,
                    default=0)
    sp.add_argument("--trust-hash", dest="trust_hash", default="",
                    help="hex header hash at --trust-height")
    sp.add_argument("--backend", default="",
                    choices=["", "auto", "cpu", "tpu", "sidecar"],
                    help="commit-verify engine; 'sidecar' rides the "
                         "host's verification daemon")
    sp.add_argument("--health-laddr", dest="health_laddr", default="",
                    help="HTTP host:port for /healthz + /metrics")
    sp.set_defaults(fn=cmd_lightserve)

    sp = sub.add_parser("version")
    sp.set_defaults(fn=cmd_version)

    sp = sub.add_parser("show-validator")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("gen-validator")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("unsafe-reset-all")
    sp.add_argument("--keep-addr-book", action="store_true",
                    help="keep the address book intact")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("reset-state",
                        help="remove the chain DBs + WAL, keep keys and "
                             "validator sign state")
    sp.set_defaults(fn=cmd_reset_state)

    sp = sub.add_parser("unsafe-reset-priv-validator",
                        help="reset validator sign state to genesis")
    sp.set_defaults(fn=cmd_unsafe_reset_priv_validator)

    sp = sub.add_parser("gen-node-key",
                        help="generate config/node_key.json, print its ID")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("probe-upnp", help="probe the LAN for a UPnP IGD")
    sp.add_argument("--timeout", type=float, default=3.0)
    sp.set_defaults(fn=cmd_probe_upnp)

    sp = sub.add_parser("replay-console",
                        help="step through the consensus WAL interactively")
    sp.add_argument("--no-input", action="store_true",
                    help="apply without pausing")
    sp.set_defaults(fn=cmd_replay_console)

    sp = sub.add_parser("show-node-id")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("rollback", help="roll state back one height")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("replay", help="re-sync the app from the stores")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("debug", help="capture a running node's state")
    dbg = sp.add_subparsers(dest="debug_cmd")
    dmp = dbg.add_parser("dump", help="poll + archive node state")
    dmp.add_argument("output_dir", nargs="?", default="./debug")
    dmp.add_argument("--rpc-laddr", dest="rpc_laddr",
                     default="tcp://127.0.0.1:26657")
    dmp.add_argument("--frequency", type=float, default=30.0)
    dmp.add_argument("--iterations", type=int, default=0,
                     help="stop after N archives (0 = forever, like the "
                          "reference)")
    dmp.set_defaults(fn=cmd_debug_dump)
    kil = dbg.add_parser("kill",
                         help="archive node state, then SIGABRT the pid")
    kil.add_argument("pid", type=int)
    kil.add_argument("out_file")
    kil.add_argument("--rpc-laddr", dest="rpc_laddr",
                     default="tcp://127.0.0.1:26657")
    kil.set_defaults(fn=cmd_debug_kill)
    # bare `tmtpu debug` behaves like one dump iteration (round-3 CLI)
    sp.set_defaults(fn=cmd_debug_dump, output_dir="./debug",
                    rpc_laddr="tcp://127.0.0.1:26657", frequency=30.0,
                    iterations=1)

    sp = sub.add_parser("reindex-event",
                        help="rebuild tx/block-event indexes from stores")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser("compact-db", help="VACUUM the data dir's DBs")
    sp.set_defaults(fn=cmd_compact_db)

    sp = sub.add_parser("light", help="light-client RPC proxy daemon")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True,
                    help="primary full node RPC URL")
    sp.add_argument("--witnesses", default="",
                    help="comma-separated witness RPC URLs")
    sp.add_argument("--trusted-height", type=int, required=True)
    sp.add_argument("--trusted-hash", required=True)
    sp.add_argument("--trusting-period", type=float,
                    default=7 * 24 * 3600.0, help="seconds")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("wal2json", help="decode a WAL to JSON lines")
    sp.add_argument("wal_file")
    sp.add_argument("--strict", action="store_true",
                    help="fail on torn/corrupt records instead of stopping")
    sp.set_defaults(fn=cmd_wal2json)

    sp = sub.add_parser("json2wal",
                        help="rebuild a WAL from wal2json output")
    sp.add_argument("json_file")
    sp.add_argument("wal_file")
    sp.set_defaults(fn=cmd_json2wal)

    sp = sub.add_parser("signer-harness",
                        help="remote-signer conformance checks")
    sp.add_argument("chain_id")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:0",
                    help="address the external signer dials "
                         "(tcp:// or unix://)")
    sp.add_argument("--accept-deadline", type=float, default=30.0,
                    help="seconds to wait for the signer to connect")
    sp.add_argument("--expect-pubkey", default="",
                    help="hex pubkey the signer must serve")
    sp.set_defaults(fn=cmd_signer_harness)

    sp = sub.add_parser("testnet", help="generate N validator home dirs")
    sp.add_argument("--validators", type=int, default=4)
    sp.add_argument("--output-dir", dest="output_dir", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", dest="starting_port", type=int,
                    default=26656)
    sp.set_defaults(fn=cmd_testnet)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
