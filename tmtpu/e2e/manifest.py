"""Testnet manifest (reference: test/e2e/pkg/manifest.go:11).

A manifest describes a testnet declaratively: the nodes (validators and
full nodes, with per-node start heights for catch-up testing), the tx load
to apply, and the perturbations to inject while the net runs. Loadable
from TOML::

    chain_id = "e2e-net"
    [load]
    rate = 50.0
    [[node]]
    name = "v0"
    [[node]]
    name = "late"
    validator = false
    start_at = 5
    [[perturbation]]
    node = "v1"
    op = "restart"
    at_height = 8
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API under the old name
    import tomli as tomllib

from dataclasses import dataclass, field


@dataclass
class NodeSpec:
    name: str
    validator: bool = True
    power: int = 100
    start_at: int = 0          # join once the net reaches this height
    key_type: str = "ed25519"  # ed25519 | sr25519 | secp256k1
    # extra "section.key" -> value config overrides for this node
    config: dict = field(default_factory=dict)
    misbehaviors: dict = field(default_factory=dict)  # height -> name
    # extra environment for the node subprocess (e.g. TMTPU_SIDECAR_ADDR)
    env: dict = field(default_factory=dict)


@dataclass
class Perturbation:
    node: str
    op: str                    # kill | restart | pause | disconnect
    at_height: int = 0         # trigger when any node reaches this height
    delay_s: float = 1.0       # dwell time before revival (restart/pause)


@dataclass
class LoadSpec:
    rate: float = 20.0         # tx/s offered
    size: int = 32             # tx payload bytes


@dataclass
class Manifest:
    chain_id: str = "e2e-testnet"
    nodes: list[NodeSpec] = field(default_factory=list)
    perturbations: list[Perturbation] = field(default_factory=list)
    load: LoadSpec = field(default_factory=LoadSpec)
    target_height: int = 12    # run until every node reaches this
    timeout_s: float = 120.0
    # e2e nets run the FAST consensus profile (~7x shorter timeouts than
    # production), so the genesis block-size cap scales down with them —
    # the reference pairs 21 MiB blocks with a 3 s propose timeout; an
    # uncapped block at a 400 ms timeout can't reach peers in time and
    # every round fails until load stops (observed livelock)
    block_max_bytes: int = 262144

    @staticmethod
    def from_toml(path: str) -> "Manifest":
        with open(path, "rb") as f:
            data = tomllib.load(f)
        m = Manifest(chain_id=data.get("chain_id", "e2e-testnet"),
                     target_height=data.get("target_height", 12),
                     timeout_s=data.get("timeout_s", 120.0),
                     block_max_bytes=data.get("block_max_bytes", 262144))
        for nd in data.get("node", []):
            m.nodes.append(NodeSpec(**{
                k: v for k, v in nd.items()
                if k in {f.name for f in dataclasses.fields(NodeSpec)}}))
        for pb in data.get("perturbation", []):
            m.perturbations.append(Perturbation(**pb))
        if "load" in data:
            m.load = LoadSpec(**data["load"])
        if not m.nodes:
            m.nodes = [NodeSpec(name=f"validator{i:02d}") for i in range(4)]
        return m
