"""One shared boot path for subprocess localnets.

Three consumers used to hand-roll the same Manifest + Runner ceremony —
tools/fleet_report.py (fleet latency report), tmtpu/scenario/net.py
(adversarial scenario nets) and the tools/ that grew out of them
(tools/critical_path.py, via tools/ab_common.py's re-export). Each one
re-invented "N validators named v00..vNN, full mesh, a LoadSpec, setup/
start/start_load, then tear it all down". This module owns that shape:

    make_manifest()  the declarative half — one place that knows how a
                     name list + per-node config dicts become NodeSpecs;
    booted()         the process half — a context manager guaranteeing
                     runner.stop() (and so SIGTERM to every node child)
                     on every exit path, load threads included.

Scenario nets keep their own Runner subclass and fault timeline; they
share only make_manifest. Report tools use both.
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Callable, Dict, Iterable, Optional

from tmtpu.e2e.manifest import LoadSpec, Manifest, NodeSpec
from tmtpu.e2e.runner import Runner


def validator_names(n: int) -> list:
    """The canonical localnet name scheme: v00, v01, ..."""
    return [f"v{i:02d}" for i in range(n)]


# A full mesh is fine for the nets this repo grew up on (3-8 nodes) but
# quadratic in gossip threads: every connection runs its own data- and
# vote-gossip routines, so 25 nodes x 24 peers x 2+ threads is ~2400
# wakeup loops fighting one GIL per process host — observed to starve
# consensus so badly a 25-validator net never commits height 1. Big
# nets dial a CHORD graph instead: node i dials i+1, i+2, i+4, ... 2^k
# (mod n). Degree is O(log n) (counting inbound, ~2 log n), the graph
# is vertex-transitive and connected, and any vote crosses it in at
# most log2(n) gossip hops.
#
# Above SPARSE_CHORD_NODES the offset list is capped at {1, 2, 4}: on a
# shared host every connection's threads occupy runqueue slots whether
# or not they poll often (a thread waiting for the GIL is runnable to
# the kernel), so message-hop latency scales with TOTAL thread count,
# not hop count. Degree 6 instead of ~2 log2 n trades a longer greedy
# route (~n/8 hops worst case) for ~40% fewer p2p threads net-wide —
# the better side of the trade once scheduling latency per hop runs
# into seconds.

MESH_MAX_NODES = 12
SPARSE_CHORD_NODES = 20
_SPARSE_OFFSETS = (1, 2, 4)


def chord_peer_names(names: Iterable[str]) -> Dict[str, list]:
    """Per-node dial plan: ``{name: [names it should dial]}``. Full
    mesh up to MESH_MAX_NODES (historic behavior for every small net);
    power-of-two chord offsets above it, capped at _SPARSE_OFFSETS for
    nets past SPARSE_CHORD_NODES."""
    names = list(names)
    n = len(names)
    if n <= MESH_MAX_NODES:
        return {a: [b for b in names if b != a] for a in names}
    offsets = []
    d = 1
    while d < n:
        offsets.append(d)
        d *= 2
    if n > SPARSE_CHORD_NODES:
        offsets = [o for o in offsets if o in _SPARSE_OFFSETS]
    return {names[i]: [names[(i + o) % n] for o in offsets]
            for i in range(n)}


# -- pooled / staggered startup (the 10-50 validator rung) --------------------
#
# Launching 25+ subprocess nodes simultaneously makes every one of them
# fight the same cores through interpreter startup + module import, the
# most CPU-hungry seconds of a node's life — observed to stretch a
# 25-node boot several-fold and trip RPC-up deadlines that a staggered
# launch sails through. Instead: launch in WAVES sized to the host
# (same cpu-derived cap as the generated-net ceiling, so one env knob —
# TMTPU_E2E_MAX_NODES — governs both how big a net may be and how many
# nodes may boot at once), gate each wave on its nodes accepting RPC
# within a per-node budget, then gate the whole net on /readyz (live
# AND caught up) instead of fixed sleeps.

BOOT_WAVE_ENV = "TMTPU_E2E_BOOT_WAVE"
BOOT_BUDGET_ENV = "TMTPU_E2E_BOOT_BUDGET_S"


def boot_wave_size() -> int:
    """Nodes launched per wave. ``TMTPU_E2E_BOOT_WAVE`` pins it;
    otherwise the generated-net node cap (cpu-derived,
    ``TMTPU_E2E_MAX_NODES``-overridable) doubles as the wave size — a
    net small enough to generate is small enough to launch at once."""
    env = os.environ.get(BOOT_WAVE_ENV, "")
    if env:
        return max(1, int(env))
    from tmtpu.e2e.generate import max_nodes
    return max_nodes()


def per_node_boot_budget_s() -> float:
    """Per-node readiness budget (seconds) for each boot phase;
    ``TMTPU_E2E_BOOT_BUDGET_S`` overrides."""
    env = os.environ.get(BOOT_BUDGET_ENV, "")
    return float(env) if env else 30.0


def wait_rpc_up(nodes, budget_s: float) -> None:
    """Every node in the wave must accept RPC within ``budget_s`` of
    the call (the wave was just launched, so this is its boot budget).
    Raises TimeoutError naming the first node that blew the budget."""
    deadline = time.monotonic() + budget_s
    pending = list(nodes)
    while pending:
        pending = [n for n in pending if n.height() < 0]
        if not pending:
            return
        if time.monotonic() > deadline:
            worst = pending[0]
            raise TimeoutError(
                f"{worst.spec.name} RPC not up within {budget_s:.0f}s "
                f"boot budget (see {worst.home}/node.log)")
        time.sleep(0.2)


def wait_ready(nodes, budget_s: float) -> None:
    """Readiness barrier: every node answers /readyz 200 (live AND
    caught up — consensus committing, watchdog green) within
    ``budget_s``. Nodes converge concurrently, so the budget is one
    shared window, not a per-node sum. Falls back to RPC-up for nodes
    without a pprof listener."""
    deadline = time.monotonic() + budget_s
    pending = list(nodes)
    while pending:
        pending = [n for n in pending if not n.ready()]
        if not pending:
            return
        if time.monotonic() > deadline:
            names = [n.spec.name for n in pending]
            raise TimeoutError(
                f"nodes never ready within {budget_s:.0f}s: {names} "
                f"(see {pending[0].home}/node.log)")
        time.sleep(0.3)


def staggered_start(nodes, *, wave_size: Optional[int] = None,
                    budget_s: Optional[float] = None,
                    ready_gate: Optional[bool] = None,
                    log: Optional[Callable[[str], None]] = None) -> None:
    """Launch ``nodes`` in pooled waves with readiness gating (see the
    section comment above). ``ready_gate`` defaults to on for multi-wave
    nets — exactly the nets whose first commit is slow enough that
    'RPC up' is not 'net live'; single-wave nets keep the historic
    cheap barrier unless explicitly asked."""
    nodes = list(nodes)
    wave_size = wave_size or boot_wave_size()
    budget_s = budget_s if budget_s is not None \
        else per_node_boot_budget_s()
    waves = [nodes[i:i + wave_size]
             for i in range(0, len(nodes), wave_size)]
    if ready_gate is None:
        ready_gate = len(waves) > 1
    for i, wave in enumerate(waves):
        if log and len(waves) > 1:
            log(f"boot wave {i + 1}/{len(waves)}: "
                f"{[n.spec.name for n in wave]}")
        for node in wave:
            node.start()
        # later waves launch into a host already running every earlier
        # wave's consensus loops: surcharge the budget per live process
        # or wave 3 of a 25-node net times out on interpreter startup
        wave_window = budget_s + 2.0 * (len(wave) + i * wave_size)
        try:
            wait_rpc_up(wave, wave_window)
        except TimeoutError as exc:
            # the wave gate PACES the launch (never 25 cold interpreters
            # at once); when the readiness barrier follows, it is the
            # correctness gate, so a slow-to-bind straggler is a log
            # line, not an abort. Without the barrier (single-wave
            # historic contract) RPC-up is the only gate: stay fatal.
            if not ready_gate:
                raise
            if log:
                log(f"boot wave {i + 1} straggler: {exc} "
                    f"(continuing; readiness gate will enforce)")
    if ready_gate:
        # first commit on a big single-host net is the slow part —
        # quorum lands mid-boot and consensus competes with the last
        # waves' interpreter startup for the same cores. One shared
        # window, surcharged per node.
        window = budget_s + 5.0 * len(nodes)
        if log:
            log(f"readiness gate: waiting /readyz on {len(nodes)} "
                f"nodes (window {window:.0f}s)")
        wait_ready(nodes, window)


def make_manifest(chain_id: str,
                  names: Iterable[str],
                  *,
                  base_config: Optional[Dict] = None,
                  node_config: Optional[Dict[str, Dict]] = None,
                  key_type: str = "ed25519",
                  key_types: Optional[Dict[str, str]] = None,
                  misbehaviors: Optional[Dict[str, Dict]] = None,
                  start_at: Optional[Callable[[str, bool], int]] = None,
                  load_rate: float = 0.0,
                  load_size: int = 32,
                  target_height: int = 3,
                  timeout_s: float = 120.0) -> Manifest:
    """Build the Manifest every subprocess localnet shares.

    Node names starting with ``v`` are validators (the e2e convention);
    anything else is a full node. ``base_config`` ("section.key" ->
    value) applies to every node, ``node_config[name]`` layers per-node
    overrides on top. ``key_types[name]`` overrides ``key_type`` per
    node (mixed-curve valsets). ``start_at(name, validator)`` may defer
    or manual-gate individual nodes (return -1 to provision without
    starting, the scenario engine's joiner convention).
    """
    nodes = []
    for name in names:
        validator = name.startswith("v")
        cfg = dict(base_config or {})
        cfg.update((node_config or {}).get(name, {}))
        nodes.append(NodeSpec(
            name=name, validator=validator,
            start_at=start_at(name, validator) if start_at else 0,
            key_type=(key_types or {}).get(name, key_type), config=cfg,
            misbehaviors=dict((misbehaviors or {}).get(name, {}))))
    return Manifest(
        chain_id=chain_id, nodes=nodes,
        load=LoadSpec(rate=load_rate, size=load_size),
        target_height=target_height, timeout_s=timeout_s)


@contextlib.contextmanager
def booted(manifest: Manifest, outdir: str, *, load: bool = False,
           verbose: bool = True):
    """setup() + start() a Runner over ``manifest``, optionally start
    the tx load, and guarantee stop() (load threads joined, SIGTERM to
    every node subprocess) on every exit path."""
    runner = Runner(manifest, outdir)
    if verbose:
        print(f"booting {len(manifest.nodes)}-node localnet "
              f"under {outdir}...", file=sys.stderr)
    try:
        runner.setup()
        runner.start()
        if load:
            runner.start_load()
        yield runner
    finally:
        runner.stop()
