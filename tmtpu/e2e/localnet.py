"""One shared boot path for subprocess localnets.

Three consumers used to hand-roll the same Manifest + Runner ceremony —
tools/fleet_report.py (fleet latency report), tmtpu/scenario/net.py
(adversarial scenario nets) and the tools/ that grew out of them
(tools/critical_path.py, via tools/ab_common.py's re-export). Each one
re-invented "N validators named v00..vNN, full mesh, a LoadSpec, setup/
start/start_load, then tear it all down". This module owns that shape:

    make_manifest()  the declarative half — one place that knows how a
                     name list + per-node config dicts become NodeSpecs;
    booted()         the process half — a context manager guaranteeing
                     runner.stop() (and so SIGTERM to every node child)
                     on every exit path, load threads included.

Scenario nets keep their own Runner subclass and fault timeline; they
share only make_manifest. Report tools use both.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Callable, Dict, Iterable, Optional

from tmtpu.e2e.manifest import LoadSpec, Manifest, NodeSpec
from tmtpu.e2e.runner import Runner


def validator_names(n: int) -> list:
    """The canonical localnet name scheme: v00, v01, ..."""
    return [f"v{i:02d}" for i in range(n)]


def make_manifest(chain_id: str,
                  names: Iterable[str],
                  *,
                  base_config: Optional[Dict] = None,
                  node_config: Optional[Dict[str, Dict]] = None,
                  key_type: str = "ed25519",
                  misbehaviors: Optional[Dict[str, Dict]] = None,
                  start_at: Optional[Callable[[str, bool], int]] = None,
                  load_rate: float = 0.0,
                  load_size: int = 32,
                  target_height: int = 3,
                  timeout_s: float = 120.0) -> Manifest:
    """Build the Manifest every subprocess localnet shares.

    Node names starting with ``v`` are validators (the e2e convention);
    anything else is a full node. ``base_config`` ("section.key" ->
    value) applies to every node, ``node_config[name]`` layers per-node
    overrides on top. ``start_at(name, validator)`` may defer or
    manual-gate individual nodes (return -1 to provision without
    starting, the scenario engine's joiner convention).
    """
    nodes = []
    for name in names:
        validator = name.startswith("v")
        cfg = dict(base_config or {})
        cfg.update((node_config or {}).get(name, {}))
        nodes.append(NodeSpec(
            name=name, validator=validator,
            start_at=start_at(name, validator) if start_at else 0,
            key_type=key_type, config=cfg,
            misbehaviors=dict((misbehaviors or {}).get(name, {}))))
    return Manifest(
        chain_id=chain_id, nodes=nodes,
        load=LoadSpec(rate=load_rate, size=load_size),
        target_height=target_height, timeout_s=timeout_s)


@contextlib.contextmanager
def booted(manifest: Manifest, outdir: str, *, load: bool = False,
           verbose: bool = True):
    """setup() + start() a Runner over ``manifest``, optionally start
    the tx load, and guarantee stop() (load threads joined, SIGTERM to
    every node subprocess) on every exit path."""
    runner = Runner(manifest, outdir)
    if verbose:
        print(f"booting {len(manifest.nodes)}-node localnet "
              f"under {outdir}...", file=sys.stderr)
    try:
        runner.setup()
        runner.start()
        if load:
            runner.start_load()
        yield runner
    finally:
        runner.stop()
