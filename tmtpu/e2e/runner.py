"""Testnet runner: setup → start → load → perturb → wait → test → stop.

Reference analogue: test/e2e/runner (main.go stages, perturb.go,
benchmark.go). Each node is a subprocess of ``python -m tmtpu.cmd start``
with its own home dir; perturbations use signals (SIGKILL + restart,
SIGTERM + restart, SIGSTOP/SIGCONT for a network-freeze analogue of the
reference's docker disconnect); invariants are asserted over public RPC
only, like the reference's test stage (test/e2e/tests/*_test.go).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time

from tmtpu.config.config import Config
from tmtpu.config import toml as cfg_toml
from tmtpu.e2e.manifest import Manifest, NodeSpec, Perturbation
from tmtpu.p2p.key import NodeKey
from tmtpu.privval.file_pv import FilePV
from tmtpu.rpc.client import HTTPClient
from tmtpu.types.genesis import GenesisDoc, GenesisValidator

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _hold_port():
    """Allocate an ephemeral port and KEEP the socket bound: closing
    immediately (the usual free-port idiom) leaves a seconds-wide window
    in which a concurrently-starting testnet grabs the port and the node
    dies with EADDRINUSE (observed flake). The holder is closed right
    before the node process launches, shrinking the race to
    milliseconds."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    return s.getsockname()[1], s


class _Node:
    def __init__(self, spec: NodeSpec, home: str):
        self.spec = spec
        self.home = home
        self.p2p_port, self._p2p_hold = _hold_port()
        self.rpc_port, self._rpc_hold = _hold_port()
        # pprof serves /healthz + /readyz — the readiness surface the
        # pooled boot path gates big nets on (localnet.staggered_start)
        self.pprof_port, self._pprof_hold = _hold_port()
        self.proc: subprocess.Popen | None = None
        self.client = HTTPClient(f"http://127.0.0.1:{self.rpc_port}",
                                 timeout=5.0)
        self.node_id = ""

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def _release_ports(self):
        for attr in ("_p2p_hold", "_rpc_hold", "_pprof_hold"):
            sock = getattr(self, attr)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                setattr(self, attr, None)

    def ready(self) -> bool:
        """/readyz verdict: live AND caught up (200). Falls back to
        plain RPC-up when the node runs without a pprof listener."""
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.pprof_port}/readyz",
                    timeout=2.0) as resp:
                return resp.status == 200
        except urllib.error.HTTPError:
            return False          # 503: serving but not ready
        except OSError:
            # no pprof listener (disabled, or still booting): degrade
            # to "committed at least one block" over plain RPC
            return self.height() >= 1

    def start(self):
        self._release_ports()
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # default: nodes run CPU crypto — no jax import in-subprocess,
        # keeps spawn fast. A spec may override (sidecar scenarios point
        # nodes at a shared verification daemon).
        backend = str(self.spec.config.get("base.crypto_backend", "cpu"))
        env.setdefault("TMTPU_CRYPTO_BACKEND", backend)
        env.update({k: str(v) for k, v in self.spec.env.items()})
        log = open(os.path.join(self.home, "node.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "tmtpu.cmd", "start",
             "--home", self.home, "--crypto-backend", backend],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True,
        )

    def signal(self, sig):
        if self.proc is not None and self.proc.poll() is None:
            os.killpg(self.proc.pid, sig)

    def stop(self, timeout: float = 10.0):
        if self.proc is None:
            return
        self.signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.signal(signal.SIGKILL)
            self.proc.wait(5)

    def height(self) -> int:
        try:
            st = self.client.status()
            return int(st["sync_info"]["latest_block_height"])
        except Exception:
            return -1


class Runner:
    def __init__(self, manifest: Manifest, outdir: str):
        self.m = manifest
        self.outdir = outdir
        self.nodes: list[_Node] = []
        self._stop_load = threading.Event()
        self._load_threads: list[threading.Thread] = []
        self.txs_sent: list[bytes] = []
        # tx -> wall-clock time_ns at broadcast, for the latency report
        # (compared against block header timestamps, also wall-clock)
        self.tx_send_ns: dict[bytes, int] = {}

    # -- stages -------------------------------------------------------------

    def setup(self):
        """Generate one home dir per node, full-mesh persistent peers,
        single genesis (validators only). Reference: test/e2e/runner/setup.go
        + cmd/tendermint testnet. Each node's Config is generated ONCE
        and reused for both the key bootstrap and the final write —
        config generation is pure CPU and used to run twice per node,
        which big pooled nets (10-50 validators) notice."""
        pvs = {}
        cfgs = {}
        for spec in self.m.nodes:
            home = os.path.join(self.outdir, spec.name)
            os.makedirs(os.path.join(home, "config"), exist_ok=True)
            os.makedirs(os.path.join(home, "data"), exist_ok=True)
            node = _Node(spec, home)
            cfg = cfgs[spec.name] = self._node_config(node)
            pv = FilePV.load_or_generate(
                cfg.rooted(cfg.base.priv_validator_key_file),
                cfg.rooted(cfg.base.priv_validator_state_file),
                key_type=spec.key_type)
            if spec.validator:
                pvs[spec.name] = pv
            node.node_id = NodeKey.load_or_gen(
                cfg.rooted(cfg.base.node_key_file)).node_id
            self.nodes.append(node)
        from tmtpu.types.params import ConsensusParams

        gen = GenesisDoc(
            chain_id=self.m.chain_id,
            genesis_time=time.time_ns(),
            validators=[
                GenesisValidator(pvs[s.name].get_pub_key(), s.power)
                for s in self.m.nodes if s.validator
            ],
            consensus_params=ConsensusParams(
                block_max_bytes=self.m.block_max_bytes),
        )
        from tmtpu.e2e.localnet import chord_peer_names
        peers = {n.spec.name: f"{n.node_id}@127.0.0.1:{n.p2p_port}"
                 for n in self.nodes}
        plan = chord_peer_names([n.spec.name for n in self.nodes])
        for node in self.nodes:
            cfg = cfgs[node.spec.name]
            cfg.p2p.persistent_peers = ",".join(
                peers[name] for name in plan[node.spec.name])
            gen.save_as(cfg.genesis_path)
            cfg_toml.write_config(
                cfg, os.path.join(node.home, "config", "config.toml"))

    def _node_config(self, node: _Node) -> Config:
        cfg = Config.default()
        cfg.base.home = node.home
        cfg.base.moniker = node.spec.name
        cfg.base.crypto_backend = "cpu"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{node.p2p_port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{node.rpc_port}"
        # /healthz + /readyz on every e2e node: the pooled boot path
        # and the chaos-soak sampler gate on readiness, not sleeps
        cfg.rpc.pprof_laddr = f"tcp://127.0.0.1:{node.pprof_port}"
        # e2e profile: fast rounds so tests finish in seconds
        test = Config.test_config()
        cfg.consensus = test.consensus
        if node.spec.misbehaviors:
            cfg.base.misbehaviors = ",".join(
                f"{name}@{h}" for h, name in
                sorted(node.spec.misbehaviors.items()))
        for key, value in node.spec.config.items():
            section, _, name = key.partition(".")
            setattr(getattr(cfg, section), name, value)
        return cfg

    def start(self, log=None):
        """Start nodes whose start_at is 0; late nodes join from
        run_perturbations once the net reaches their height. Nets
        bigger than one boot wave launch staggered with readiness
        gating (tmtpu/e2e/localnet.py — the 10-50 validator rung)."""
        from tmtpu.e2e.localnet import staggered_start
        staggered_start(
            [n for n in self.nodes if n.spec.start_at == 0], log=log)

    def start_load(self):
        """Offer ``load.rate`` tx/s round-robin over the validators. Above
        ~40 tx/s, txs go in JSON-RPC batch requests on a ~50 ms cadence
        (one HTTP round-trip per ~rate/20 txs): per-request overhead — not
        bandwidth — is what bounds single-host ingest, the same reason the
        reference's loadtime generator batches
        (test/loadtime/load/main.go)."""

        # one worker per ~120 tx/s: a single thread's HTTP round-trips cap
        # out near 200 tx/s regardless of node capacity (the generator,
        # not the net, becomes the bottleneck — seen in knee sweeps)
        n_workers = max(1, round(self.m.load.rate / 120.0))
        rate_each = self.m.load.rate / n_workers
        lock = threading.Lock()

        def loop(worker: int):
            from tmtpu.rpc.client import HTTPClient

            validators = [n for n in self.nodes if n.spec.start_at == 0]
            # own keep-alive client per worker: HTTPClient serializes on
            # one connection, sharing would re-serialize the workers
            clients = [HTTPClient(f"http://127.0.0.1:{n.rpc_port}",
                                  timeout=5.0) for n in validators]
            chunk = max(1, int(rate_each * 0.05))
            interval = chunk / max(rate_each, 0.1)
            i = 0
            next_at = time.monotonic()
            while not self._stop_load.is_set():
                cli = clients[(i // chunk) % len(clients)]
                txs = []
                for _ in range(chunk):
                    txs.append((b"load-%d-%06d=" % (worker, i)) + os.urandom(
                        self.m.load.size // 2).hex().encode())
                    i += 1
                try:
                    sent_ns = time.time_ns()
                    if chunk == 1:
                        cli.broadcast_tx_async(txs[0])
                        accepted = txs
                    else:
                        # call_batch returns per-entry results — an
                        # RPCClientError entry (mempool full, rejection)
                        # means that tx was never accepted; recording it
                        # as sent would poison the committed-tx invariant
                        # and the latency report
                        results = cli.broadcast_tx_async_batch(txs)
                        accepted = [tx for tx, r in zip(txs, results)
                                    if not isinstance(r, Exception)]
                    with lock:
                        for tx in accepted:
                            self.txs_sent.append(tx)
                            self.tx_send_ns[tx] = sent_ns
                except Exception:
                    pass  # node may be mid-perturbation
                # elapsed-compensating pacing: sleep to the schedule, not
                # a full interval after each (slow) request
                next_at += interval
                delay = next_at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                else:
                    next_at = time.monotonic()  # fell behind: reset

        self._load_threads = [
            threading.Thread(target=loop, args=(w,), daemon=True)
            for w in range(n_workers)
        ]
        for t in self._load_threads:
            t.start()

    def max_height(self) -> int:
        return max((n.height() for n in self.nodes if n.running),
                   default=-1)

    def run_perturbations(self):
        """Blocking: fire each perturbation when the net reaches its
        height; also starts late-joining nodes (reference: perturb.go)."""
        pending = sorted(self.m.perturbations, key=lambda p: p.at_height)
        late = [n for n in self.nodes if n.spec.start_at > 0]
        deadline = time.monotonic() + self.m.timeout_s
        while (pending or late) and time.monotonic() < deadline:
            h = self.max_height()
            for node in [n for n in late if h >= n.spec.start_at]:
                node.start()
                late.remove(node)
            while pending and h >= pending[0].at_height:
                self._apply(pending.pop(0))
            time.sleep(0.25)
        if pending or late:
            raise TimeoutError(f"perturbations pending at timeout: "
                               f"{[p.op for p in pending]} late={late}")

    def _apply(self, p: Perturbation):
        node = next(n for n in self.nodes if n.spec.name == p.node)
        if p.op == "kill":
            node.signal(signal.SIGKILL)
            node.proc.wait(10)
            time.sleep(p.delay_s)
            node.start()
        elif p.op == "restart":
            node.stop()
            time.sleep(p.delay_s)
            node.start()
        elif p.op in ("pause", "disconnect"):
            node.signal(signal.SIGSTOP)
            time.sleep(p.delay_s)
            node.signal(signal.SIGCONT)
        else:
            raise ValueError(f"unknown perturbation op {p.op!r}")

    def wait_for(self, height: int | None = None):
        target = height or self.m.target_height
        deadline = time.monotonic() + self.m.timeout_s
        while time.monotonic() < deadline:
            hs = [n.height() for n in self.nodes]
            if all(h >= target for h in hs):
                return
            time.sleep(0.3)
        raise TimeoutError(f"heights {[n.height() for n in self.nodes]} "
                           f"< target {target}")

    def stop_load(self):
        self._stop_load.set()
        for t in self._load_threads:
            t.join(5)

    def test(self):
        """Invariants over RPC (reference: test/e2e/tests/): app hash and
        block id agreement at every common height, monotonic time, and the
        load txs actually committed and queryable."""
        ref_node = self.nodes[0]
        top = min(n.height() for n in self.nodes)
        assert top >= self.m.target_height
        for other in self.nodes[1:]:
            for h in range(2, top + 1):
                a = ref_node.client.block(height=h)["block"]["header"]
                b = other.client.block(height=h)["block"]["header"]
                assert a["app_hash"] == b["app_hash"], (
                    f"app hash divergence at {h}")
                assert a["last_block_id"] == b["last_block_id"], (
                    f"chain divergence at {h}")
        # at least half the offered load must have committed, and a sampled
        # committed tx must be queryable everywhere
        if self.txs_sent:
            found = 0
            sample = self.txs_sent[: min(20, len(self.txs_sent))]
            for tx in sample:
                try:
                    import hashlib
                    res = ref_node.client.tx(
                        hashlib.sha256(tx).hexdigest().upper())
                    if res:
                        found += 1
                except Exception:
                    pass
            assert found >= len(sample) // 2, (
                f"only {found}/{len(sample)} sampled txs committed")

    def benchmark(self) -> dict:
        """Block-rate statistics over the run (reference: benchmark.go),
        plus the per-tx latency distribution when load was applied
        (reference: test/loadtime/report — there, latency = block time
        minus the timestamp embedded in each tx; here the runner already
        holds every tx's send time, so no payload format is needed)."""
        from tmtpu.light.provider import _rfc3339_to_ns

        node = self.nodes[0]
        top = node.height()
        times = {}
        block_txs = {}
        for h in range(2, top + 1):
            blk = node.client.block(height=h)["block"]
            times[h] = _rfc3339_to_ns(blk["header"]["time"])
            block_txs[h] = blk["data"].get("txs") or []
        if len(times) < 2:
            return {}
        ts = [times[h] for h in sorted(times)][-51:]
        intervals = [(b - a) / 1e9 for a, b in zip(ts, ts[1:])]
        out = {
            "blocks": len(intervals),
            "avg_interval_s": sum(intervals) / len(intervals),
            "max_interval_s": max(intervals),
            "blocks_per_min": 60.0 / (sum(intervals) / len(intervals)),
        }
        out.update(self.latency_report(times, block_txs))
        return out

    def latency_report(self, block_time_ns: dict, block_txs: dict) -> dict:
        """p50/p95/max broadcast→commit latency over every load tx found
        in a block (tx latency = committing block's timestamp - send
        time, the reference loadtime/report definition). Header time is
        BFT time — the median of the PREVIOUS height's precommit
        timestamps — so it lags real commit time by ~one block interval;
        at sub-second block rates a tx committed within one block can
        therefore report small NEGATIVE latency. Txs still uncommitted at
        report time are counted, not silently dropped."""
        import base64

        if not self.tx_send_ns:
            return {}
        lat_s = []
        committed = set()
        for h, txs in block_txs.items():
            for b64 in txs:
                tx = base64.b64decode(b64)
                sent = self.tx_send_ns.get(tx)
                if sent is not None:
                    committed.add(tx)
                    lat_s.append((block_time_ns[h] - sent) / 1e9)
        if not lat_s:
            return {"txs_committed": 0,
                    "txs_uncommitted": len(self.tx_send_ns)}
        lat_s.sort()

        def pct(p):
            return lat_s[min(len(lat_s) - 1, int(p * len(lat_s)))]

        return {
            "txs_committed": len(lat_s),
            "txs_uncommitted": len(self.tx_send_ns) - len(committed),
            "latency_p50_s": round(pct(0.50), 3),
            "latency_p95_s": round(pct(0.95), 3),
            "latency_max_s": round(lat_s[-1], 3),
        }

    def stop(self):
        self.stop_load()
        for node in self.nodes:
            node.stop()

    # -- one-shot -----------------------------------------------------------

    def run(self) -> dict:
        try:
            self.setup()
            self.start()
            self.start_load()
            self.run_perturbations()
            self.wait_for()
            self.stop_load()
            self.test()
            stats = self.benchmark()
            # nodes are stopped on exit — snapshot heights while they serve
            self.final_heights = [n.height() for n in self.nodes]
            return stats
        finally:
            self.stop()
