"""End-to-end testnet harness (reference analogue: test/e2e/).

The reference drives docker-compose testnets from a TOML manifest through
stages setup/start/load/perturb/wait/test/stop (test/e2e/README.md:34-58,
test/e2e/pkg/manifest.go:11). This harness runs the same stages with each
node as a local subprocess of ``python -m tmtpu.cmd start`` — no Docker in
the image — talking to the nodes only through their public surfaces: the
config/home dir, signals, and RPC.
"""

from tmtpu.e2e.manifest import Manifest, NodeSpec, Perturbation  # noqa: F401
from tmtpu.e2e.runner import Runner  # noqa: F401
