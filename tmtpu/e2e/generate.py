"""Random testnet manifest generator (reference:
test/e2e/generator/generate.go:1 + random.go — uniformChoice /
probSetChoice over topology, node options, and perturbations).

Differences from the reference, by design:

- Scaled to this box: the reference caps "large" nets for CPU reasons
  (generate.go:88 FIXME). The cap here derives from ``os.cpu_count()``
  (~2 subprocess nodes per core, floor 6 so a single-core box keeps
  the historic ceiling, hard ceiling 16); ``TMTPU_E2E_MAX_NODES``
  overrides it outright for CI boxes whose cgroup quota belies their
  visible core count.
- Curve mix is a first-class axis: each validator's key draws from
  ed25519/sr25519/secp256k1 (the reference's codec handles only two
  curves; BASELINE.md "mixed-curve valsets" row).
- Statesync is not an axis here: bootstrapping trust hashes requires a
  live net and is covered by tests/test_statesync.py; late-start nodes
  exercise the blocksync catch-up path instead (generate.go nextStartAt).

Deterministic: same seed -> same manifests, so a failing random net is
reproducible from the seed recorded in its chain_id.
"""

from __future__ import annotations

import os
import random

from tmtpu.e2e.manifest import LoadSpec, Manifest, NodeSpec, Perturbation

TOPOLOGIES = ("single", "quad", "large")

# weighted axes (generate.go nodeMempools / nodePerturbations analogues)
_CURVES = ["ed25519", "ed25519", "sr25519", "secp256k1"]
_MEMPOOLS = ["v0", "v1"]
# all three fast-sync implementations, weighted toward the default —
# the reference's nightly matrices mix fast-sync versions the same way
# (test/e2e/generator: testnets mix FastSync versions)
_BLOCKSYNCS = ["v0", "v0", "v1", "v2"]
_PERTURBATIONS = {"kill": 0.1, "restart": 0.1, "pause": 0.1}


def max_nodes() -> int:
    """Ceiling on a generated net's node count. Every node is its own
    subprocess, so the ceiling tracks the host: ~2 nodes per visible
    core, floored at 6 (the historic single-core cap) and hard-capped
    at 16 (past that, full-mesh p2p dominates and the net measures the
    scheduler, not consensus). ``TMTPU_E2E_MAX_NODES`` overrides the
    derivation for hosts whose cgroup CPU quota is smaller than the
    core count Python reports. Same seed + same cap -> same manifests."""
    env = os.environ.get("TMTPU_E2E_MAX_NODES", "")
    if env:
        return max(1, int(env))
    cores = os.cpu_count() or 1
    return max(6, min(16, cores * 2))


def generate_manifest(rng: random.Random, topology: str | None = None,
                      seed_tag: str = "") -> Manifest:
    """One random testnet manifest."""
    topology = topology or rng.choice(TOPOLOGIES)
    if topology == "single":
        n_validators, n_fulls = 1, 0
    elif topology == "quad":
        n_validators, n_fulls = 4, 0
    else:  # large (bounded by max_nodes(): each node is a subprocess)
        cap = max_nodes()
        n_validators = 4 + rng.randrange(max(1, cap - 4))
        n_fulls = rng.randrange(min(2, max(0, cap - n_validators)) + 1)

    m = Manifest(chain_id=f"gen-{seed_tag or topology}",
                 target_height=8 + rng.randrange(4),
                 timeout_s=240.0)

    # BFT quorum starts at genesis; the rest join late and blocksync in
    # (generate.go:106-118 nextStartAt). Unlike the reference — which adds
    # late validators via ValidatorUpdates — late validators here are in
    # the genesis valset from the start, so genesis-started validators
    # must hold a POWER supermajority by construction or the net could
    # never reach the late joiners' start heights: genesis powers are an
    # order of magnitude above late powers.
    quorum = n_validators * 2 // 3 + 1
    next_start = 5
    for i in range(n_validators):
        start_at, power = 0, 100 + rng.randrange(71)
        if i >= quorum:
            start_at, next_start = next_start, next_start + 2
            power = 10 + rng.randrange(20)
        m.nodes.append(NodeSpec(
            name=f"validator{i:02d}",
            power=power,
            start_at=start_at,
            key_type=rng.choice(_CURVES),
            config=_node_config(rng),
        ))
    for i in range(n_fulls):
        m.nodes.append(NodeSpec(
            name=f"full{i:02d}", validator=False,
            start_at=rng.choice([0, next_start]),
            config=_node_config(rng),
        ))

    # perturbation schedule: each started-at-genesis node may draw each op
    # with probability 0.1 (generate.go nodePerturbations probSetChoice).
    # Single-node nets skip kill/pause: with no peers to catch up from, a
    # one-validator net pausing its only proposer just stalls the clock.
    if n_validators + n_fulls > 1:
        for node in m.nodes:
            if node.start_at:
                continue
            for op, prob in _PERTURBATIONS.items():
                if rng.random() < prob:
                    m.perturbations.append(Perturbation(
                        node=node.name, op=op,
                        at_height=2 + rng.randrange(5),
                        delay_s=0.5 + rng.random()))

    m.load = LoadSpec(rate=float(10 + rng.randrange(30)),
                      size=rng.choice([32, 128, 256]))
    return m


def _node_config(rng: random.Random) -> dict:
    """Random per-node config overrides ("section.key" -> value)."""
    cfg = {"mempool.version": rng.choice(_MEMPOOLS)}
    if rng.random() < 0.3:
        cfg["mempool.recheck"] = False
    cfg["block_sync.version"] = rng.choice(_BLOCKSYNCS)
    return cfg


def generate(seed: int, groups: int = 1) -> list[Manifest]:
    """`groups` manifests per topology, deterministically from `seed`
    (generator/main.go writes one TOML per manifest; callers here get the
    objects and feed them straight to tmtpu.e2e.runner.Runner)."""
    rng = random.Random(seed)
    out = []
    for g in range(groups):
        for topo in TOPOLOGIES:
            out.append(generate_manifest(
                rng, topo, seed_tag=f"{topo}-s{seed}g{g}"))
    return out
