"""Sidecar client: one multiplexed connection, many in-flight requests.

``SidecarClient`` is deliberately dumb about crypto — it moves raw
(pk_bytes, msg, sig, power) lanes over the wire and returns the
daemon's mask. All fallback POLICY (breaker, in-process retry, serial
CPU) lives in :class:`tmtpu.crypto.batch.SidecarBatchVerifier`; the
client only distinguishes the failure KINDS the policy needs:

- :class:`SidecarUnavailable` — can't connect, connection died
  mid-request, per-request deadline hit, or the daemon answered a
  non-OK status other than overload. Counts against the
  ``crypto.sidecar`` breaker.
- :class:`SidecarOverloaded` — explicit admission-control backpressure.
  The daemon is HEALTHY and saying "not now"; the caller verifies this
  batch in-process but does not penalize the breaker for it.

One background reader thread demultiplexes responses to waiters by
request id; callers block on their own event with their own deadline,
so a slow joint dispatch never heads-of-line-blocks a Ping. Reconnects
are lazy (next request attempts) with a flat backoff window so a dead
daemon costs one failed ``connect()`` per window, not one per verify.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from tmtpu.sidecar import protocol as proto

ENV_ADDR = "TMTPU_SIDECAR_ADDR"


def default_addr(home: str = "") -> str:
    """Resolution order: explicit config addr (caller passes it through),
    ``TMTPU_SIDECAR_ADDR`` env, then the conventional per-home unix
    socket path."""
    env = os.environ.get(ENV_ADDR, "")
    if env:
        return env
    if home:
        return f"unix://{os.path.join(home, 'data', 'sidecar.sock')}"
    return ""


class SidecarError(Exception):
    pass


class SidecarUnavailable(SidecarError):
    """Daemon unreachable / dead connection / deadline / hard error."""


class SidecarOverloaded(SidecarError):
    """Explicit backpressure: daemon healthy but queues are full."""


class _Waiter:
    __slots__ = ("event", "reply", "error")

    def __init__(self):
        self.event = threading.Event()
        self.reply = None
        self.error: Optional[Exception] = None


class SidecarClient:
    def __init__(self, addr: str, *,
                 client_id: str = "",
                 connect_timeout_s: float = 2.0,
                 request_deadline_s: float = 10.0,
                 retry_backoff_s: float = 1.0,
                 max_frame_bytes: int = proto.DEFAULT_MAX_FRAME_BYTES):
        self.addr = addr
        self.client_id = client_id or f"pid-{os.getpid()}"
        self._connect_timeout_s = connect_timeout_s
        self._request_deadline_s = request_deadline_s
        self._retry_backoff_s = retry_backoff_s
        self._max_frame_bytes = max_frame_bytes
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._wlock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._waiters: Dict[int, _Waiter] = {}
        self._waiters_lock = threading.Lock()
        self._seq = itertools.count(1)
        self._last_connect_fail = 0.0
        self.hello_ack: Optional[proto.HelloAck] = None

    # --- connection management ---

    def connected(self) -> bool:
        return self._sock is not None

    def _ensure_connected(self) -> None:
        if self._sock is not None:
            return
        with self._conn_lock:
            if self._sock is not None:
                return
            now = time.monotonic()
            if now - self._last_connect_fail < self._retry_backoff_s:
                raise SidecarUnavailable(
                    f"sidecar {self.addr}: in connect backoff")
            try:
                self._connect_locked()
            except (OSError, proto.ProtocolError, EOFError,
                    ValueError) as exc:
                self._last_connect_fail = time.monotonic()
                raise SidecarUnavailable(
                    f"sidecar {self.addr}: {exc}") from exc

    def _connect_locked(self) -> None:
        from tmtpu.libs import metrics as _m

        _m.sidecar_client_reconnects.inc()
        kind, target = proto.parse_addr(self.addr)
        if kind == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self._connect_timeout_s)
        sock.connect(target)
        rfile = sock.makefile("rb")
        reader = proto.FrameReader(rfile, self._max_frame_bytes)
        sock.sendall(proto.encode_frame(proto.Hello(
            version=proto.PROTOCOL_VERSION, client_id=self.client_id,
            features=["verify", "tally"])))
        ack = reader.read_msg()
        if isinstance(ack, proto.ErrorReply) and \
                ack.code == proto.ERR_VERSION and \
                proto.PROTOCOL_VERSION > min(proto.SUPPORTED_VERSIONS):
            # version-skew tolerance: an old daemon hard-rejects a newer
            # Hello (pre-v2 daemons knew no negotiation), so retry the
            # handshake once at the oldest version we still speak. The
            # old daemon closes the rejected connection, so reconnect.
            try:
                sock.close()
            except OSError:
                pass
            if kind == "unix":
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            else:
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.settimeout(self._connect_timeout_s)
            sock.connect(target)
            rfile = sock.makefile("rb")
            reader = proto.FrameReader(rfile, self._max_frame_bytes)
            sock.sendall(proto.encode_frame(proto.Hello(
                version=min(proto.SUPPORTED_VERSIONS),
                client_id=self.client_id,
                features=["verify", "tally"])))
            ack = reader.read_msg()
        if isinstance(ack, proto.ErrorReply):
            raise SidecarUnavailable(
                f"sidecar rejected handshake (code {ack.code}): "
                f"{ack.message}")
        if not isinstance(ack, proto.HelloAck):
            raise proto.ProtocolError(
                f"expected HelloAck, got {type(ack).__name__}")
        sock.settimeout(None)  # reader thread blocks; waiters time out
        self.hello_ack = ack
        self._sock = sock
        self._rfile = rfile
        _m.sidecar_client_up.set(1.0)
        threading.Thread(target=self._read_loop, args=(reader, sock),
                         name="sidecar-client-read",
                         daemon=True).start()

    def close(self) -> None:
        with self._conn_lock:
            self._teardown(SidecarUnavailable("client closed"))

    def _teardown(self, err: Exception) -> None:
        from tmtpu.libs import metrics as _m

        sock, self._sock = self._sock, None
        self._rfile = None
        self.hello_ack = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            _m.sidecar_client_up.set(0.0)
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, {}
        for w in waiters.values():
            w.error = err
            w.event.set()

    def _read_loop(self, reader: proto.FrameReader,
                   sock: socket.socket) -> None:
        try:
            while True:
                msg = reader.read_msg()
                rid = getattr(msg, "request_id",
                              getattr(msg, "nonce", 0))
                if isinstance(msg, proto.ErrorReply) and rid == 0:
                    raise SidecarUnavailable(
                        f"sidecar connection error {msg.code}: "
                        f"{msg.message}")
                with self._waiters_lock:
                    w = self._waiters.pop(rid, None)
                if w is not None:
                    w.reply = msg
                    w.event.set()
                # unmatched reply: waiter already timed out — drop it
        except (EOFError, OSError, proto.ProtocolError,
                SidecarUnavailable) as exc:
            with self._conn_lock:
                if self._sock is sock:
                    self._teardown(SidecarUnavailable(
                        f"sidecar connection lost: {exc}"))

    # --- request primitives ---

    def _roundtrip(self, rid: int, msg, deadline_s: float):
        w = _Waiter()
        with self._waiters_lock:
            self._waiters[rid] = w
        sock = None
        try:
            data = proto.encode_frame(msg)
            sock = self._sock
            if sock is None:
                raise SidecarUnavailable("sidecar not connected")
            with self._wlock:
                sock.sendall(data)
        except BaseException as exc:
            # every failure path must unregister the waiter, including
            # the sock-is-None raise (else a connect race leaks it)
            with self._waiters_lock:
                self._waiters.pop(rid, None)
            if isinstance(exc, OSError):
                with self._conn_lock:
                    if self._sock is sock:
                        self._teardown(SidecarUnavailable(str(exc)))
                raise SidecarUnavailable(
                    f"sidecar send failed: {exc}") from exc
            raise
        if not w.event.wait(deadline_s):
            with self._waiters_lock:
                self._waiters.pop(rid, None)
            raise SidecarUnavailable(
                f"sidecar request deadline ({deadline_s:.3f}s) exceeded")
        if w.error is not None:
            raise SidecarUnavailable(str(w.error)) from w.error
        return w.reply

    # --- public API ---

    def trace_ctx_supported(self) -> bool:
        """True when the daemon acked a version that knows the v2
        trace-context fields (never attach them to an older daemon)."""
        ack = self.hello_ack
        return ack is not None and \
            ack.version >= proto.TRACE_CTX_MIN_VERSION

    def verify(self, curve: str, lanes: List[Tuple[bytes, bytes, bytes,
                                                   int]],
               tally: bool = False,
               deadline_s: Optional[float] = None) -> Tuple[List[bool],
                                                            int, Dict]:
        """Ship lanes to the daemon; returns (mask, tallied, dispatch
        info). Raises :class:`SidecarOverloaded` on backpressure and
        :class:`SidecarUnavailable` on everything else non-OK.

        When the calling thread has an active trace context
        (libs.trace.activate) and the daemon speaks v2, the context
        rides the request so the daemon's joint dispatch is attributable
        to the height that caused it."""
        from tmtpu.libs import metrics as _m
        from tmtpu.libs import trace as _trace

        deadline_s = deadline_s or self._request_deadline_s
        self._ensure_connected()
        rid = next(self._seq)
        ctx = _trace.current_context()
        ctx_bytes = b""
        if ctx is not None and self.trace_ctx_supported():
            ctx_bytes = ctx.encode()
            _m.trace_context_tx.inc(transport="sidecar")
            _trace.mark("sidecar.verify", ctx=ctx, curve=curve,
                        lanes=len(lanes))
        req = proto.VerifyRequest(
            request_id=rid, curve=curve, tally=tally,
            deadline_ms=int(deadline_s * 1000),
            lanes=[proto.Lane(pub_key=pk, msg=m, sig=s, power=p)
                   for pk, m, s, p in lanes],
            trace_ctx=ctx_bytes)
        t0 = time.perf_counter()
        try:
            reply = self._roundtrip(rid, req, deadline_s)
        except SidecarUnavailable:
            _m.sidecar_client_requests.inc(curve=curve, status="error")
            raise
        _m.sidecar_client_request_latency.observe(
            time.perf_counter() - t0, curve=curve)
        if not isinstance(reply, proto.VerifyResponse):
            _m.sidecar_client_requests.inc(curve=curve, status="error")
            raise SidecarUnavailable(
                f"unexpected reply {type(reply).__name__}")
        status = proto.STATUS_NAMES.get(reply.status,
                                        str(reply.status))
        _m.sidecar_client_requests.inc(curve=curve, status=status)
        if reply.status == proto.STATUS_OVERLOADED:
            raise SidecarOverloaded(reply.error or "overloaded")
        if reply.status != proto.STATUS_OK:
            raise SidecarUnavailable(
                f"sidecar status {status}: {reply.error}")
        if reply.lane_count != len(lanes):
            raise SidecarUnavailable(
                f"sidecar answered {reply.lane_count} lanes "
                f"for {len(lanes)}")
        mask = proto.unpack_mask(reply.mask, reply.lane_count)
        info = {"dispatch_id": reply.dispatch_id,
                "dispatch_lanes": reply.dispatch_lanes,
                "dispatch_clients": reply.dispatch_clients,
                "dispatch_traces": reply.dispatch_traces}
        return mask, reply.tallied, info

    def ping(self, deadline_s: Optional[float] = None) -> proto.Pong:
        self._ensure_connected()
        nonce = next(self._seq)
        reply = self._roundtrip(nonce, proto.Ping(nonce=nonce),
                                deadline_s or self._request_deadline_s)
        if not isinstance(reply, proto.Pong):
            raise SidecarUnavailable(
                f"unexpected reply {type(reply).__name__}")
        return reply

    def stats(self, deadline_s: Optional[float] = None) -> Dict:
        """Daemon introspection snapshot. StatsResponse carries no id,
        so stats calls serialize on request id 0 — fine for a debug
        endpoint."""
        self._ensure_connected()
        reply = self._roundtrip(0, proto.StatsRequest(),
                                deadline_s or self._request_deadline_s)
        if not isinstance(reply, proto.StatsResponse):
            raise SidecarUnavailable(
                f"unexpected reply {type(reply).__name__}")
        return json.loads(reply.stats_json.decode())
