"""The sidecar daemon: socket listener, protocol loop, verify engine.

One daemon process owns the JAX device for a whole host. It compiles
the Pallas verify kernels ONCE (``warm()`` forces the compile at
startup instead of on the first client's request) and serves every node
process through the cross-client coalescer, so N validators pay one
~35s compile instead of N, and their lanes merge into joint dispatches.

The verify engine is :func:`tmtpu.crypto.batch.new_batch_verifier` —
the daemon inherits the whole in-process stack for free: the
daemon-wide sigcache (a signature verified for node A is a cache hit
when node B re-proves it), the ``crypto.tpu`` breaker with serial
fallback, per-batch deadlines, and the batch metric set. A sidecar
daemon never returns a wrong mask: device failure degrades to the
engine's exact serial re-verify, and engine failure degrades to an
error verdict the client treats as "no answer, verify locally".

Introspection: ``Ping``/``StatsRequest`` on the protocol socket, plus
an optional HTTP listener (``health_laddr``) serving ``/healthz``
(JSON snapshot, 200/503 by backend-breaker state) and ``/metrics``
(Prometheus text) for curl/scrapers that don't speak the frame
protocol.

Run it: ``python -m tmtpu sidecar --addr unix:///tmp/tmtpu-sidecar.sock``
(cmd/__main__.py), point nodes at it with ``crypto.backend=sidecar``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from tmtpu.crypto import batch as crypto_batch
from tmtpu.crypto import encoding as _enc  # noqa: F401 — registers all
# curve key types in KEY_TYPES (the daemon validates request curves
# against that registry before anything else imports the curve modules)
from tmtpu.crypto.keys import KEY_TYPES
from tmtpu.libs import breaker as _bk
from tmtpu.sidecar import protocol as proto
from tmtpu.sidecar.coalescer import Coalescer, Overloaded

_FAILURE_STATUS = {
    "expired": proto.STATUS_OVERLOADED,
    "engine": proto.STATUS_BACKEND_DOWN,
    "stopped": proto.STATUS_SHUTTING_DOWN,
}


class SidecarServer:
    def __init__(self, addr: str, *,
                 backend: str = "auto",
                 max_queue_lanes: int = 65536,
                 max_lanes_per_dispatch: int = 40960,
                 max_frame_bytes: int = proto.DEFAULT_MAX_FRAME_BYTES,
                 request_deadline_s: float = 30.0,
                 health_laddr: str = "",
                 server_id: str = "",
                 mesh_devices: Optional[int] = None,
                 shard_min_lanes: Optional[int] = None):
        self.addr = addr
        self._kind, self._target = proto.parse_addr(addr)
        if backend not in ("auto", "cpu", "tpu"):
            raise ValueError(
                f"sidecar daemon backend must be auto/cpu/tpu, got "
                f"{backend!r} (a daemon serving 'sidecar' would recurse)")
        self._backend = backend
        # daemon-side mesh knobs: the daemon owns every chip on the
        # host, so its [sidecar] overrides win over [crypto] here
        self._mesh_devices = mesh_devices
        self._shard_min_lanes = shard_min_lanes
        self._max_lanes_per_dispatch = max_lanes_per_dispatch
        self._max_frame_bytes = max_frame_bytes
        self._default_deadline_s = request_deadline_s
        self._health_laddr = health_laddr
        self.server_id = server_id or f"sidecar-{os.getpid()}"
        self.coalescer = Coalescer(
            self._engine_verify,
            max_queue_lanes=max_queue_lanes,
            max_lanes_per_dispatch=max_lanes_per_dispatch)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._health_httpd = None
        self._health_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._running = False
        self._draining = False
        self._started_at = 0.0
        self._warmed = False

    # --- verify engine ---

    def _engine_verify(self, curve: str, items: List[tuple],
                       tally: bool) -> Tuple[List[bool], int]:
        """Coalescer dispatch target: raw (pk_bytes, msg, sig, power)
        lanes → PubKey objects → one in-process batch verify."""
        pk_cls = KEY_TYPES[curve][0]
        bv = crypto_batch.new_batch_verifier(self._backend)
        for pk_b, msg, sig, power in items:
            bv.add(pk_cls(pk_b), msg, sig, power)
        if tally:
            _all_ok, mask, tallied = bv.verify_tally()
        else:
            _all_ok, mask = bv.verify()
            tallied = 0
        return mask, tallied

    def backend_name(self) -> str:
        b = self._backend
        if b == "auto":
            b = "tpu" if crypto_batch._tpu_available() else "cpu"
        return b

    def warm(self) -> float:
        """Force kernel compilation NOW by pushing one self-signed batch
        through the engine, so the first client request doesn't eat the
        compile latency. Returns the warm-up wall seconds."""
        from tmtpu.crypto import ed25519 as _ed

        t0 = time.perf_counter()
        priv = _ed.gen_priv_key()
        pk = priv.pub_key()
        lanes = max(crypto_batch._TPU_MIN_BATCH, 8)
        items = []
        for i in range(lanes):
            msg = b"sidecar-warm-%d" % i
            items.append((pk.bytes(), msg, priv.sign(msg), 1))
        mask, _ = self._engine_verify("ed25519", items, tally=False)
        if not all(mask):
            raise RuntimeError("sidecar warm-up verify returned invalid "
                               "for self-signed lanes")
        self._warmed = True
        return time.perf_counter() - t0

    # --- lifecycle ---

    def start(self) -> None:
        if self._running:
            return
        if self._kind == "unix":
            path = self._target
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.bind(path)
        else:
            host, port = self._target
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            if port == 0:
                # ephemeral-port bind: rewrite addr so clients/tests can
                # read the real endpoint back off server.addr
                port = sock.getsockname()[1]
                self._target = (host, port)
                self.addr = f"tcp://{host}:{port}"
        sock.listen(64)
        self._listener = sock
        self._running = True
        self._started_at = time.monotonic()
        if self._mesh_devices is not None or \
                self._shard_min_lanes is not None:
            from tmtpu.tpu import mesh_dispatch as _mesh

            _mesh.set_overrides(mesh_devices=self._mesh_devices,
                                shard_min_lanes=self._shard_min_lanes)
        self.coalescer.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="sidecar-accept", daemon=True)
        self._accept_thread.start()
        if self._health_laddr:
            self._start_health_http()

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful-shutdown phase one (the SIGTERM path): stop taking
        new work, finish what's in flight. Closes the listener, answers
        every subsequent VerifyRequest with STATUS_OVERLOADED (clients
        treat ONLY overload as penalty-free fallback — a drain must not
        cost every connected node a breaker-worth of errors), and blocks
        until the coalescer has dispatched its queue and answered every
        in-flight joint batch, or the timeout passes (returns False).
        Ping/Stats keep working throughout. Call stop() afterwards."""
        self._draining = True
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        return self.coalescer.drain(timeout)

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            # shutdown() before close(): close() alone does not wake a
            # thread blocked in accept(), which would leave stop() eating
            # the full accept-thread join timeout
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.coalescer.stop()
        if self._health_httpd is not None:
            try:
                self._health_httpd.shutdown()
                self._health_httpd.server_close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            self._health_httpd = None
        ht = self._health_thread
        if ht is not None and ht is not threading.current_thread():
            ht.join(timeout=2.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        if self._kind == "unix":
            try:
                os.unlink(self._target)
            except OSError:
                pass

    def snapshot(self) -> Dict:
        with self._conns_lock:
            n_conns = len(self._conns)
        return {
            "server_id": self.server_id,
            "addr": self.addr,
            "backend": self.backend_name(),
            "warmed": self._warmed,
            "draining": self._draining,
            "uptime_s": round(max(0.0, time.monotonic() -
                                  self._started_at), 3),
            "connections": n_conns,
            "coalescer": self.coalescer.snapshot(),
            "mesh": __import__(
                "tmtpu.tpu.mesh_dispatch",
                fromlist=["snapshot"]).snapshot(),
            "breakers": _bk.snapshot_all(),
            "sigcache": __import__(
                "tmtpu.crypto.sigcache", fromlist=["stats"]).stats(),
        }

    # --- connection handling ---

    def _accept_loop(self) -> None:
        from tmtpu.libs import metrics as _m

        while self._running:
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _peer = listener.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
                _m.sidecar_server_connections.set(len(self._conns))
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="sidecar-conn", daemon=True).start()

    def _drop_conn(self, conn) -> None:
        from tmtpu.libs import metrics as _m

        with self._conns_lock:
            self._conns.discard(conn)
            _m.sidecar_server_connections.set(len(self._conns))
        try:
            conn.close()
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        from tmtpu.libs import metrics as _m

        rfile = conn.makefile("rb")
        wlock = threading.Lock()

        def send(msg) -> None:
            data = proto.encode_frame(msg)
            with wlock:
                conn.sendall(data)

        reader = proto.FrameReader(rfile, self._max_frame_bytes)
        try:
            # handshake: Hello first, version within SUPPORTED_VERSIONS
            try:
                first = reader.read_msg()
            except proto.ProtocolError as exc:
                _m.sidecar_server_protocol_errors.inc(kind="bad-frame")
                try:
                    send(proto.ErrorReply(code=proto.ERR_PROTOCOL,
                                          message=str(exc)))
                except OSError:
                    pass
                return
            if not isinstance(first, proto.Hello):
                _m.sidecar_server_protocol_errors.inc(kind="no-hello")
                send(proto.ErrorReply(
                    code=proto.ERR_PROTOCOL,
                    message=f"expected Hello, got "
                            f"{type(first).__name__}"))
                return
            if first.version not in proto.SUPPORTED_VERSIONS:
                _m.sidecar_server_protocol_errors.inc(
                    kind="version-mismatch")
                send(proto.ErrorReply(
                    code=proto.ERR_VERSION,
                    message=f"protocol version {first.version} not in "
                            f"server-supported "
                            f"{list(proto.SUPPORTED_VERSIONS)}"))
                return
            # version-skew tolerance: serve old clients at their version
            # (they never see v2-only optional fields anyway — unknown
            # fields are skipped — but the ack tells THEM not to send any)
            negotiated = min(first.version, proto.PROTOCOL_VERSION)
            client_id = first.client_id or "anon"
            _m.sidecar_server_requests.inc(type="hello")
            send(proto.HelloAck(
                version=negotiated,
                server_id=self.server_id,
                backend=self.backend_name(),
                max_lanes=self._max_lanes_per_dispatch,
                max_frame_bytes=self._max_frame_bytes))
            while self._running:
                try:
                    msg = reader.read_msg()
                except proto.ProtocolError as exc:
                    _m.sidecar_server_protocol_errors.inc(kind="bad-frame")
                    try:
                        send(proto.ErrorReply(code=proto.ERR_PROTOCOL,
                                              message=str(exc)))
                    except OSError:
                        pass
                    return  # framing is lost; the stream cannot recover
                if isinstance(msg, proto.VerifyRequest):
                    _m.sidecar_server_requests.inc(type="verify")
                    self._handle_verify(client_id, msg, send)
                elif isinstance(msg, proto.Ping):
                    _m.sidecar_server_requests.inc(type="ping")
                    send(proto.Pong(
                        nonce=msg.nonce, backend=self.backend_name(),
                        uptime_ms=int((time.monotonic() -
                                       self._started_at) * 1000)))
                elif isinstance(msg, proto.StatsRequest):
                    _m.sidecar_server_requests.inc(type="stats")
                    send(proto.StatsResponse(stats_json=json.dumps(
                        self.snapshot()).encode()))
                else:
                    _m.sidecar_server_protocol_errors.inc(
                        kind="unexpected-type")
                    send(proto.ErrorReply(
                        code=proto.ERR_PROTOCOL,
                        message=f"unexpected {type(msg).__name__}"))
        except (EOFError, OSError, BrokenPipeError):
            pass  # peer went away
        finally:
            self._drop_conn(conn)

    def _handle_verify(self, client_id: str, req: proto.VerifyRequest,
                       send) -> None:
        def reject(status: int, error: str) -> None:
            send(proto.VerifyResponse(
                request_id=req.request_id, status=status,
                lane_count=len(req.lanes), error=error))

        if self._draining:
            # OVERLOADED, not SHUTTING_DOWN: the client's overload path
            # falls back in-process without charging its breaker
            reject(proto.STATUS_OVERLOADED, "daemon draining for shutdown")
            return
        if req.curve not in KEY_TYPES:
            reject(proto.STATUS_BAD_REQUEST,
                   f"unknown curve {req.curve!r}")
            return
        if not req.lanes:
            reject(proto.STATUS_BAD_REQUEST, "zero lanes")
            return
        if len(req.lanes) > self._max_lanes_per_dispatch:
            reject(proto.STATUS_OVERLOADED,
                   f"{len(req.lanes)} lanes exceeds per-request cap "
                   f"{self._max_lanes_per_dispatch}")
            return
        items = [(ln.pub_key, ln.msg, ln.sig, ln.power)
                 for ln in req.lanes]
        deadline_s = (req.deadline_ms / 1000.0 if req.deadline_ms
                      else self._default_deadline_s)
        # v2 piggybacked trace context: strict decode, garbage ⇒ untraced
        # (never rejected — the context is advisory, not load-bearing)
        trace_ctx = None
        if req.trace_ctx:
            from tmtpu.libs import metrics as _m
            from tmtpu.libs import trace as _trace

            trace_ctx = _trace.adopt(bytes(req.trace_ctx))
            if trace_ctx is None:
                _m.trace_context_invalid.inc(transport="sidecar")
            else:
                _m.trace_context_rx.inc(transport="sidecar")
        try:
            pending = self.coalescer.submit(
                client_id, req.curve, items, req.tally,
                deadline_s=deadline_s, trace_ctx=trace_ctx)
        except Overloaded as exc:
            reject(proto.STATUS_OVERLOADED, str(exc))
            return

        def finish() -> None:
            # grace over the request deadline: the coalescer answers
            # expiry itself; this wait only guards a wedged dispatch
            if not pending.wait(deadline_s + 5.0):
                try:
                    reject(proto.STATUS_BACKEND_DOWN,
                           "dispatch wedged past deadline")
                except OSError:
                    pass
                return
            if pending.mask is None:
                status = _FAILURE_STATUS.get(
                    pending.failure, proto.STATUS_BACKEND_DOWN)
                try:
                    reject(status, pending.error or "verify failed")
                except OSError:
                    pass
                return
            try:
                send(proto.VerifyResponse(
                    request_id=req.request_id,
                    status=proto.STATUS_OK,
                    mask=proto.pack_mask(pending.mask),
                    lane_count=len(pending.mask),
                    tallied=pending.tallied,
                    dispatch_id=pending.dispatch_id,
                    dispatch_lanes=pending.dispatch_lanes,
                    dispatch_clients=pending.dispatch_clients,
                    dispatch_traces=pending.dispatch_traces))
            except OSError:
                pass  # client gone; the dispatch already happened

        # answer off-thread so the connection keeps reading — one client
        # can pipeline many request_ids and they coalesce with each other
        threading.Thread(target=finish, name="sidecar-reply",
                         daemon=True).start()

    # --- health HTTP ---

    def _start_health_http(self) -> None:
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    snap = server.snapshot()
                    br = snap["breakers"].get(
                        crypto_batch.BREAKER_NAME, {})
                    healthy = br.get("state", "closed") != "open"
                    body = json.dumps(
                        {"healthy": healthy, **snap}).encode()
                    self.send_response(200 if healthy else 503)
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    from tmtpu.libs import metrics as _m

                    body = _m.render_prometheus().encode()
                    self.send_response(200)
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    ctype = "text/plain"
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        host, _sep, port = self._health_laddr.rpartition(":")
        httpd = http.server.ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)), Handler)
        self._health_httpd = httpd
        self._health_thread = threading.Thread(
            target=httpd.serve_forever, name="sidecar-health",
            daemon=True)
        self._health_thread.start()
