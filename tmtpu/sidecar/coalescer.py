"""Cross-client batch coalescing: many requests, one device dispatch.

This is the piece that turns one TPU into a shared resource: four
localnet nodes each verifying ~100 lanes/block become one daemon
dispatching ~400-lane joint batches. Requests from any number of
connections enter per-curve queues; a single dispatcher thread gathers
them under the adaptive-flush policy (its own
:class:`~tmtpu.crypto.batch.AdaptiveFlushScheduler` instance, fed by
real request arrivals and real dispatch round-trips) and hands ONE
concatenated lane list per curve to the verify engine. Each request
gets back exactly its slice of the joint mask plus the dispatch
metadata (id, total lanes, distinct clients) so clients — and the
two-client coalescing test — can PROVE their lanes shared a dispatch.

Whole-request granularity: a request's lanes never split across
dispatches, so mask slicing is a single contiguous cut and a request
observes exactly one dispatch. ``max_lanes_per_dispatch`` is therefore
a soft cap — gathering stops once adding the next whole request would
exceed it, but a single oversized request still dispatches alone.

Admission control: ``submit`` rejects with :class:`Overloaded` when
accepting the request would push total queued lanes past
``max_queue_lanes``. The daemon answers ``STATUS_OVERLOADED`` —
explicit backpressure the client converts into in-process fallback —
instead of queueing unboundedly and blowing every caller's deadline.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tmtpu.crypto.batch import AdaptiveFlushScheduler

# verify engine signature: (curve, [(pk, msg, sig, power)], tally)
#   -> (mask, tallied)
VerifyFn = Callable[[str, List[tuple], bool], Tuple[List[bool], int]]


class Overloaded(Exception):
    """Admission control rejected the request; queues are full."""


class PendingRequest:
    """One client's verify request riding toward a joint dispatch."""

    __slots__ = ("client_id", "curve", "items", "tally", "deadline",
                 "enqueued_at", "done", "mask", "tallied", "error",
                 "failure", "dispatch_id", "dispatch_lanes",
                 "dispatch_clients", "trace_ctx", "dispatch_traces")

    def __init__(self, client_id: str, curve: str, items: List[tuple],
                 tally: bool, deadline: Optional[float],
                 trace_ctx=None):
        self.client_id = client_id
        self.curve = curve
        self.items = items
        self.tally = tally
        self.deadline = deadline          # monotonic, None = no deadline
        self.enqueued_at = time.monotonic()
        self.done = threading.Event()
        self.mask: Optional[List[bool]] = None
        self.tallied = 0
        self.error = ""
        self.failure = ""          # "" | "expired" | "engine" | "stopped"
        self.dispatch_id = 0
        self.dispatch_lanes = 0
        self.dispatch_clients = 0
        # distributed-tracing: the request's TraceContext (or None) and,
        # after dispatch, how many traced requests shared the dispatch
        self.trace_ctx = trace_ctx
        self.dispatch_traces = 0

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class Coalescer:
    def __init__(self, verify_fn: VerifyFn, *,
                 max_queue_lanes: int = 65536,
                 max_lanes_per_dispatch: int = 40960,
                 scheduler: Optional[AdaptiveFlushScheduler] = None):
        self._verify_fn = verify_fn
        self._max_queue_lanes = max_queue_lanes
        self._max_lanes_per_dispatch = max_lanes_per_dispatch
        # a PRIVATE scheduler — the daemon's arrival/RTT profile is the
        # aggregate of all clients, distinct from any one node's
        self.scheduler = scheduler or AdaptiveFlushScheduler()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, List[PendingRequest]] = {}
        self._queued_lanes = 0
        self._inflight = 0            # batches cut but not yet answered
        self._dispatch_seq = 0
        self._mesh_dispatches = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="sidecar-coalescer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # fail whatever never dispatched so no client blocks forever
        with self._lock:
            leftovers = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._queued_lanes = 0
        for req in leftovers:
            req.error = "coalescer stopped"
            req.failure = "stopped"
            req.done.set()

    # --- client side ---

    def submit(self, client_id: str, curve: str, items: List[tuple],
               tally: bool, deadline_s: Optional[float] = None,
               trace_ctx=None) -> PendingRequest:
        """Enqueue; returns a waitable :class:`PendingRequest`. Raises
        :class:`Overloaded` when queues are full (never queues partial
        requests). ``trace_ctx`` (a libs.trace.TraceContext or None)
        tags the joint dispatch this request ends up riding."""
        from tmtpu.libs import metrics as _m

        req = PendingRequest(
            client_id, curve, items, tally,
            None if deadline_s is None
            else time.monotonic() + deadline_s,
            trace_ctx=trace_ctx)
        with self._cond:
            if not self._running:
                raise Overloaded("coalescer not running")
            if self._queued_lanes + len(items) > self._max_queue_lanes:
                _m.sidecar_server_overloads_total.inc()
                raise Overloaded(
                    f"queue full: {self._queued_lanes} lanes queued, "
                    f"+{len(items)} exceeds cap {self._max_queue_lanes}")
            self._queues.setdefault(curve, []).append(req)
            self._queued_lanes += len(items)
            _m.sidecar_server_queue_lanes.set(self._queued_lanes)
            self._cond.notify_all()
        self.scheduler.note_arrivals(len(items))
        return req

    def queued_lanes(self) -> int:
        with self._lock:
            return self._queued_lanes

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued request has dispatched AND every cut
        batch has been answered, or the timeout passes (returns False).
        The dispatcher keeps running — graceful shutdown calls drain()
        first (with admission already closed upstream), then stop()."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._running and (self._queued_lanes > 0
                                     or self._inflight > 0):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                # bounded wait: the dispatcher notifies on completion,
                # but a wedged engine must not turn drain into a hang
                self._cond.wait(timeout=min(remaining, 0.25))
            return self._queued_lanes == 0 and self._inflight == 0

    def snapshot(self) -> Dict:
        with self._lock:
            per_curve = {c: sum(len(r.items) for r in q)
                         for c, q in self._queues.items() if q}
            return {"queued_lanes": self._queued_lanes,
                    "queued_by_curve": per_curve,
                    "inflight_batches": self._inflight,
                    "dispatches": self._dispatch_seq,
                    "mesh_dispatches": self._mesh_dispatches,
                    "scheduler": self.scheduler.snapshot()}

    # --- dispatcher ---

    def _pick_curve_locked(self) -> Optional[str]:
        """Curve whose oldest request has waited longest (FIFO across
        curves so a busy ed25519 stream cannot starve a k1 trickle)."""
        best, best_t = None, None
        for curve, q in self._queues.items():
            if q and (best_t is None or q[0].enqueued_at < best_t):
                best, best_t = curve, q[0].enqueued_at
        return best

    def _run(self) -> None:
        while True:
            batch: List[PendingRequest] = []
            with self._cond:
                while self._running:
                    curve = self._pick_curve_locked()
                    if curve is None:
                        self._cond.wait(timeout=0.5)
                        continue
                    q = self._queues[curve]
                    lanes = sum(len(r.items) for r in q)
                    # gather: linger only while the adaptive window says
                    # more arrivals are worth the wait AND the oldest
                    # request has slack before its deadline
                    wait = self.scheduler.gather_wait_s(lanes)
                    if lanes >= self._max_lanes_per_dispatch:
                        wait = 0.0
                    now = time.monotonic()
                    elapsed = now - q[0].enqueued_at
                    remaining = wait - elapsed
                    if q[0].deadline is not None:
                        remaining = min(remaining, q[0].deadline - now)
                    if remaining > 1e-4:
                        self._cond.wait(timeout=remaining)
                        continue
                    # cut whole requests up to the dispatch cap (always
                    # at least one, even if alone it exceeds the cap)
                    taken_lanes = 0
                    while q and (not batch or taken_lanes + len(q[0].items)
                                 <= self._max_lanes_per_dispatch):
                        r = q.pop(0)
                        batch.append(r)
                        taken_lanes += len(r.items)
                    self._queued_lanes -= taken_lanes
                    self._inflight += 1
                    from tmtpu.libs import metrics as _m

                    _m.sidecar_server_queue_lanes.set(self._queued_lanes)
                    break
                if not self._running:
                    return
            if batch:
                try:
                    self._dispatch(batch[0].curve, batch)
                finally:
                    with self._cond:
                        self._inflight -= 1
                        self._cond.notify_all()

    def _dispatch(self, curve: str, batch: List[PendingRequest]) -> None:
        from tmtpu.libs import metrics as _m
        from tmtpu.libs import timeline as _tl

        # expired requests are answered without wasting device lanes
        now = time.monotonic()
        live: List[PendingRequest] = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                req.error = "deadline expired before dispatch"
                req.failure = "expired"
                req.done.set()
            else:
                live.append(req)
        if not live:
            return
        with self._lock:
            self._dispatch_seq += 1
            dispatch_id = self._dispatch_seq
        joint: List[tuple] = []
        for req in live:
            joint.extend(req.items)
        clients = len({req.client_id for req in live})
        tally = any(req.tally for req in live)
        from tmtpu.tpu import mesh_dispatch as _mesh

        mesh_before = _mesh.dispatch_count()
        t0 = time.perf_counter()
        try:
            mask, _tallied = self._verify_fn(curve, joint, tally)
        except Exception as exc:  # noqa: BLE001 — engine bug must not
            # wedge clients; they get an error verdict, never a mask
            for req in live:
                req.error = f"verify engine failed: {exc}"
                req.failure = "engine"
                req.done.set()
            return
        dt = time.perf_counter() - t0
        self.scheduler.note_dispatch(len(joint), dt)
        _m.sidecar_server_dispatches_total.inc(curve=curve)
        _m.sidecar_server_dispatch_lanes.observe(len(joint), curve=curve)
        _m.sidecar_server_dispatch_clients.observe(clients)
        # did the engine shard this joint dispatch across the mesh? The
        # verify path (crypto/batch.py → tpu/mesh_dispatch.py) decides;
        # here we account for it: per-chip occupancy in Stats + metrics
        meshed = _mesh.dispatch_count() - mesh_before
        shards = 0
        if meshed:
            snap = _mesh.snapshot()
            shards = snap["devices"]
            with self._lock:
                self._mesh_dispatches += meshed
            _m.sidecar_server_mesh_dispatches.inc(meshed, curve=curve)
            for dev, lanes in snap["occupancy_lanes"].items():
                _m.sidecar_server_mesh_occupancy_lanes.set(
                    lanes, device=dev)
        _tl.record_sidecar(role="server", curve=curve, lanes=len(joint),
                           clients=clients, requests=len(live),
                           mesh_shards=shards,
                           seconds=round(dt, 6))
        # tag the joint dispatch with every context it served: one
        # sidecar.dispatch mark per distinct trace, so a fleet join sees
        # exactly which heights shared this device flush
        traced = [req.trace_ctx for req in live
                  if req.trace_ctx is not None]
        if traced:
            from tmtpu.libs import trace as _trace

            seen_tids = set()
            for ctx in traced:
                if ctx.trace_id in seen_tids:
                    continue
                seen_tids.add(ctx.trace_id)
                _trace.mark("sidecar.dispatch", ctx=ctx,
                            dispatch_id=dispatch_id, lanes=len(joint),
                            clients=clients, requests=len(live),
                            seconds=round(dt, 6))
        if len(mask) != len(joint):
            for req in live:
                req.error = (f"verify engine returned {len(mask)} verdicts "
                             f"for {len(joint)} lanes")
                req.failure = "engine"
                req.done.set()
            return
        off = 0
        for req in live:
            n = len(req.items)
            req.mask = [bool(v) for v in mask[off:off + n]]
            # per-request tally recomputed from ITS slice — the joint
            # tallied sum spans all clients and belongs to nobody;
            # verify-only requests get 0, not a number they didn't ask for
            req.tallied = sum(it[3] for it, ok
                              in zip(req.items, req.mask)
                              if ok) if req.tally else 0
            req.dispatch_id = dispatch_id
            req.dispatch_lanes = len(joint)
            req.dispatch_clients = clients
            req.dispatch_traces = len(traced)
            off += n
            req.done.set()
