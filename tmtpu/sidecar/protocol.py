"""Sidecar wire protocol: length-prefixed frames carrying typed messages.

Framing (reuses :mod:`tmtpu.libs.protoio` primitives):

    frame   = uvarint(len(body)) || body
    body    = type_byte || payload
    payload = protobuf encoding of the message class for type_byte

One byte of type tag inside the length prefix keeps the stream
self-describing without a wrapper message, and lets the reader reject
unknown or oversized frames before decoding a single field. Both sides
enforce ``max_frame_bytes`` (default 8 MiB) — a VerifyRequest for 40960
lanes of (32B pk, ~110B msg, 64B sig) is ~8.5 MB, so real deployments
raise the cap in lockstep with ``max_lanes_per_dispatch``; the default
covers the 10k-validator north-star with headroom.

Handshake: client sends :class:`Hello` first; server answers
:class:`HelloAck` carrying the NEGOTIATED version (min of both sides,
``SUPPORTED_VERSIONS`` only) or :class:`ErrorReply` (``ERR_VERSION``)
and closes on an unsupported version. Anything else as a first message
is a protocol error. ``PROTOCOL_VERSION`` bumps on any wire change;
since v2 the daemon keeps serving v1 clients (version-skew tolerance:
an old client on a new daemon just never sees the v2-only optional
fields), and a v2 client that gets ``ERR_VERSION`` from a v1 daemon
retries the handshake at version 1.

Version history:
- v1: Hello/HelloAck/Verify/Ping/Stats base protocol.
- v2: optional distributed-tracing context — ``VerifyRequest.trace_ctx``
  (libs/trace.py wire form) and ``VerifyResponse.dispatch_traces``
  (how many traced requests the joint dispatch coalesced). Both fields
  are additive; a v1 peer skips them as unknown fields.

Verify masks travel bit-packed (:func:`pack_mask`/:func:`unpack_mask`):
lane i's verdict is bit ``i & 7`` of byte ``i >> 3``, LSB-first —
40960 lanes fit in 5 KiB instead of a 40960-element repeated bool.
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Tuple, Type

from tmtpu.libs.protoio import (
    DelimitedReader,
    ProtoMessage,
    encode_uvarint,
)

PROTOCOL_VERSION = 2
# every version this tree still speaks; the daemon accepts any of them
# and the negotiated version is min(client, server)
SUPPORTED_VERSIONS = (1, 2)
# first version carrying trace-context fields
TRACE_CTX_MIN_VERSION = 2

# Hard ceiling on one frame; configurable per server/client but both
# sides always enforce *some* cap so a corrupt length prefix can't OOM.
DEFAULT_MAX_FRAME_BYTES = 8 * 1024 * 1024

# --- VerifyResponse.status ---
STATUS_OK = 0
STATUS_OVERLOADED = 1      # admission control rejected; retry or fall back
STATUS_BACKEND_DOWN = 2    # device breaker open server-side; served serially
STATUS_BAD_REQUEST = 3     # unknown curve, zero lanes, malformed lane
STATUS_SHUTTING_DOWN = 4   # daemon draining; do not resubmit

STATUS_NAMES = {
    STATUS_OK: "ok",
    STATUS_OVERLOADED: "overloaded",
    STATUS_BACKEND_DOWN: "backend_down",
    STATUS_BAD_REQUEST: "bad_request",
    STATUS_SHUTTING_DOWN: "shutting_down",
}

# --- ErrorReply.code ---
ERR_VERSION = 1        # Hello.version not in SUPPORTED_VERSIONS
ERR_PROTOCOL = 2       # bad frame / unexpected message sequence
ERR_INTERNAL = 3       # server bug; connection stays usable


class Hello(ProtoMessage):
    FIELDS = [
        (1, "version", "uint32"),
        (2, "client_id", "string"),
        (3, "features", ("rep", "string")),
    ]


class HelloAck(ProtoMessage):
    FIELDS = [
        (1, "version", "uint32"),
        (2, "server_id", "string"),
        (3, "backend", "string"),           # "tpu" | "cpu"
        (4, "max_lanes", "uint32"),          # per-request admission cap
        (5, "max_frame_bytes", "uint64"),
    ]


class Lane(ProtoMessage):
    """One signature to check. ``power`` rides along for fused
    verify+tally; 0 when the request is verify-only."""

    FIELDS = [
        (1, "pub_key", "bytes"),
        (2, "msg", "bytes"),
        (3, "sig", "bytes"),
        (4, "power", "int64"),
    ]


class VerifyRequest(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),
        (2, "curve", "string"),             # "ed25519" | "sr25519" | "secp256k1"
        (3, "tally", "bool"),
        (4, "deadline_ms", "uint32"),        # 0 = server default
        (5, "lanes", ("rep", ("msg", Lane))),
        # v2: optional trace context (libs/trace.py wire form; empty =
        # untraced). Clients only attach it when the daemon acked v2.
        (6, "trace_ctx", "bytes"),
    ]


class VerifyResponse(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),
        (2, "status", "uint32"),
        (3, "mask", "bytes"),                # bit-packed, lane_count bits
        (4, "lane_count", "uint32"),
        (5, "tallied", "int64"),
        (6, "dispatch_id", "uint64"),        # joint-dispatch identity…
        (7, "dispatch_lanes", "uint32"),     # …total lanes it carried
        (8, "dispatch_clients", "uint32"),   # …distinct clients coalesced
        (9, "error", "string"),
        # v2: how many traced requests the joint dispatch served — the
        # coalescer's dispatch span carries the trace ids themselves
        (10, "dispatch_traces", "uint32"),
    ]


class Ping(ProtoMessage):
    FIELDS = [(1, "nonce", "uint64")]


class Pong(ProtoMessage):
    FIELDS = [
        (1, "nonce", "uint64"),
        (2, "backend", "string"),
        (3, "uptime_ms", "uint64"),
    ]


class StatsRequest(ProtoMessage):
    FIELDS = []


class StatsResponse(ProtoMessage):
    """Introspection snapshot; ``stats_json`` is a JSON object so the
    payload can grow without protocol bumps (it is advisory, not
    consensus-critical)."""

    FIELDS = [(1, "stats_json", "bytes")]


class ErrorReply(ProtoMessage):
    FIELDS = [
        (1, "request_id", "uint64"),         # 0 when not tied to a request
        (2, "code", "uint32"),
        (3, "message", "string"),
    ]


# type_byte → message class. Gaps left for future message kinds; numbers
# are wire-visible and MUST never be reused for a different class.
MESSAGE_TYPES: Dict[int, Type[ProtoMessage]] = {
    1: Hello,
    2: HelloAck,
    3: VerifyRequest,
    4: VerifyResponse,
    5: Ping,
    6: Pong,
    7: StatsRequest,
    8: StatsResponse,
    9: ErrorReply,
}

TYPE_BYTES: Dict[Type[ProtoMessage], int] = {
    cls: tb for tb, cls in MESSAGE_TYPES.items()
}


class ProtocolError(Exception):
    """Raised on malformed frames, unknown types, or bad sequencing."""


def encode_frame(msg: ProtoMessage,
                 type_bytes: Optional[Dict[Type[ProtoMessage], int]] = None
                 ) -> bytes:
    """Encode one frame. ``type_bytes`` defaults to the sidecar registry;
    sibling frame protocols (tmtpu/lightserve) pass their own class→tag
    map to reuse the codec without sharing a wire namespace."""
    tb = (TYPE_BYTES if type_bytes is None else type_bytes).get(type(msg))
    if tb is None:
        raise ProtocolError(f"unregistered message type {type(msg).__name__}")
    body = bytes([tb]) + msg.encode()
    return encode_uvarint(len(body)) + body


def decode_frame(body: bytes,
                 message_types: Optional[Dict[int, Type[ProtoMessage]]] = None
                 ) -> ProtoMessage:
    """Decode one frame *body* (type byte + payload, length prefix already
    stripped). ``message_types`` defaults to the sidecar registry; sibling
    protocols pass their own tag→class map."""
    if not body:
        raise ProtocolError("empty frame")
    cls = (MESSAGE_TYPES if message_types is None else message_types
           ).get(body[0])
    if cls is None:
        raise ProtocolError(f"unknown message type {body[0]}")
    try:
        return cls.decode(body[1:])
    except (EOFError, ValueError, UnicodeDecodeError) as exc:
        raise ProtocolError(
            f"malformed {cls.__name__} payload: {exc}") from exc


class FrameReader:
    """Reads framed messages from a binary stream, enforcing the frame cap.

    Thin veneer over :class:`protoio.DelimitedReader`; EOF mid-frame
    surfaces as ``EOFError`` (peer went away), anything else malformed as
    :class:`ProtocolError` so the connection loop can answer
    ``ERR_PROTOCOL`` before closing. ``message_types`` selects the tag
    registry (defaults to the sidecar's).
    """

    def __init__(self, stream, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 message_types: Optional[Dict[int,
                                              Type[ProtoMessage]]] = None):
        self._rd = DelimitedReader(stream, max_size=max_frame_bytes)
        self._message_types = message_types

    def read_msg(self) -> ProtoMessage:
        try:
            body = self._rd.read_msg()
        except ValueError as exc:  # oversized frame / runaway varint
            raise ProtocolError(str(exc)) from exc
        return decode_frame(body, self._message_types)


def pack_mask(mask: List[bool]) -> bytes:
    out = bytearray((len(mask) + 7) // 8)
    for i, ok in enumerate(mask):
        if ok:
            out[i >> 3] |= 1 << (i & 7)
    return bytes(out)


def unpack_mask(packed: bytes, lane_count: int) -> List[bool]:
    if len(packed) < (lane_count + 7) // 8:
        raise ProtocolError(
            f"mask too short: {len(packed)} bytes for {lane_count} lanes")
    return [bool(packed[i >> 3] & (1 << (i & 7))) for i in range(lane_count)]


def write_frame(stream: io.RawIOBase, msg: ProtoMessage,
                type_bytes: Optional[Dict[Type[ProtoMessage], int]] = None
                ) -> None:
    stream.write(encode_frame(msg, type_bytes))
    flush = getattr(stream, "flush", None)
    if flush is not None:
        flush()


def parse_addr(addr: str) -> Tuple[str, object]:
    """Parse ``unix:///path/to.sock`` or ``tcp://host:port`` into
    ``("unix", path)`` / ``("tcp", (host, port))``."""
    if addr.startswith("unix://"):
        path = addr[len("unix://"):]
        if not path:
            raise ValueError(f"empty unix socket path in {addr!r}")
        return "unix", path
    if addr.startswith("tcp://"):
        hostport = addr[len("tcp://"):]
        host, sep, port = hostport.rpartition(":")
        if not sep or not host:
            raise ValueError(f"tcp address needs host:port: {addr!r}")
        return "tcp", (host, int(port))
    raise ValueError(
        f"sidecar address must be unix:// or tcp://, got {addr!r}")
