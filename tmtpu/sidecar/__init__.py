"""Verification sidecar — a standalone batch-verify daemon.

The BASELINE.json north-star ships signature batches to "a JAX/Pallas
sidecar"; this package is that daemon. One process owns the jax device
(paying the ~35 s kernel compile exactly once per daemon lifetime) and
serves batched ed25519/sr25519/secp256k1 verification plus fused
verify+tally to any number of node processes over a length-prefixed
unix-socket/TCP protocol (libs/protoio framing, tmtpu/sidecar/protocol.py).

Why a daemon instead of per-process device access: committee-based
consensus work (arXiv:2302.00418) shows batch amplitude is the dominant
throughput lever for ed25519, and on a multi-validator host the only way
to reach large batches is to COALESCE lanes across processes — four
localnet nodes each verifying ~100 lanes/block become one daemon
dispatching ~400-lane joint batches. The server-side coalescer
(coalescer.py) gathers lanes from concurrent clients under the adaptive
flush EWMAs from crypto/batch.py and returns exact per-lane masks to
each submitter.

Layers:

- ``protocol.py`` — wire messages (Hello/HelloAck handshake with version
  check, VerifyRequest/VerifyResponse, Ping/Pong, Stats) and framing
  (uvarint length prefix + 1-byte type tag), with hard frame-size caps.
- ``coalescer.py`` — cross-client batch coalescing with bounded queues,
  admission control, and explicit overload verdicts.
- ``server.py`` — the daemon: socket listener, per-connection protocol
  loop, the verify engine (crypto/batch verifiers — so the sidecar gets
  the sigcache, the per-curve breakers and the serial fallback for
  free), warm-start compilation, and an optional HTTP /healthz+/metrics
  listener.
- ``client.py`` — ``SidecarClient``: multiplexed request/response over
  one connection, connection retry with backoff, per-request deadlines.

Node processes select the daemon with ``crypto.backend=sidecar``
(config) — ``crypto/batch.py SidecarBatchVerifier`` slots UNDER the
sigcache→dedup→breaker stack and falls back to in-process verify (then
serial CPU) when the daemon is down or slow, so killing the daemon
mid-run degrades throughput but never correctness.
"""

from tmtpu.sidecar.protocol import PROTOCOL_VERSION  # noqa: F401
