"""Mock peer/reactor for reactor unit tests (reference analogue:
p2p/mock/peer.go and the Reactor test doubles in p2p/mocks/).

``MockPeer`` satisfies the surface reactors use (id/send/metadata);
``MockReactor`` records everything routed to it. Neither opens sockets, so
reactor logic can be tested without a Switch or TCP.
"""

from __future__ import annotations

import threading


class MockPeer:
    """In-memory peer: captures sent messages per channel."""

    def __init__(self, node_id: str = "mockpeer0000000000000000",
                 outbound: bool = False, persistent: bool = False):
        self.node_id = node_id
        self.outbound = outbound
        self.persistent = persistent
        self.sent: list[tuple[int, bytes]] = []
        self._kv: dict = {}
        self._running = True
        self._lock = threading.Lock()

    # surface used by reactors / PeerState
    @property
    def id(self) -> str:
        return self.node_id

    def is_running(self) -> bool:
        return self._running

    def send(self, chan_id: int, payload: bytes) -> bool:
        if not self._running:
            return False
        with self._lock:
            self.sent.append((chan_id, bytes(payload)))
        return True

    def try_send(self, chan_id: int, payload: bytes) -> bool:
        return self.send(chan_id, payload)

    def get(self, key, default=None):
        return self._kv.get(key, default)

    def set(self, key, value):
        self._kv[key] = value

    def stop(self):
        self._running = False

    # test helpers
    def sent_on(self, chan_id: int) -> list[bytes]:
        with self._lock:
            return [p for c, p in self.sent if c == chan_id]


class MockReactor:
    """Records peers added/removed and messages received per channel."""

    def __init__(self, channels: list[int]):
        self.channels = channels
        self.peers: list = []
        self.removed: list = []
        self.received: list[tuple[str, int, bytes]] = []
        self.switch = None

    def get_channels(self):
        return self.channels

    def set_switch(self, sw):
        self.switch = sw

    def add_peer(self, peer):
        self.peers.append(peer)

    def remove_peer(self, peer, reason=""):
        self.removed.append((peer, reason))

    def receive(self, chan_id: int, peer, payload: bytes):
        self.received.append((getattr(peer, "id", "?"), chan_id,
                              bytes(payload)))

    def start(self):
        pass

    def stop(self):
        pass
