"""Peer trust metric (reference analogue: p2p/trust/ — metric.go's
interval-weighted good/bad event history and store.go's per-peer
persistence).

Design (same model as the reference, re-derived): time is divided into
fixed intervals; each interval accumulates good/bad event counts and
closes into a history ring. The metric value combines

    r = current-interval proportion (weight fades in as the interval fills)
    h = history value: weighted average over past intervals, recent
        intervals weighted highest
    d = derivative penalty when the current proportion is falling below
        the historic trend

giving a score in [0, 1] (new peers start at 1). A TrustMetricStore keys
metrics by peer id and persists scores across restarts via the node DB.
"""

from __future__ import annotations

import json
import threading
import time


class TrustMetric:
    INTERVAL_S = 30.0
    MAX_HISTORY = 16

    def __init__(self, now: float | None = None):
        self._lock = threading.Lock()
        self._good = 0.0
        self._bad = 0.0
        self._history: list[float] = []
        self._start = now if now is not None else time.monotonic()

    # -- event input --------------------------------------------------------

    def good_event(self, weight: float = 1.0, now: float | None = None):
        with self._lock:
            self._roll(now)
            self._good += weight

    def bad_event(self, weight: float = 1.0, now: float | None = None):
        with self._lock:
            self._roll(now)
            self._bad += weight

    # -- internals ----------------------------------------------------------

    def _roll(self, now: float | None):
        now = now if now is not None else time.monotonic()
        while now - self._start >= self.INTERVAL_S:
            self._history.append(self._proportion())
            if len(self._history) > self.MAX_HISTORY:
                self._history.pop(0)
            self._good = self._bad = 0.0
            self._start += self.INTERVAL_S

    def _proportion(self) -> float:
        total = self._good + self._bad
        if total == 0:
            return 1.0
        return self._good / total

    def _history_value(self) -> float:
        if not self._history:
            return 1.0
        # recent intervals weigh most: weight k+1 for the k-th oldest
        num = den = 0.0
        for k, v in enumerate(self._history):
            w = float(k + 1)
            num += w * v
            den += w
        return num / den

    # -- output -------------------------------------------------------------

    def value(self, now: float | None = None) -> float:
        with self._lock:
            self._roll(now)
            now = now if now is not None else time.monotonic()
            r = self._proportion()
            h = self._history_value()
            # fade the current interval in as it fills
            a = min((now - self._start) / self.INTERVAL_S, 1.0) * 0.5
            v = a * r + (1.0 - a) * h
            # derivative penalty when behavior is degrading
            if r < h:
                v += (r - h) * 0.25
            return max(0.0, min(1.0, v))


class TrustMetricStore:
    """Per-peer metrics with JSON persistence (store.go)."""

    KEY = b"trust/metrics"

    def __init__(self, db=None):
        self._lock = threading.Lock()
        self._metrics: dict[str, TrustMetric] = {}
        self._db = db
        self._seed: dict[str, float] = {}
        if db is not None:
            raw = db.get(self.KEY)
            if raw:
                try:
                    self._seed = json.loads(raw.decode())
                except ValueError:
                    self._seed = {}

    def get(self, peer_id: str) -> TrustMetric:
        with self._lock:
            m = self._metrics.get(peer_id)
            if m is None:
                m = TrustMetric()
                # resume from the persisted score as one history interval
                seed = self._seed.get(peer_id)
                if seed is not None:
                    m._history.append(seed)
                self._metrics[peer_id] = m
            return m

    def peer_disconnected(self, peer_id: str):
        self.save()

    def save(self):
        if self._db is None:
            return
        with self._lock:
            data = {pid: m.value() for pid, m in self._metrics.items()}
            data.update({k: v for k, v in self._seed.items()
                         if k not in data})
        self._db.set(self.KEY, json.dumps(data).encode())
