"""Peer-behaviour reporting (reference analogue: behaviour/ — the
``Reporter`` abstraction that decouples "this peer did X" from "what to do
about it"; upstream it is consumed by blockchain/v2).

``SwitchReporter`` translates bad behavior into switch actions
(stop-for-error) and good behavior into trust-metric credit;
``MockReporter`` records reports for tests.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    reason: str    # e.g. "bad_block", "bad_message", "consensus_vote"
    good: bool = False


class MockReporter:
    def __init__(self):
        self._lock = threading.Lock()
        self.reports: list[PeerBehaviour] = []

    def report(self, pb: PeerBehaviour) -> None:
        with self._lock:
            self.reports.append(pb)

    def of(self, peer_id: str) -> list[PeerBehaviour]:
        with self._lock:
            return [r for r in self.reports if r.peer_id == peer_id]


class SwitchReporter:
    """Routes bad behavior to Switch.stop_peer_for_error and feeds the
    trust metric store when one is attached."""

    def __init__(self, switch, trust_store=None):
        self.switch = switch
        self.trust_store = trust_store

    def report(self, pb: PeerBehaviour) -> None:
        if self.trust_store is not None:
            metric = self.trust_store.get(pb.peer_id)
            (metric.good_event if pb.good else metric.bad_event)()
        if pb.good:
            return
        peer = self.switch.peers.get(pb.peer_id) \
            if hasattr(self.switch, "peers") else None
        if peer is not None:
            self.switch.stop_peer_for_error(peer, pb.reason)
