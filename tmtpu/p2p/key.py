"""Node identity (reference: p2p/key.go) — ed25519 node key; the node ID is
the hex of the pubkey address."""

from __future__ import annotations

import json
import os
from typing import Optional

from tmtpu.crypto import ed25519


class NodeKey:
    def __init__(self, priv_key):
        self.priv_key = priv_key

    @property
    def node_id(self) -> str:
        """ID = hex(address(pubkey)) (p2p/key.go PubKeyToID)."""
        return self.priv_key.pub_key().address().hex()

    def pub_key(self):
        return self.priv_key.pub_key()

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(ed25519.gen_priv_key())

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            return cls(ed25519.PrivKeyEd25519(
                bytes.fromhex(d["priv_key"]["value"])))
        nk = cls.generate()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"priv_key": {"type": "ed25519",
                                    "value": nk.priv_key.bytes().hex()}}, f)
        return nk
