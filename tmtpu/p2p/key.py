"""Node identity (reference: p2p/key.go) — ed25519 node key; the node ID is
the hex of the pubkey address."""

from __future__ import annotations

import json
import os
from typing import Optional

from tmtpu.crypto import ed25519


class NodeKey:
    def __init__(self, priv_key):
        self.priv_key = priv_key

    @property
    def node_id(self) -> str:
        """ID = hex(address(pubkey)) (p2p/key.go PubKeyToID)."""
        return self.priv_key.pub_key().address().hex()

    def pub_key(self):
        return self.priv_key.pub_key()

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(ed25519.gen_priv_key())

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        """node_key.json in the reference's amino form (p2p/key.go
        NodeKey through libs/json: tendermint/PrivKeyEd25519 + base64);
        legacy tmtpu hex files still load."""
        from tmtpu.libs import amino_json

        if os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            return cls(amino_json.unmarshal_priv_key(d["priv_key"]))
        nk = cls.generate()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {"priv_key": amino_json.marshal_priv_key(nk.priv_key)}, f)
        return nk
