"""Peer, Reactor and Switch (reference: p2p/peer.go:23, p2p/base_reactor.go,
p2p/switch.go).

The Switch owns the transport, the reactor registry (channel id → reactor)
and the peer set; it accepts inbound peers, dials persistent peers with
backoff, and fans Broadcast out over all peers' MConnections.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from tmtpu.libs.service import BaseService
from tmtpu.p2p.conn.connection import ChannelDescriptor, MConnection
from tmtpu.p2p.transport import NodeInfo, Transport, parse_peer_addr


class Reactor:
    """p2p/base_reactor.go Reactor interface."""

    def __init__(self, name: str):
        self.name = name
        self.switch: Optional["Switch"] = None

    def get_channels(self) -> List[ChannelDescriptor]:
        return []

    def init_peer(self, peer: "Peer") -> None:
        """Attach per-peer state BEFORE the connection's recv routine
        starts (p2p/base_reactor.go InitPeer). Anything receive() needs
        must be set here, not in add_peer — a peer's first messages can
        arrive before add_peer runs."""

    def add_peer(self, peer: "Peer") -> None:
        pass

    def remove_peer(self, peer: "Peer", reason) -> None:
        pass

    def receive(self, channel_id: int, peer: "Peer", msg_bytes: bytes) -> None:
        pass

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass


class Peer:
    """p2p/peer.go — a connected peer wrapping its MConnection."""

    def __init__(self, conn, node_info: NodeInfo, remote_ip: str,
                 outbound: bool, channel_descs, on_receive, on_error,
                 send_rate: int = 5_120_000, recv_rate: int = 5_120_000):
        self.node_info = node_info
        self.remote_ip = remote_ip
        self.outbound = outbound
        self.mconn = MConnection(conn, channel_descs,
                                 lambda ch, msg: on_receive(self, ch, msg),
                                 lambda err: on_error(self, err),
                                 send_rate=send_rate, recv_rate=recv_rate)
        self._data: Dict[str, object] = {}
        self._data_lock = threading.Lock()

    @property
    def node_id(self) -> str:
        return self.node_info.node_id

    @property
    def moniker(self) -> str:
        return self.node_info.moniker

    def start(self) -> None:
        self.mconn.start()

    def stop(self) -> None:
        self.mconn.stop()

    def is_running(self) -> bool:
        return self.mconn.is_running()

    def has_channel(self, channel_id: int) -> bool:
        """peer.go hasChannel — did the peer advertise this channel in its
        NodeInfo? Sending on an unadvertised channel is a fatal 'unknown
        channel' error on the remote's MConnection."""
        return channel_id in self.node_info.channels

    def send(self, channel_id: int, msg: bytes) -> bool:
        if not self.has_channel(channel_id):
            return False
        return self.mconn.send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        if not self.has_channel(channel_id):
            return False
        return self.mconn.try_send(channel_id, msg)

    def get(self, key: str):
        with self._data_lock:
            return self._data.get(key)

    def set(self, key: str, value) -> None:
        with self._data_lock:
            self._data[key] = value

    def __repr__(self):
        return f"Peer{{{self.node_id[:12]} {self.remote_ip}}}"


class Switch(BaseService):
    RECONNECT_BASE_S = 0.5
    RECONNECT_MAX_TRIES = 20

    def __init__(self, transport: Transport,
                 max_inbound: int = 40, max_outbound: int = 10,
                 send_rate: int = 5_120_000, recv_rate: int = 5_120_000):
        super().__init__("Switch")
        self.transport = transport
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        self.reactors: Dict[str, Reactor] = {}
        self._channel_descs: List[ChannelDescriptor] = []
        self._reactor_by_channel: Dict[int, Reactor] = {}
        self.peers: Dict[str, Peer] = {}
        self._peers_lock = threading.RLock()
        self._persistent: List[str] = []  # "id@host:port"
        self.max_inbound = max_inbound
        self.max_outbound = max_outbound
        self._threads: List[threading.Thread] = []

    # -- wiring -------------------------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        for d in reactor.get_channels():
            if d.channel_id in self._reactor_by_channel:
                raise ValueError(f"channel {d.channel_id} already claimed")
            self._reactor_by_channel[d.channel_id] = reactor
            self._channel_descs.append(d)
        reactor.switch = self
        self.reactors[name] = reactor

    @property
    def node_id(self) -> str:
        return self.transport.node_key.node_id

    def set_persistent_peers(self, addrs: List[str]) -> None:
        self._persistent = [a for a in addrs if a]

    # -- lifecycle ----------------------------------------------------------

    def on_start(self) -> None:
        for r in self.reactors.values():
            r.on_start()
        t = threading.Thread(target=self._accept_routine, daemon=True,
                             name="switch-accept")
        t.start()
        self._threads.append(t)
        for addr in self._persistent:
            t = threading.Thread(target=self._dial_persistent, args=(addr,),
                                 daemon=True, name=f"dial-{addr[:16]}")
            t.start()
            self._threads.append(t)

    def on_stop(self) -> None:
        self.transport.close()
        with self._peers_lock:
            peers = list(self.peers.values())
        for p in peers:
            p.stop()
        for r in self.reactors.values():
            r.on_stop()

    # -- peer lifecycle -----------------------------------------------------

    def _accept_routine(self) -> None:
        # each upgrade runs in its own thread so a stalled client can't
        # block inbound connectivity (transport.go accepts concurrently)
        while self.is_running():
            try:
                conn, addr = self.transport._listener.accept()
            except OSError:
                if not self.is_running():
                    return
                time.sleep(0.05)
                continue
            threading.Thread(target=self._upgrade_inbound,
                             args=(conn, addr[0]), daemon=True,
                             name="switch-upgrade").start()

    def _upgrade_inbound(self, conn, ip: str) -> None:
        try:
            sc, ni = self.transport._upgrade(conn)
        except Exception:
            try:
                conn.close()
            except OSError:
                pass
            return
        with self._peers_lock:
            n_in = sum(1 for p in self.peers.values() if not p.outbound)
        if n_in >= self.max_inbound:
            sc.close()
            return
        self._add_peer_conn(sc, ni, ip, outbound=False)

    def _dial_persistent(self, addr: str) -> None:
        """Persistent peers are redialed forever with capped exponential
        backoff (switch.go reconnectToPeer — never give up on persistent)."""
        pid, hp = parse_peer_addr(addr)
        tries = 0
        while self.is_running():
            with self._peers_lock:
                connected = bool(pid) and pid in self.peers
            if connected:
                tries = 0
            else:
                try:
                    sc, ni, ip = self.transport.dial(hp, expected_id=pid)
                    self._add_peer_conn(sc, ni, ip, outbound=True)
                    tries = 0
                except Exception:
                    tries += 1
            time.sleep(min(self.RECONNECT_BASE_S * (2 ** min(tries, 6)), 30)
                       if tries else 1.0)

    def dial_peer(self, addr: str) -> Optional[Peer]:
        pid, hp = parse_peer_addr(addr)
        sc, ni, ip = self.transport.dial(hp, expected_id=pid)
        return self._add_peer_conn(sc, ni, ip, outbound=True)

    def _add_peer_conn(self, sc, ni: NodeInfo, ip: str, outbound: bool
                       ) -> Optional[Peer]:
        if ni.node_id == self.node_id:
            sc.close()  # self-connection (switch.go filters these)
            return None
        with self._peers_lock:
            if ni.node_id in self.peers:
                sc.close()
                return None
            peer = Peer(sc, ni, ip, outbound, self._channel_descs,
                        self._on_peer_receive, self._on_peer_error,
                        send_rate=self.send_rate, recv_rate=self.recv_rate)
            self.peers[ni.node_id] = peer
            from tmtpu.libs import metrics as _m

            _m.p2p_peers.set(len(self.peers))
        # reference ordering (switch.go addPeer): InitPeer on every reactor
        # BEFORE the connection starts delivering, then AddPeer — one-shot
        # messages (e.g. consensus NewRoundStep) sent by the remote right
        # after its handshake would otherwise race the peer-state setup and
        # be dropped
        for r in self.reactors.values():
            try:
                r.init_peer(peer)
            except Exception:
                pass
        peer.start()
        for r in self.reactors.values():
            try:
                r.add_peer(peer)
            except Exception:
                pass
        return peer

    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        self._remove_peer(peer, reason)

    def _on_peer_error(self, peer: Peer, err: Exception) -> None:
        self._remove_peer(peer, err)

    def _remove_peer(self, peer: Peer, reason) -> None:
        from tmtpu.libs import metrics as _m

        with self._peers_lock:
            existing = self.peers.pop(peer.node_id, None)
            if existing is not None:
                _m.p2p_peers.set(len(self.peers))
        if existing is None:
            return
        peer.stop()
        for r in self.reactors.values():
            try:
                r.remove_peer(peer, reason)
            except Exception:
                pass

    def _on_peer_receive(self, peer: Peer, channel_id: int, msg: bytes
                         ) -> None:
        reactor = self._reactor_by_channel.get(channel_id)
        if reactor is None:
            return
        try:
            reactor.receive(channel_id, peer, msg)
        except Exception as e:  # noqa: BLE001
            from tmtpu.libs import metrics as _m

            _m.p2p_recv_errors.inc(channel=f"0x{channel_id:02x}")
            self.stop_peer_for_error(peer, e)

    # -- broadcast (switch.go:306) ------------------------------------------

    def broadcast(self, channel_id: int, msg: bytes) -> None:
        with self._peers_lock:
            peers = list(self.peers.values())
        for p in peers:
            p.try_send(channel_id, msg)

    def peers_list(self) -> List[Peer]:
        with self._peers_lock:
            return list(self.peers.values())

    def num_peers(self) -> int:
        with self._peers_lock:
            return len(self.peers)
