"""UPnP IGD port mapping (reference analogue: p2p/upnp — NAT traversal
used by the probe-upnp CLI and the node's external-address discovery).

Protocol (same as the reference, re-implemented from the UPnP IGD spec):
1. SSDP discovery: UDP multicast M-SEARCH to 239.255.255.250:1900 for
   ``InternetGatewayDevice``; the gateway answers with a LOCATION header.
2. Fetch the device-description XML from LOCATION; find the
   ``WANIPConnection`` (or ``WANPPPConnection``) service's controlURL.
3. SOAP calls on the control URL: GetExternalIPAddress,
   AddPortMapping, DeletePortMapping.

Everything protocol-level (request building, response parsing) is pure
and unit-tested; only ``discover()`` touches the network (and simply
times out in a NAT-less/zero-egress deployment).
"""

from __future__ import annotations

import socket
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from urllib.parse import urljoin

SSDP_ADDR = ("239.255.255.250", 1900)
SEARCH_TARGET = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
_WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


def build_msearch(timeout_s: int = 2) -> bytes:
    return (
        "M-SEARCH * HTTP/1.1\r\n"
        f"HOST: {SSDP_ADDR[0]}:{SSDP_ADDR[1]}\r\n"
        'MAN: "ssdp:discover"\r\n'
        f"MX: {timeout_s}\r\n"
        f"ST: {SEARCH_TARGET}\r\n"
        "\r\n"
    ).encode()


def parse_ssdp_response(data: bytes) -> str | None:
    """LOCATION header from an SSDP HTTP/1.1 200 response (or None)."""
    try:
        text = data.decode("utf-8", "replace")
    except Exception:
        return None
    lines = text.split("\r\n")
    if not lines or "200" not in lines[0]:
        return None
    for line in lines[1:]:
        name, _, value = line.partition(":")
        if name.strip().lower() == "location":
            return value.strip()
    return None


def parse_control_url(desc_xml: bytes, base_url: str) -> str | None:
    """controlURL of the WAN(IP|PPP)Connection service from the gateway's
    device-description document, resolved against base_url."""
    try:
        root = ET.fromstring(desc_xml)
    except ET.ParseError:
        return None
    ns = "{urn:schemas-upnp-org:device-1-0}"
    for svc in root.iter(f"{ns}service"):
        stype = svc.findtext(f"{ns}serviceType", "")
        if stype in _WAN_SERVICES:
            ctl = svc.findtext(f"{ns}controlURL", "")
            if ctl:
                return urljoin(base_url, ctl)
    return None


def build_soap(action: str, service: str, args: dict) -> tuple[bytes, dict]:
    """(body, headers) for an IGD SOAP call."""
    arg_xml = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
    body = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{service}">{arg_xml}</u:{action}>'
        "</s:Body></s:Envelope>"
    ).encode()
    headers = {
        "Content-Type": 'text/xml; charset="utf-8"',
        "SOAPAction": f'"{service}#{action}"',
    }
    return body, headers


def parse_soap_value(resp_xml: bytes, tag: str) -> str | None:
    try:
        root = ET.fromstring(resp_xml)
    except ET.ParseError:
        return None
    for el in root.iter():
        if el.tag.rsplit("}", 1)[-1] == tag:
            return el.text or ""
    return None


@dataclass
class Gateway:
    control_url: str
    service: str = _WAN_SERVICES[0]

    def _call(self, action: str, args: dict, timeout: float = 5.0) -> bytes:
        body, headers = build_soap(action, self.service, args)
        req = urllib.request.Request(self.control_url, data=body,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()

    def external_ip(self) -> str | None:
        resp = self._call("GetExternalIPAddress", {})
        return parse_soap_value(resp, "NewExternalIPAddress")

    def add_port_mapping(self, external_port: int, internal_port: int,
                         internal_ip: str, proto: str = "TCP",
                         description: str = "tmtpu",
                         lease_s: int = 0) -> bool:
        self._call("AddPortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": external_port,
            "NewProtocol": proto,
            "NewInternalPort": internal_port,
            "NewInternalClient": internal_ip,
            "NewEnabled": 1,
            "NewPortMappingDescription": description,
            "NewLeaseDuration": lease_s,
        })
        return True

    def delete_port_mapping(self, external_port: int,
                            proto: str = "TCP") -> bool:
        self._call("DeletePortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": external_port,
            "NewProtocol": proto,
        })
        return True


def discover(timeout_s: float = 3.0) -> Gateway | None:
    """SSDP-discover an internet gateway; None when there isn't one
    (normal in datacenter/zero-egress deployments)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout_s)
    try:
        sock.sendto(build_msearch(int(timeout_s)), SSDP_ADDR)
        location = None
        try:
            while location is None:
                data, _ = sock.recvfrom(4096)
                location = parse_ssdp_response(data)
        except socket.timeout:
            return None
        with urllib.request.urlopen(location, timeout=timeout_s) as r:
            desc = r.read()
        ctl = parse_control_url(desc, location)
        return Gateway(ctl) if ctl else None
    except OSError:
        return None
    finally:
        sock.close()
