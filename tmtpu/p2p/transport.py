"""Transport + NodeInfo handshake (reference: p2p/transport.go
MultiplexTransport, p2p/node_info.go).

Dial/accept TCP, upgrade to SecretConnection, then swap DefaultNodeInfo
protos and validate compatibility (chain network, ID match)."""

from __future__ import annotations

import socket
import threading
from typing import List, Optional, Tuple

from tmtpu.libs.protoio import ProtoMessage, encode_uvarint, decode_uvarint

from tmtpu.p2p.conn import secret_connection as _sc

if _sc.HAVE_CRYPTO:
    SecretConnection = _sc.SecretConnection
else:  # no `cryptography` package on this box: fall back to the
    # authenticated-plaintext dev connection (same handshake shape and
    # duck-typed surface; see plain_connection.py for the security caveats)
    import warnings

    from tmtpu.p2p.conn.plain_connection import PlainAuthConnection as \
        SecretConnection  # noqa: N814

    warnings.warn(
        "tmtpu.p2p: `cryptography` not installed — peer connections are "
        "AUTHENTICATED PLAINTEXT (dev/CI fallback, single-host use only)",
        RuntimeWarning, stacklevel=2)
from tmtpu.p2p.key import NodeKey


class ProtocolVersionPB(ProtoMessage):
    FIELDS = [(1, "p2p", "uint64"), (2, "block", "uint64"), (3, "app", "uint64")]


class NodeInfoOtherPB(ProtoMessage):
    FIELDS = [(1, "tx_index", "string"), (2, "rpc_address", "string")]


class NodeInfoPB(ProtoMessage):
    """proto/tendermint/p2p/types.proto DefaultNodeInfo."""

    FIELDS = [
        (1, "protocol_version", ("msg!", ProtocolVersionPB)),
        (2, "default_node_id", "string"),
        (3, "listen_addr", "string"),
        (4, "network", "string"),
        (5, "version", "string"),
        (6, "channels", "bytes"),
        (7, "moniker", "string"),
        (8, "other", ("msg!", NodeInfoOtherPB)),
    ]


class NodeInfo:
    def __init__(self, node_id: str, listen_addr: str, network: str,
                 version: str, channels: bytes, moniker: str,
                 p2p_version: int = 8, block_version: int = 11,
                 rpc_address: str = ""):
        self.node_id = node_id
        self.listen_addr = listen_addr
        self.network = network
        self.version = version
        self.channels = channels
        self.moniker = moniker
        self.p2p_version = p2p_version
        self.block_version = block_version
        self.rpc_address = rpc_address

    def to_proto(self) -> NodeInfoPB:
        return NodeInfoPB(
            protocol_version=ProtocolVersionPB(p2p=self.p2p_version,
                                               block=self.block_version),
            default_node_id=self.node_id,
            listen_addr=self.listen_addr,
            network=self.network,
            version=self.version,
            channels=self.channels,
            moniker=self.moniker,
            other=NodeInfoOtherPB(tx_index="on",
                                  rpc_address=self.rpc_address),
        )

    @classmethod
    def from_proto(cls, m: NodeInfoPB) -> "NodeInfo":
        return cls(m.default_node_id, m.listen_addr, m.network, m.version,
                   bytes(m.channels), m.moniker,
                   m.protocol_version.p2p if m.protocol_version else 0,
                   m.protocol_version.block if m.protocol_version else 0,
                   m.other.rpc_address if m.other else "")

    def compatible_with(self, other: "NodeInfo") -> Optional[str]:
        """node_info.go CompatibleWith — None if ok, else reason."""
        if self.block_version != other.block_version:
            return f"peer block version {other.block_version} != {self.block_version}"
        if self.network != other.network:
            return f"peer network {other.network!r} != {self.network!r}"
        if not set(self.channels) & set(other.channels):
            return "no common channels"
        return None


MAX_NODE_INFO_SIZE = 10240  # node_info.go MaxNodeInfoSize


class TransportError(Exception):
    pass


class Transport:
    """p2p/transport.go MultiplexTransport."""

    def __init__(self, node_key: NodeKey, node_info: NodeInfo,
                 dial_timeout: float = 3.0, handshake_timeout: float = 20.0,
                 conn_wrapper=None):
        self.node_key = node_key
        self.node_info = node_info
        self.dial_timeout = dial_timeout
        self.handshake_timeout = handshake_timeout
        # conn_wrapper(secret_conn, peer_id) -> conn-like — the link
        # shaping / fuzzing shim (p2p/shaping.py, p2p/fuzz.py). Applied
        # after the handshake, once the peer's wire identity is known,
        # so both inbound and outbound connections are covered and the
        # handshake itself is never shaped.
        self.conn_wrapper = conn_wrapper
        self._listener: Optional[socket.socket] = None
        self._closed = threading.Event()

    def listen(self, addr: str) -> None:
        host, port = _split_addr(addr)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)

    @property
    def listen_port(self) -> int:
        return self._listener.getsockname()[1]

    def accept(self) -> Tuple[SecretConnection, NodeInfo, str]:
        """Block until an inbound peer completes the upgrade.
        Returns (secret_conn, peer_node_info, remote_ip)."""
        conn, addr = self._listener.accept()
        try:
            return self._upgrade(conn) + (addr[0],)
        except Exception:
            conn.close()
            raise

    def dial(self, addr: str, expected_id: str = ""
             ) -> Tuple[SecretConnection, NodeInfo, str]:
        host, port = _split_addr(addr)
        conn = socket.create_connection((host, port),
                                        timeout=self.dial_timeout)
        conn.settimeout(self.handshake_timeout)
        try:
            sc, ni = self._upgrade(conn)
        except Exception:
            conn.close()
            raise
        if expected_id and ni.node_id != expected_id:
            sc.close()
            raise TransportError(
                f"dialed {expected_id} but got {ni.node_id}")
        conn.settimeout(None)
        return sc, ni, host

    def _upgrade(self, conn: socket.socket
                 ) -> Tuple[SecretConnection, NodeInfo]:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(self.handshake_timeout)
        sc = SecretConnection(conn, self.node_key.priv_key)
        # verify the authenticated key matches the claimed node id later;
        # now swap NodeInfo (transport.go handshake)
        data = self.node_info.to_proto().encode()
        sc.write(encode_uvarint(len(data)) + data)
        buf = b""
        while True:
            buf += sc.read_exact(1)
            try:
                n, _ = decode_uvarint(buf, 0)
                break
            except EOFError:
                continue
        if n > MAX_NODE_INFO_SIZE:
            raise TransportError(f"node info too big: {n}")
        peer_info = NodeInfo.from_proto(NodeInfoPB.decode(sc.read_exact(n)))
        # the wire identity must match the claimed id (transport.go:...)
        wire_id = sc.remote_pub_key.address().hex()
        if peer_info.node_id != wire_id:
            raise TransportError(
                f"peer claimed id {peer_info.node_id} but wire identity is "
                f"{wire_id}")
        reason = self.node_info.compatible_with(peer_info)
        if reason is not None:
            raise TransportError(f"incompatible peer: {reason}")
        conn.settimeout(None)
        if self.conn_wrapper is not None:
            sc = self.conn_wrapper(sc, peer_info.node_id)
        return sc, peer_info

    def close(self) -> None:
        self._closed.set()
        if self._listener is not None:
            self._listener.close()


def _split_addr(addr: str) -> Tuple[str, int]:
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://"):]
    if "@" in addr:  # id@host:port
        addr = addr.split("@", 1)[1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def parse_peer_addr(addr: str) -> Tuple[str, str]:
    """'id@host:port' -> (id, 'host:port')."""
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://"):]
    if "@" in addr:
        pid, hp = addr.split("@", 1)
        return pid, hp
    return "", addr
