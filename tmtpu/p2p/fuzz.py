"""FuzzedConnection (reference: p2p/fuzz.go) — wraps a connection-like
object and probabilistically delays or drops reads/writes, driven by
FuzzConnConfig (config/config.go:663). Used by network fault-injection
tests — and, via the ``[p2p] fuzz_*`` config section + the transport's
``conn_wrapper`` hook, by scenario localnets — to shake out ordering
and partial-delivery assumptions."""

from __future__ import annotations

import random
import time
from typing import Iterable, Optional


class FuzzConnConfig:
    """config/config.go FuzzConnConfig defaults, plus MODE_PARTITION:
    a stall-by-peer-id-set mode for scripted network splits. The
    ``partition_ids`` set is read live on every operation, so mutating
    it (scenario engine over ``unsafe_net_shape``) re-partitions every
    existing connection without reconnects."""

    MODE_DROP = "drop"
    MODE_DELAY = "delay"
    MODE_PARTITION = "partition"

    def __init__(self, mode: str = MODE_DROP,
                 max_delay_s: float = 3.0,
                 prob_drop_rw: float = 0.2,
                 prob_drop_conn: float = 0.0,
                 prob_sleep: float = 0.0,
                 seed: Optional[int] = None,
                 partition_ids: Optional[Iterable[str]] = None):
        self.mode = mode
        self.max_delay_s = max_delay_s
        self.prob_drop_rw = prob_drop_rw
        self.prob_drop_conn = prob_drop_conn
        self.prob_sleep = prob_sleep
        self.rng = random.Random(seed)
        self.partition_ids = set(partition_ids or ())

    def set_partition(self, ids: Iterable[str]) -> None:
        """Replace the partitioned peer set (empty iterable = heal)."""
        self.partition_ids = set(ids)


class FuzzedConnection:
    """Duck-types the SecretConnection surface (write / read_exact /
    close) the MConnection drives. ``peer_id`` identifies the remote for
    MODE_PARTITION; connections wrapped without one never partition."""

    def __init__(self, conn, config: Optional[FuzzConnConfig] = None,
                 peer_id: str = ""):
        self.conn = conn
        self.config = config or FuzzConnConfig()
        self.peer_id = peer_id
        self._dead = False
        self._closed = False

    def _partitioned(self) -> bool:
        cfg = self.config
        return (cfg.mode == FuzzConnConfig.MODE_PARTITION
                and bool(self.peer_id)
                and self.peer_id in cfg.partition_ids)

    def _fuzz(self) -> bool:
        """Returns True if the operation should be swallowed."""
        cfg = self.config
        if self._dead:
            raise ConnectionError("fuzz: connection dropped")
        if cfg.mode == FuzzConnConfig.MODE_PARTITION:
            # stall, never swallow: returning success for a write the
            # peer will never see marks gossip as delivered in PeerState
            # and wedges catch-up after the heal (see p2p/shaping.py) —
            # real TCP backpressures, so the write blocks until heal,
            # close, or the stall deadline kills the conn
            if self._partitioned():
                from tmtpu.p2p import shaping as _shaping
                from tmtpu.libs import metrics as _m

                _m.p2p_shape_drops.inc(kind="partition")
                deadline = (time.monotonic()
                            + _shaping.PARTITION_STALL_MAX_S)
                while self._partitioned():
                    if self._closed or self._dead:
                        raise ConnectionError(
                            "fuzz: closed during partition")
                    if time.monotonic() > deadline:
                        raise ConnectionError(
                            "fuzz: partitioned write stalled out")
                    time.sleep(0.05)
            return False
        if cfg.mode == FuzzConnConfig.MODE_DELAY:
            if cfg.rng.random() < cfg.prob_sleep:
                time.sleep(cfg.rng.random() * cfg.max_delay_s)
            return False
        # drop mode
        if cfg.prob_drop_conn and cfg.rng.random() < cfg.prob_drop_conn:
            self._dead = True
            self.close()
            raise ConnectionError("fuzz: connection dropped")
        if cfg.rng.random() < cfg.prob_sleep:
            time.sleep(cfg.rng.random() * cfg.max_delay_s)
        return cfg.rng.random() < cfg.prob_drop_rw

    def write(self, data: bytes) -> int:
        if self._fuzz():
            return len(data)  # silently swallowed
        return self.conn.write(data)

    def read_exact(self, n: int) -> bytes:
        # reads can't be "dropped" without desyncing the stream; only
        # delay/kill apply (fuzz.go fuzzes reads by delaying)
        cfg = self.config
        if self._dead:
            raise ConnectionError("fuzz: connection dropped")
        if cfg.rng.random() < cfg.prob_sleep:
            time.sleep(cfg.rng.random() * cfg.max_delay_s)
        return self.conn.read_exact(n)

    def close(self) -> None:
        self._closed = True  # unblocks a write stalled in a partition
        try:
            self.conn.close()
        except OSError:
            pass

    def __getattr__(self, name):
        return getattr(self.conn, name)
