"""FuzzedConnection (reference: p2p/fuzz.go) — wraps a connection-like
object and probabilistically delays or drops reads/writes, driven by
FuzzConnConfig (config/config.go:663). Used by network fault-injection
tests to shake out ordering and partial-delivery assumptions."""

from __future__ import annotations

import random
import time
from typing import Optional


class FuzzConnConfig:
    """config/config.go FuzzConnConfig defaults."""

    MODE_DROP = "drop"
    MODE_DELAY = "delay"

    def __init__(self, mode: str = MODE_DROP,
                 max_delay_s: float = 3.0,
                 prob_drop_rw: float = 0.2,
                 prob_drop_conn: float = 0.0,
                 prob_sleep: float = 0.0,
                 seed: Optional[int] = None):
        self.mode = mode
        self.max_delay_s = max_delay_s
        self.prob_drop_rw = prob_drop_rw
        self.prob_drop_conn = prob_drop_conn
        self.prob_sleep = prob_sleep
        self.rng = random.Random(seed)


class FuzzedConnection:
    """Duck-types the SecretConnection surface (write / read_exact /
    close) the MConnection drives."""

    def __init__(self, conn, config: Optional[FuzzConnConfig] = None):
        self.conn = conn
        self.config = config or FuzzConnConfig()
        self._dead = False

    def _fuzz(self) -> bool:
        """Returns True if the operation should be swallowed."""
        cfg = self.config
        if self._dead:
            raise ConnectionError("fuzz: connection dropped")
        if cfg.mode == FuzzConnConfig.MODE_DELAY:
            if cfg.rng.random() < cfg.prob_sleep:
                time.sleep(cfg.rng.random() * cfg.max_delay_s)
            return False
        # drop mode
        if cfg.prob_drop_conn and cfg.rng.random() < cfg.prob_drop_conn:
            self._dead = True
            self.close()
            raise ConnectionError("fuzz: connection dropped")
        if cfg.rng.random() < cfg.prob_sleep:
            time.sleep(cfg.rng.random() * cfg.max_delay_s)
        return cfg.rng.random() < cfg.prob_drop_rw

    def write(self, data: bytes) -> int:
        if self._fuzz():
            return len(data)  # silently swallowed
        return self.conn.write(data)

    def read_exact(self, n: int) -> bytes:
        # reads can't be "dropped" without desyncing the stream; only
        # delay/kill apply (fuzz.go fuzzes reads by delaying)
        cfg = self.config
        if self._dead:
            raise ConnectionError("fuzz: connection dropped")
        if cfg.rng.random() < cfg.prob_sleep:
            time.sleep(cfg.rng.random() * cfg.max_delay_s)
        return self.conn.read_exact(n)

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass

    def __getattr__(self, name):
        return getattr(self.conn, name)
