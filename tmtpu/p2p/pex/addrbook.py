"""Address book (reference: p2p/pex/addrbook.go).

Known peer addresses split into NEW (heard about, never connected) and OLD
(connected successfully at least once) sets, hashed into buckets so one
gossiping peer can't flood the whole book (addrbook.go bucket design).
Persisted as JSON (addrbook.go saveToFile / file.go).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
# addrbook.go: max failed attempts before an address is dropped
MAX_ATTEMPTS = 5
GET_SELECTION_PCT = 23  # getSelection: % of book returned per PEX reply
MAX_GET_SELECTION = 250


class KnownAddress:
    __slots__ = ("addr", "src", "attempts", "last_attempt", "last_success",
                 "bucket_type")

    def __init__(self, addr: str, src: str):
        self.addr = addr          # "id@host:port"
        self.src = src            # node_id that told us
        self.attempts = 0
        self.last_attempt = 0.0
        self.last_success = 0.0
        self.bucket_type = "new"

    def to_json(self) -> dict:
        return {"addr": self.addr, "src": self.src,
                "attempts": self.attempts,
                "last_attempt": self.last_attempt,
                "last_success": self.last_success,
                "bucket_type": self.bucket_type}

    @classmethod
    def from_json(cls, d: dict) -> "KnownAddress":
        ka = cls(d["addr"], d.get("src", ""))
        ka.attempts = int(d.get("attempts", 0))
        ka.last_attempt = float(d.get("last_attempt", 0))
        ka.last_success = float(d.get("last_success", 0))
        ka.bucket_type = d.get("bucket_type", "new")
        return ka


def _addr_id(addr: str) -> str:
    return addr.split("@", 1)[0] if "@" in addr else ""


class AddrBook:
    def __init__(self, file_path: str = "", our_id: str = ""):
        self.file_path = file_path
        self.our_id = our_id
        self._lock = threading.Lock()
        self._by_id: Dict[str, KnownAddress] = {}
        # bucket index: (type, bucket) -> list of ids, to cap per-source
        # flooding the way addrbook.go's hashed buckets do
        self._buckets: Dict[Tuple[str, int], List[str]] = {}
        self._key = os.urandom(8).hex()  # addrbook.go:  randomized hashing
        if file_path and os.path.exists(file_path):
            self._load()

    # -- bucket hashing (addrbook.go calcNewBucket/calcOldBucket) ----------

    def _bucket_of(self, ka: KnownAddress) -> Tuple[str, int]:
        n = NEW_BUCKET_COUNT if ka.bucket_type == "new" else OLD_BUCKET_COUNT
        h = hashlib.sha256(
            (self._key + ka.src + ka.addr).encode()).digest()
        return (ka.bucket_type, int.from_bytes(h[:4], "big") % n)

    # -- mutation -----------------------------------------------------------

    def add_address(self, addr: str, src: str = "") -> bool:
        """addrbook.go:262 AddAddress. Returns True if stored."""
        pid = _addr_id(addr)
        if not pid or pid == self.our_id:
            return False
        with self._lock:
            ka = self._by_id.get(pid)
            if ka is not None:
                # vetted (old-bucket) entries are never overwritten by
                # gossip; a NEW entry refreshes its address if it moved
                if ka.bucket_type == "new" and ka.addr != addr:
                    b = self._bucket_of(ka)
                    if pid in self._buckets.get(b, []):
                        self._buckets[b].remove(pid)
                    ka.addr = addr
                    ka.src = src
                    self._buckets.setdefault(self._bucket_of(ka),
                                             []).append(pid)
                return False
            ka = KnownAddress(addr, src)
            bucket = self._bucket_of(ka)
            ids = self._buckets.setdefault(bucket, [])
            if len(ids) >= BUCKET_SIZE:
                # evict the stalest new-bucket entry (addrbook.go
                # expireNew picks the worst)
                worst = min(ids, key=lambda i: self._by_id[i].last_success)
                ids.remove(worst)
                del self._by_id[worst]
            ids.append(pid)
            self._by_id[pid] = ka
            return True

    def mark_attempt(self, addr: str) -> None:
        with self._lock:
            ka = self._by_id.get(_addr_id(addr))
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_good(self, addr: str) -> None:
        """addrbook.go MarkGood — promote to the old bucket."""
        with self._lock:
            ka = self._by_id.get(_addr_id(addr))
            if ka:
                ka.attempts = 0
                ka.last_success = time.time()
                if ka.bucket_type == "new":
                    self._rebucket(ka, "old")

    def mark_bad(self, addr: str) -> None:
        self.remove_address(addr)

    def remove_address(self, addr: str) -> None:
        with self._lock:
            pid = _addr_id(addr)
            ka = self._by_id.pop(pid, None)
            if ka:
                b = self._bucket_of(ka)
                if pid in self._buckets.get(b, []):
                    self._buckets[b].remove(pid)

    def _rebucket(self, ka: KnownAddress, new_type: str) -> None:
        pid = _addr_id(ka.addr)
        old_b = self._bucket_of(ka)
        if pid in self._buckets.get(old_b, []):
            self._buckets[old_b].remove(pid)
        ka.bucket_type = new_type
        dest = self._bucket_of(ka)
        ids = self._buckets.setdefault(dest, [])
        if new_type == "old" and len(ids) >= BUCKET_SIZE:
            # full old bucket: demote the stalest vetted entry back to
            # new rather than growing without bound (addrbook.go
            # moveToOld pushes one back into a new bucket)
            stalest = min(ids, key=lambda i: self._by_id[i].last_success)
            ids.remove(stalest)
            demoted = self._by_id[stalest]
            demoted.bucket_type = "new"
            nids = self._buckets.setdefault(self._bucket_of(demoted), [])
            if len(nids) >= BUCKET_SIZE:  # cascade: evict, don't overflow
                worst = min(nids, key=lambda i: self._by_id[i].last_success)
                nids.remove(worst)
                del self._by_id[worst]
            nids.append(stalest)
        ids.append(pid)

    # -- selection ----------------------------------------------------------

    def pick_address(self, new_bias_pct: int = 30,
                     exclude: Optional[set] = None) -> Optional[str]:
        """addrbook.go:303 PickAddress — biased pick between new/old."""
        with self._lock:
            exclude = exclude or set()
            news = [k for k in self._by_id.values()
                    if k.bucket_type == "new"
                    and _addr_id(k.addr) not in exclude
                    and k.attempts < MAX_ATTEMPTS]
            olds = [k for k in self._by_id.values()
                    if k.bucket_type == "old"
                    and _addr_id(k.addr) not in exclude
                    and k.attempts < MAX_ATTEMPTS]
            pools = []
            if news:
                pools.append((new_bias_pct, news))
            if olds:
                pools.append((100 - new_bias_pct, olds))
            if not pools:
                return None
            total = sum(w for w, _ in pools)
            r = random.uniform(0, total)
            for w, pool in pools:
                if r <= w:
                    return random.choice(pool).addr
                r -= w
            return random.choice(pools[-1][1]).addr

    def get_selection(self) -> List[str]:
        """addrbook.go:386 GetSelection — random subset for a PEX reply."""
        with self._lock:
            addrs = [k.addr for k in self._by_id.values()]
        random.shuffle(addrs)
        n = max(min(len(addrs) * GET_SELECTION_PCT // 100,
                    MAX_GET_SELECTION), min(len(addrs), 32))
        return addrs[:n]

    def has_address(self, addr: str) -> bool:
        with self._lock:
            return _addr_id(addr) in self._by_id

    def is_good(self, addr: str) -> bool:
        with self._lock:
            ka = self._by_id.get(_addr_id(addr))
            return bool(ka and ka.bucket_type == "old")

    def size(self) -> int:
        with self._lock:
            return len(self._by_id)

    def need_more_addrs(self) -> bool:
        return self.size() < 1000  # addrbook.go needAddressThreshold

    def empty(self) -> bool:
        return self.size() == 0

    # -- persistence --------------------------------------------------------

    def save(self) -> None:
        if not self.file_path:
            return
        import tempfile

        with self._lock:
            data = {"key": self._key,
                    "addrs": [k.to_json() for k in self._by_id.values()]}
        d = os.path.dirname(self.file_path) or "."
        os.makedirs(d, exist_ok=True)
        # unique temp name: concurrent saves (ensure loop vs on_stop) must
        # not race each other's rename
        fd, tmp = tempfile.mkstemp(dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, self.file_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def _load(self) -> None:
        try:
            with open(self.file_path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        self._key = data.get("key", self._key)
        for d in data.get("addrs", []):
            ka = KnownAddress.from_json(d)
            pid = _addr_id(ka.addr)
            if pid and pid != self.our_id:
                self._by_id[pid] = ka
                self._buckets.setdefault(self._bucket_of(ka),
                                         []).append(pid)
