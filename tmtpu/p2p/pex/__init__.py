"""Peer exchange (reference: p2p/pex/)."""

from tmtpu.p2p.pex.addrbook import AddrBook  # noqa: F401
from tmtpu.p2p.pex.reactor import PEX_CHANNEL, PexReactor  # noqa: F401
