"""PEX reactor (reference: p2p/pex/pex_reactor.go).

Channel 0x00. New peers are asked for addresses (rate-limited); requests
are answered with a random book selection; learned addresses feed the
addrbook; an ensure-peers loop dials book addresses until the outbound
target is met. Seed mode answers one exchange then hangs up
(pex_reactor.go seed crawl behavior, simplified: no dedicated crawler).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from tmtpu.libs.protoio import ProtoMessage
from tmtpu.p2p.conn.connection import ChannelDescriptor
from tmtpu.p2p.pex.addrbook import AddrBook
from tmtpu.p2p.switch import Peer, Reactor

PEX_CHANNEL = 0x00

_ENSURE_PERIOD_S = 1.0   # pex_reactor.go defaultEnsurePeersPeriod is 30s;
# shortened: Python nets in tests need to converge fast
_REQUEST_INTERVAL_S = 10.0  # per-peer request rate limit
_SAVE_PERIOD_S = 30.0


class NetAddressPB(ProtoMessage):
    """proto/tendermint/p2p/pex.proto NetAddress."""

    FIELDS = [(1, "id", "string"), (2, "ip", "string"), (3, "port", "uint32")]


class PexRequestPB(ProtoMessage):
    FIELDS = []


class PexAddrsPB(ProtoMessage):
    FIELDS = [(1, "addrs", ("rep", ("msg!", NetAddressPB)))]


class PexMessagePB(ProtoMessage):
    """oneof sum: pex_request=1 | pex_addrs=2."""

    FIELDS = [
        (1, "pex_request", ("msg", PexRequestPB)),
        (2, "pex_addrs", ("msg", PexAddrsPB)),
    ]


def _to_net_addr(addr: str) -> Optional[NetAddressPB]:
    if "@" not in addr:
        return None
    pid, hp = addr.split("@", 1)
    host, _, port = hp.rpartition(":")
    try:
        return NetAddressPB(id=pid, ip=host, port=int(port))
    except ValueError:
        return None


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, seed_mode: bool = False,
                 seeds: Optional[list] = None):
        super().__init__("PEX")
        self.book = book
        self.seed_mode = seed_mode
        self.seeds = [s for s in (seeds or []) if s]
        self._last_requested: Dict[str, float] = {}  # rate-limit our requests
        self._pending_reply: set = set()   # peers we await one reply from
        self._asked_us: Dict[str, float] = {}    # rate-limit inbound requests
        self._stopped = threading.Event()
        self._threads = []

    def get_channels(self):
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10)]

    def on_start(self) -> None:
        t = threading.Thread(target=self._ensure_peers_routine, daemon=True,
                             name="pex-ensure")
        t.start()
        self._threads.append(t)

    def on_stop(self) -> None:
        self._stopped.set()
        self.book.save()

    # -- peer events --------------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        # learn the dialable address of outbound peers (pex_reactor.go:174:
        # inbound peers' listen ports are unverified, only ask them)
        addr = self._peer_addr(peer)
        if peer.outbound:
            if addr:
                self.book.mark_good(addr)
        else:
            if addr:
                self.book.add_address(addr, src=peer.node_id)
        self._maybe_request(peer)

    def remove_peer(self, peer: Peer, reason) -> None:
        self._last_requested.pop(peer.node_id, None)
        self._pending_reply.discard(peer.node_id)
        self._asked_us.pop(peer.node_id, None)

    def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        m = PexMessagePB.decode(msg_bytes)
        if m.pex_request is not None:
            now = time.time()
            last = self._asked_us.get(peer.node_id, 0)
            if now - last < _REQUEST_INTERVAL_S / 2:
                if self.switch:  # flooding us with requests
                    self.switch.stop_peer_for_error(
                        peer, ValueError("pex request flood"))
                return
            self._asked_us[peer.node_id] = now
            addrs = []
            for a in self.book.get_selection():
                na = _to_net_addr(a)
                if na is not None and na.id != peer.node_id:
                    addrs.append(na)
            peer.send(PEX_CHANNEL,
                      PexMessagePB(pex_addrs=PexAddrsPB(addrs=addrs)).encode())
            if self.seed_mode and self.switch:
                # seeds serve addresses then free the slot
                # (pex_reactor.go:478 attemptDisconnects)
                threading.Timer(
                    0.5, lambda: self.switch.stop_peer_for_error(
                        peer, "seed exchange complete")).start()
        elif m.pex_addrs is not None:
            # one reply per request (pex_reactor.go:307 ReceiveAddrs deletes
            # the request marker first — repeats are unsolicited)
            if peer.node_id not in self._pending_reply:
                if self.switch:
                    self.switch.stop_peer_for_error(
                        peer, ValueError("unsolicited pex addrs"))
                return
            self._pending_reply.discard(peer.node_id)
            from tmtpu.p2p.pex.addrbook import MAX_GET_SELECTION

            for na in m.pex_addrs.addrs[:MAX_GET_SELECTION]:
                if na.id and na.ip and na.port:
                    self.book.add_address(f"{na.id}@{na.ip}:{na.port}",
                                          src=peer.node_id)

    # -- internals ----------------------------------------------------------

    def _peer_addr(self, peer: Peer) -> Optional[str]:
        la = peer.node_info.listen_addr
        if not la:
            return None
        hp = la.rsplit("/", 1)[-1]
        host, _, port = hp.rpartition(":")
        if host in ("0.0.0.0", "::", ""):
            host = peer.remote_ip
        return f"{peer.node_id}@{host}:{port}"

    def _maybe_request(self, peer: Peer) -> None:
        if not peer.has_channel(PEX_CHANNEL):
            return
        now = time.time()
        if now - self._last_requested.get(peer.node_id, 0) \
                < _REQUEST_INTERVAL_S:
            return
        self._last_requested[peer.node_id] = now
        self._pending_reply.add(peer.node_id)
        peer.send(PEX_CHANNEL,
                  PexMessagePB(pex_request=PexRequestPB()).encode())

    def _ensure_peers_routine(self) -> None:
        """pex_reactor.go:388 ensurePeers — keep outbound slots filled from
        the book; fall back to seeds when the book is dry."""
        last_save = time.time()
        while not self._stopped.is_set():
            time.sleep(_ENSURE_PERIOD_S)
            sw = self.switch
            if sw is None or not sw.is_running():
                continue
            peers = sw.peers_list()
            out = sum(1 for p in peers if p.outbound)
            need = sw.max_outbound - out
            connected = {p.node_id for p in peers} | {sw.node_id}
            if need > 0:
                dialed = 0
                tried = set()
                while dialed < need:
                    addr = self.book.pick_address(exclude=connected | tried)
                    if addr is None:
                        break
                    tried.add(addr.split("@", 1)[0])
                    self.book.mark_attempt(addr)
                    try:
                        if sw.dial_peer(addr) is not None:
                            self.book.mark_good(addr)
                            dialed += 1
                    except Exception:  # noqa: BLE001
                        pass
                if dialed == 0 and self.book.empty() and self.seeds:
                    self._dial_seeds(sw)
            # ask a connected peer for more when the book is thin
            if self.book.need_more_addrs() and peers:
                import random as _r

                self._maybe_request(_r.choice(peers))
            if time.time() - last_save > _SAVE_PERIOD_S:
                try:
                    self.book.save()
                except OSError:
                    pass  # disk hiccups must not kill the ensure loop
                last_save = time.time()

    def _dial_seeds(self, sw) -> None:
        import random as _r

        seeds = list(self.seeds)
        _r.shuffle(seeds)
        for s in seeds:
            try:
                if sw.dial_peer(s) is not None:
                    return
            except Exception:  # noqa: BLE001
                continue
