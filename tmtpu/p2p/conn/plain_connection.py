"""Authenticated-plaintext peer connection — DEV/CI FALLBACK ONLY.

SecretConnection (the real wire protocol: X25519 ECDH + ChaCha20Poly1305
frames, p2p/conn/secret_connection.go) hard-requires the `cryptography`
package and has no pure-python equivalent fast enough for a live net.
On boxes without that package the whole p2p stack — and every tool that
builds a localnet — becomes unimportable, so transport.py falls back to
this class: the same handshake SHAPE (exchange identities, prove key
ownership by signing the peer's challenge) over an UNENCRYPTED stream.

Ed25519 signing/verification rides tmtpu's pure-python reference
implementation, so this path needs nothing beyond the stdlib.

Security properties: peers are mutually AUTHENTICATED (a peer must hold
the private key for the node id it claims — transport.py's wire-identity
check still works), but traffic is neither encrypted nor MITM-bound (no
DH, so the challenge signatures do not pin the channel). Never use it
across a real network; it exists so single-host localnets and CI smoke
runs work where the AEAD stack is absent. The fallback is selected only
by ImportError — an environment with `cryptography` installed can never
silently downgrade.

Duck-types the SecretConnection surface the Transport/MConnection drive:
``write`` / ``read`` / ``read_exact`` / ``close`` / ``remote_pub_key``.
"""

from __future__ import annotations

import os
import threading

from tmtpu.crypto.keys import KEY_TYPES

_MAGIC = b"TMPLAIN1"  # never a valid SecretConnection ephemeral-key frame
_CHALLENGE_SIZE = 32
_SIG_SIZE = 64
_AUTH_CONTEXT = b"TMTPU-PLAIN-AUTH:"


class PlainConnectionError(Exception):
    pass


class PlainAuthConnection:
    def __init__(self, sock, local_priv_key):
        """Performs the full handshake on construction (blocking socket)."""
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()

        local_pub = local_priv_key.pub_key().bytes()
        challenge = os.urandom(_CHALLENGE_SIZE)
        self._sock.sendall(_MAGIC + local_pub + challenge)
        hello = self._read_exact_raw(
            len(_MAGIC) + len(local_pub) + _CHALLENGE_SIZE)
        if not hello.startswith(_MAGIC):
            raise PlainConnectionError(
                "peer is not speaking the plaintext fallback protocol "
                "(mixed-stack net? the real SecretConnection cannot "
                "interoperate with this dev fallback)")
        remote_pub = hello[len(_MAGIC):len(_MAGIC) + 32]
        remote_challenge = hello[len(_MAGIC) + 32:]
        if remote_pub == local_pub:
            raise PlainConnectionError("identity key reflected")

        # prove ownership of the claimed identity: sign the challenge the
        # PEER issued, verify the peer's signature over ours
        self._sock.sendall(
            local_priv_key.sign(_AUTH_CONTEXT + remote_challenge))
        remote_sig = self._read_exact_raw(_SIG_SIZE)
        entry = KEY_TYPES["ed25519"]
        self.remote_pub_key = entry[0](remote_pub)
        if not self.remote_pub_key.verify_signature(
                _AUTH_CONTEXT + challenge, remote_sig):
            raise PlainConnectionError("challenge verification failed")

    def _read_exact_raw(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise PlainConnectionError("connection closed")
            out += chunk
        return out

    def write(self, data: bytes) -> int:
        with self._send_lock:
            self._sock.sendall(data)
        return len(data)

    def read(self, n: int = 65536) -> bytes:
        with self._recv_lock:
            chunk = self._sock.recv(n)
        if not chunk:
            raise PlainConnectionError("connection closed")
        return chunk

    def read_exact(self, n: int) -> bytes:
        with self._recv_lock:
            out = b""
            while len(out) < n:
                chunk = self._sock.recv(n - len(out))
                if not chunk:
                    raise PlainConnectionError("connection closed")
                out += chunk
            return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
