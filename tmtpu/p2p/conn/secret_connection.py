"""SecretConnection (reference: p2p/conn/secret_connection.go:63) —
Station-to-Station authenticated encryption for peer links:

1. exchange ephemeral X25519 pubkeys (:289-335);
2. HKDF-SHA256 over the DH secret → two ChaCha20-Poly1305 keys
   (:337 deriveSecrets); the CHALLENGE comes from a merlin transcript
   over the sorted ephemeral keys + DH secret (:111-135), binding the
   authentication to the key ordering;
3. sign the challenge with the node's ed25519 key and exchange
   AuthSigMessages over the now-encrypted link (MakeSecretConnection :92).

Frames: 1024-byte data chunks, sealed to 1028+16 bytes with a 12-byte
little-endian counter nonce per direction (:44-57).
"""

from __future__ import annotations

import struct
import threading
from typing import Optional

try:  # X25519 + ChaCha20-Poly1305 have no pure-Python fallback here
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - depends on environment
    HAVE_CRYPTO = False

from tmtpu.crypto.keys import KEY_TYPES
from tmtpu.libs.protoio import ProtoMessage, encode_uvarint, decode_uvarint
from tmtpu.types import pb

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_OVERHEAD = 16


class AuthSigMessage(ProtoMessage):
    """proto/tendermint/p2p/conn.proto AuthSigMessage."""

    FIELDS = [(1, "pub_key", ("msg!", pb.PublicKey)), (2, "sig", "bytes")]


class SecretConnectionError(Exception):
    pass


class SecretConnection:
    def __init__(self, sock, local_priv_key):
        """Performs the full handshake on construction (blocking socket)."""
        if not HAVE_CRYPTO:
            raise SecretConnectionError(
                "SecretConnection requires the `cryptography` package "
                "(X25519/ChaCha20-Poly1305); use a plaintext transport")
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._recv_buf = b""
        self._send_nonce = 0
        self._recv_nonce = 0

        # 1. ephemeral key exchange
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        self._sock.sendall(encode_uvarint(32) + eph_pub)
        remote_eph = self._read_exact_raw(33)
        n, pos = decode_uvarint(remote_eph, 0)
        if n != 32:
            raise SecretConnectionError("bad ephemeral key frame")
        remote_eph_pub = remote_eph[pos:pos + 32]
        if remote_eph_pub == eph_pub:
            raise SecretConnectionError("ephemeral key reflected")

        # 2. derive secrets; key assignment depends on sort order
        # (secret_connection.go:111-135): the CHALLENGE comes from a merlin
        # transcript over the sorted ephemeral keys + DH secret — binding
        # the authentication to the key ordering — while the two AEAD keys
        # come from HKDF over the DH secret (deriveSecrets :337).
        from tmtpu.crypto.merlin import Transcript

        lo, hi = sorted((eph_pub, remote_eph_pub))
        transcript = Transcript(
            b"TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH")
        transcript.append_message(b"EPHEMERAL_LOWER_PUBLIC_KEY", lo)
        transcript.append_message(b"EPHEMERAL_UPPER_PUBLIC_KEY", hi)
        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(
            remote_eph_pub))
        transcript.append_message(b"DH_SECRET", shared)
        okm = HKDF(algorithm=hashes.SHA256(), length=64, salt=None,
                   info=b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"
                   ).derive(shared)
        loc_is_least = eph_pub < remote_eph_pub
        if loc_is_least:
            recv_key, send_key = okm[:32], okm[32:64]
        else:
            send_key, recv_key = okm[:32], okm[32:64]
        self._challenge = transcript.challenge_bytes(
            b"SECRET_CONNECTION_MAC", 32)
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)

        # 3. authenticate: sign the challenge, swap AuthSigMessages over the
        # encrypted channel
        sig = local_priv_key.sign(self._challenge)
        auth = AuthSigMessage(
            pub_key=pb.PublicKey(ed25519=local_priv_key.pub_key().bytes()),
            sig=sig,
        ).encode()
        self.write(encode_uvarint(len(auth)) + auth)
        buf = b""
        while True:
            buf += self.read_exact(1)
            try:
                n, pos = decode_uvarint(buf, 0)
                break
            except EOFError:
                continue
        remote_auth_raw = self.read_exact(n)
        remote_auth = AuthSigMessage.decode(remote_auth_raw)
        if not remote_auth.pub_key.ed25519:
            raise SecretConnectionError("peer sent non-ed25519 identity")
        entry = KEY_TYPES["ed25519"]
        self.remote_pub_key = entry[0](bytes(remote_auth.pub_key.ed25519))
        if not self.remote_pub_key.verify_signature(self._challenge,
                                                    bytes(remote_auth.sig)):
            raise SecretConnectionError("challenge verification failed")

    # -- raw socket helpers (pre-encryption) --------------------------------

    def _read_exact_raw(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise SecretConnectionError("connection closed in handshake")
            out += chunk
        return out

    # -- encrypted frames ---------------------------------------------------

    def _nonce(self, counter: int) -> bytes:
        return b"\x00\x00\x00\x00" + struct.pack("<Q", counter)

    def write(self, data: bytes) -> int:
        """Encrypt+send in 1024-byte frames; returns bytes consumed."""
        total = len(data)
        with self._send_lock:
            while data:
                chunk, data = data[:DATA_MAX_SIZE], data[DATA_MAX_SIZE:]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                sealed = self._send_aead.encrypt(
                    self._nonce(self._send_nonce), frame, None)
                self._send_nonce += 1
                self._sock.sendall(sealed)
        return total

    def read(self, n: int = 65536) -> bytes:
        """Read up to n decrypted bytes (at least one frame)."""
        with self._recv_lock:
            if not self._recv_buf:
                sealed = self._read_exact_raw(TOTAL_FRAME_SIZE + AEAD_OVERHEAD)
                frame = self._recv_aead.decrypt(
                    self._nonce(self._recv_nonce), sealed, None)
                self._recv_nonce += 1
                (ln,) = struct.unpack_from("<I", frame, 0)
                if ln > DATA_MAX_SIZE:
                    raise SecretConnectionError("invalid frame length")
                self._recv_buf = frame[DATA_LEN_SIZE:DATA_LEN_SIZE + ln]
            out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
            return out

    def read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += self.read(n - len(out))
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
