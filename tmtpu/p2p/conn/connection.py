"""MConnection (reference: p2p/conn/connection.go:78) — multiplexes N
prioritized channels over one (secret) stream.

Wire format: uvarint-length-delimited Packet protos —
PacketPing / PacketPong / PacketMsg{channel_id, eof, data} (the reference's
proto/tendermint/p2p/conn.proto). Messages are chunked into
``max_packet_msg_payload_size`` packets with an EOF marker.

One send thread drains per-channel queues by priority; one recv thread
reassembles packets and hands complete messages to the owner's
``on_receive(channel_id, msg_bytes)``.

Flow control (connection.go flowrate/sendRate/recvRate): both directions
are token-bucket limited so a slow or malicious peer can't monopolize the
node's bandwidth; missing pongs within ``PONG_TIMEOUT`` disconnect the
peer (connection.go pongTimeoutCh).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from tmtpu.libs.protoio import ProtoMessage, encode_uvarint, decode_uvarint
from tmtpu.types import pb


class PacketPing(ProtoMessage):
    FIELDS: list = []


class PacketPong(ProtoMessage):
    FIELDS: list = []


class PacketMsg(ProtoMessage):
    FIELDS = [(1, "channel_id", "int32"), (2, "eof", "bool"),
              (3, "data", "bytes")]


class Packet(ProtoMessage):
    FIELDS = [
        (1, "ping", ("msg", PacketPing)),
        (2, "pong", ("msg", PacketPong)),
        (3, "msg", ("msg", PacketMsg)),
    ]


class ChannelDescriptor:
    def __init__(self, channel_id: int, priority: int = 1,
                 send_queue_capacity: int = 100,
                 recv_message_capacity: int = 22 * 1024 * 1024):
        self.channel_id = channel_id
        self.priority = priority
        self.send_queue_capacity = send_queue_capacity
        self.recv_message_capacity = recv_message_capacity


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.send_queue: "queue.Queue[bytes]" = queue.Queue(
            maxsize=desc.send_queue_capacity)
        self.sending = b""
        self.recv_buf = b""
        self.recently_sent = 0


class _RateLimiter:
    """Token bucket (the reference's flowrate.Monitor Limit())."""

    def __init__(self, rate_bytes_per_s: int):
        self.rate = rate_bytes_per_s
        self._tokens = float(rate_bytes_per_s)  # 1s of burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def consume(self, n: int) -> None:
        """Block until ``n`` bytes of budget are available. Amounts larger
        than the bucket (1s of rate) are consumed in capacity-sized chunks
        — a single oversized request must never exceed what the bucket can
        ever hold, or it would spin forever."""
        if self.rate <= 0:
            return
        while n > 0:
            chunk = min(n, self.rate)
            while True:
                with self._lock:
                    now = time.monotonic()
                    self._tokens = min(
                        float(self.rate),
                        self._tokens + (now - self._last) * self.rate)
                    self._last = now
                    if self._tokens >= chunk:
                        self._tokens -= chunk
                        break
                    wait = (chunk - self._tokens) / self.rate
                time.sleep(min(wait, 0.1))
            n -= chunk


class MConnection:
    PING_INTERVAL = 30.0
    PONG_TIMEOUT = 45.0   # connection.go defaultPongTimeout (we allow 1.5x)
    FLUSH_INTERVAL = 0.01

    def __init__(self, conn, channel_descs: List[ChannelDescriptor],
                 on_receive: Callable[[int, bytes], None],
                 on_error: Callable[[Exception], None],
                 max_packet_payload: int = 1024,
                 send_rate: int = 5_120_000, recv_rate: int = 5_120_000):
        self._conn = conn  # SecretConnection or raw socket-like
        self._channels: Dict[int, _Channel] = {
            d.channel_id: _Channel(d) for d in channel_descs
        }
        self._on_receive = on_receive
        self._on_error = on_error
        self._max_payload = max_packet_payload
        self._send_limiter = _RateLimiter(send_rate)
        self._recv_limiter = _RateLimiter(recv_rate)
        self._send_event = threading.Event()
        self._pong_pending = False
        self._ping_sent_at = 0.0    # nonzero while awaiting a pong
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        for fn, name in ((self._send_routine, "mconn-send"),
                         (self._recv_routine, "mconn-recv")):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._send_event.set()
        if hasattr(self._conn, "close"):
            self._conn.close()

    def is_running(self) -> bool:
        return not self._stopped.is_set()

    # -- sending ------------------------------------------------------------

    def send(self, channel_id: int, msg: bytes, timeout: float = 10.0) -> bool:
        """Queue a complete message on a channel (connection.go Send)."""
        ch = self._channels.get(channel_id)
        if ch is None or self._stopped.is_set():
            return False
        try:
            ch.send_queue.put(bytes(msg), timeout=timeout)
        except queue.Full:
            return False
        self._send_event.set()
        return True

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        ch = self._channels.get(channel_id)
        if ch is None or self._stopped.is_set():
            return False
        try:
            ch.send_queue.put_nowait(bytes(msg))
        except queue.Full:
            return False
        self._send_event.set()
        return True

    def _write_packet(self, p: Packet) -> None:
        data = p.encode()
        self._conn.write(encode_uvarint(len(data)) + data)

    def _send_routine(self) -> None:
        last_ping = time.monotonic()
        try:
            while not self._stopped.is_set():
                fired = self._send_event.wait(timeout=0.05)
                self._send_event.clear()
                if self._stopped.is_set():
                    return
                if self._pong_pending:
                    self._write_packet(Packet(pong=PacketPong()))
                    self._pong_pending = False
                now = time.monotonic()
                if now - last_ping > self.PING_INTERVAL:
                    self._write_packet(Packet(ping=PacketPing()))
                    last_ping = now
                    if not self._ping_sent_at:
                        self._ping_sent_at = now
                if self._ping_sent_at and \
                        now - self._ping_sent_at > self.PONG_TIMEOUT:
                    raise ConnectionError(
                        "pong timeout: peer unresponsive")
                # drain by priority — bounded per pass so ping/pong (and
                # the pong deadline) stay serviced while queues are busy;
                # the rate limiter can make each packet block, so an
                # unbounded drain would starve keepalives entirely
                for _ in range(256):
                    if not self._send_some():
                        break
                else:
                    self._send_event.set()  # more to drain next pass
        except Exception as e:  # noqa: BLE001
            if not self._stopped.is_set():
                self._on_error(e)
                self.stop()

    def _send_some(self) -> bool:
        """Send one packet from the least-recently-served highest-priority
        channel with pending data (connection.go sendSomePacketMsgs)."""
        best: Optional[_Channel] = None
        best_ratio = None
        for ch in self._channels.values():
            if not ch.sending and not ch.send_queue.empty():
                try:
                    ch.sending = ch.send_queue.get_nowait()
                except queue.Empty:
                    pass
            if ch.sending:
                ratio = ch.recently_sent / max(1, ch.desc.priority)
                if best_ratio is None or ratio < best_ratio:
                    best, best_ratio = ch, ratio
        if best is None:
            return False
        chunk = best.sending[:self._max_payload]
        rest = best.sending[self._max_payload:]
        eof = not rest
        self._send_limiter.consume(len(chunk))
        self._write_packet(Packet(msg=PacketMsg(
            channel_id=best.desc.channel_id, eof=eof, data=chunk)))
        best.sending = rest
        best.recently_sent += len(chunk)
        # decay so long-lived connections keep rotating fairly
        if best.recently_sent > 10 * 1024 * 1024:
            for ch in self._channels.values():
                ch.recently_sent //= 2
        return True

    # -- receiving ----------------------------------------------------------

    def _read_uvarint(self) -> int:
        buf = b""
        while True:
            b = self._conn.read_exact(1) if hasattr(self._conn, "read_exact") \
                else self._conn.recv(1)
            if not b:
                raise ConnectionError("eof")
            buf += b
            try:
                n, _ = decode_uvarint(buf, 0)
                return n
            except EOFError:
                continue

    def _read_exact(self, n: int) -> bytes:
        if hasattr(self._conn, "read_exact"):
            return self._conn.read_exact(n)
        out = b""
        while len(out) < n:
            chunk = self._conn.recv(n - len(out))
            if not chunk:
                raise ConnectionError("eof")
            out += chunk
        return out

    def _recv_routine(self) -> None:
        try:
            while not self._stopped.is_set():
                n = self._read_uvarint()
                if n > 30 * 1024 * 1024:
                    raise ConnectionError(f"packet too big: {n}")
                self._recv_limiter.consume(n)
                pkt = Packet.decode(self._read_exact(n))
                if pkt.ping is not None:
                    self._pong_pending = True
                    self._send_event.set()
                elif pkt.pong is not None:
                    self._ping_sent_at = 0.0
                elif pkt.msg is not None:
                    ch = self._channels.get(pkt.msg.channel_id)
                    if ch is None:
                        raise ConnectionError(
                            f"unknown channel {pkt.msg.channel_id}")
                    ch.recv_buf += bytes(pkt.msg.data)
                    if len(ch.recv_buf) > ch.desc.recv_message_capacity:
                        raise ConnectionError("recv message too big")
                    if pkt.msg.eof:
                        msg, ch.recv_buf = ch.recv_buf, b""
                        self._on_receive(ch.desc.channel_id, msg)
        except Exception as e:  # noqa: BLE001
            if not self._stopped.is_set():
                self._on_error(e)
                self.stop()
