"""Per-link WAN emulation for localnet peer connections.

Real deployments put validators behind oceans: 100-300 ms of latency,
jitter, constrained bandwidth, and a few percent loss. The localnet
benches so far ran on loopback, which hides every timeout/gossip
interaction the consensus timeouts exist for. This module shapes each
DIRECTED peer link at the transport layer — the shim wraps the
SecretConnection right after the handshake identifies the peer, so
everything above (MConnection, reactors) is untouched.

Three pieces:

- :class:`LinkSpec` — the per-link shape: latency_ms, jitter_ms,
  bw_kbps, drop probability. Parsed from the ``[p2p] shape_links``
  string (``"<peer_id_or_*>:latency_ms=200,jitter_ms=20,bw_kbps=1024,
  drop=0.05;..."``).
- :class:`LinkShaper` — per-node policy table (peer id -> LinkSpec,
  ``*`` default) plus a mutable partition set. ``wrap(conn, peer_id)``
  is installed as the transport's ``conn_wrapper``. Policies and the
  partition set are read live by every wrapped connection, so the
  scenario engine re-shapes a running node over ``unsafe_net_shape``
  without reconnects.
- :class:`ShapedConnection` — the conn wrapper. Egress-side only: each
  node shapes what IT sends, so a directed link A->B is configured on A.
  Partition = stalled writes (TCP-backpressure emulation, see below);
  drop = seeded per-write retransmission penalty; latency+jitter =
  deferred delivery through a per-connection drain thread (packets stay
  pipelined in flight, as on a real WAN — sleeping in the sender thread
  would cap the link at one packet per RTT, which is a satellite modem,
  not a WAN); bandwidth = token bucket feeding the same queue.

Partition semantics matter: a real network split does NOT silently eat
bytes on a live TCP stream — the kernel retransmits, the sender's
window fills, writes BLOCK, and everything queued delivers after the
heal (or the connection dies trying). Swallowing writes while
returning success is a behavior no real network exhibits, and it
poisons gossip: reactors mark messages as delivered in PeerState and
never resend, so a short partition leaves a peer wedged forever
(observed: a validator split at height 1 never caught up — the
majority believed it already had block 1's parts). So partitioned
writes STALL until heal, close, or ``PARTITION_STALL_MAX_S`` — the
MConnection send queues back up, try_send starts failing honestly,
and the catch-up state stays truthful.

The same reasoning shapes ``drop``: the emulation rides a reliable
localhost TCP stream, where a lost segment surfaces to the application
as a retransmission delay spike, never as missing bytes. So a sampled
"drop" charges an RTO-style penalty (~3x the one-way latency, floored
at 200 ms) and then delivers the write anyway.

Shaping is deterministic per (seed, peer_id): every link derives its
RNG from the node seed and the peer id, so two runs with the same seed
drop the same writes.
"""

from __future__ import annotations

import collections
import random
import threading
import time
import zlib
from typing import Dict, Iterable, Optional

from tmtpu.libs import metrics as _m

# How long a partitioned write stalls before the connection is declared
# dead (OSError). Mirrors real TCP: retransmission backoff holds a
# one-sided conversation alive for a while, then the connection drops
# and the switch's redial loop takes over. Kept just above the
# MConnection PONG_TIMEOUT so ping liveness usually kills the conn
# first, the way it would on hardware.
PARTITION_STALL_MAX_S = 60.0
_PARTITION_POLL_S = 0.05


class LinkSpec:
    """Shape of one directed link. All fields optional; zero = off."""

    __slots__ = ("latency_ms", "jitter_ms", "bw_kbps", "drop")

    def __init__(self, latency_ms: float = 0.0, jitter_ms: float = 0.0,
                 bw_kbps: float = 0.0, drop: float = 0.0):
        self.latency_ms = float(latency_ms)
        self.jitter_ms = float(jitter_ms)
        self.bw_kbps = float(bw_kbps)
        self.drop = float(drop)

    def validate(self) -> None:
        if self.latency_ms < 0 or self.jitter_ms < 0 or self.bw_kbps < 0:
            raise ValueError("link shape values must be >= 0")
        if not 0.0 <= self.drop < 1.0:
            raise ValueError(f"drop must be in [0, 1), got {self.drop}")

    def is_noop(self) -> bool:
        return (self.latency_ms == 0 and self.jitter_ms == 0
                and self.bw_kbps == 0 and self.drop == 0)

    def to_dict(self) -> Dict[str, float]:
        return {"latency_ms": self.latency_ms, "jitter_ms": self.jitter_ms,
                "bw_kbps": self.bw_kbps, "drop": self.drop}

    @classmethod
    def from_dict(cls, d: Dict) -> "LinkSpec":
        unknown = set(d) - {"latency_ms", "jitter_ms", "bw_kbps", "drop"}
        if unknown:
            raise ValueError(f"unknown link shape keys: {sorted(unknown)}")
        spec = cls(**{k: float(v) for k, v in d.items()})
        spec.validate()
        return spec

    def __repr__(self) -> str:
        return (f"LinkSpec(latency_ms={self.latency_ms}, "
                f"jitter_ms={self.jitter_ms}, bw_kbps={self.bw_kbps}, "
                f"drop={self.drop})")


def parse_links(spec: str) -> Dict[str, LinkSpec]:
    """``"peer_or_*:k=v,k=v;peer2:k=v"`` -> {peer_id: LinkSpec}.

    The empty string parses to an empty table. Raises ValueError on any
    malformed entry — config validation fails loudly, never silently
    un-shapes a link."""
    table: Dict[str, LinkSpec] = {}
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        if ":" not in entry:
            raise ValueError(
                f"link shape entry {entry!r}: expected 'peer:k=v,...'")
        peer, _, kvs = entry.partition(":")
        peer = peer.strip()
        if not peer:
            raise ValueError(f"link shape entry {entry!r}: empty peer id")
        params: Dict[str, float] = {}
        for kv in filter(None, (p.strip() for p in kvs.split(","))):
            if "=" not in kv:
                raise ValueError(
                    f"link shape entry {entry!r}: bad param {kv!r}")
            k, _, v = kv.partition("=")
            try:
                params[k.strip()] = float(v)
            except ValueError:
                raise ValueError(
                    f"link shape entry {entry!r}: non-numeric {kv!r}"
                ) from None
        table[peer] = LinkSpec.from_dict(params)
    return table


def render_links(table: Dict[str, LinkSpec]) -> str:
    """Inverse of :func:`parse_links` (config round-trip, RPC echo)."""
    parts = []
    for peer in sorted(table):
        s = table[peer]
        kvs = ",".join(f"{k}={v:g}" for k, v in s.to_dict().items() if v)
        parts.append(f"{peer}:{kvs}" if kvs else f"{peer}:drop=0")
    return ";".join(parts)


class LinkShaper:
    """Per-node shaping policy: link table + partition set, applied to
    every peer connection via the transport ``conn_wrapper`` hook."""

    def __init__(self, links: Optional[Dict[str, LinkSpec]] = None,
                 seed: int = 0):
        self._lock = threading.Lock()
        self._links: Dict[str, LinkSpec] = dict(links or {})
        self._partition: set = set()
        self._seed = int(seed)

    # --- policy reads (called per write from ShapedConnection) ---

    def spec_for(self, peer_id: str) -> Optional[LinkSpec]:
        with self._lock:
            return self._links.get(peer_id) or self._links.get("*")

    def is_partitioned(self, peer_id: str) -> bool:
        with self._lock:
            return peer_id in self._partition

    # --- runtime mutation (scenario engine over unsafe_net_shape) ---

    def set_links(self, links: Dict[str, LinkSpec]) -> None:
        with self._lock:
            self._links = dict(links)

    def update_links(self, links: Dict[str, LinkSpec]) -> None:
        with self._lock:
            self._links.update(links)

    def set_partition(self, ids: Iterable[str]) -> None:
        """Replace the partitioned peer set (empty iterable = heal)."""
        with self._lock:
            self._partition = set(ids)

    def clear(self) -> None:
        with self._lock:
            self._links.clear()
            self._partition.clear()

    def snapshot(self) -> Dict:
        with self._lock:
            return {"links": {p: s.to_dict()
                              for p, s in sorted(self._links.items())},
                    "partition": sorted(self._partition),
                    "seed": self._seed}

    # --- the transport hook ---

    def wrap(self, conn, peer_id: str):
        """``Transport.conn_wrapper`` signature. Always wraps (even with
        an empty table) so runtime re-shaping reaches connections that
        were established before the first ``unsafe_net_shape`` call."""
        return ShapedConnection(conn, self, peer_id)


class ShapedConnection:
    """Egress-shaping conn wrapper duck-typing the SecretConnection
    surface (write / read_exact / close) the MConnection drives.

    Delayed writes go through a per-connection FIFO drain thread:
    ``write`` computes the packet's delivery time, enqueues, and
    returns immediately, so many packets ride the emulated pipe
    concurrently (real latency is propagation delay, not a throughput
    cap). The drain thread delivers strictly in order — a reliable
    stream never reorders — and a bounded queue gives the sender
    honest backpressure when the pipe backs up."""

    # bounded in-flight buffer: kernel socket buffer + pipe BDP stand-in
    QUEUE_MAX_BYTES = 256 * 1024

    def __init__(self, conn, shaper: LinkShaper, peer_id: str):
        self.conn = conn
        self.shaper = shaper
        self.peer_id = peer_id
        # deterministic per (node seed, peer id): reruns with the same
        # seed drop the same writes on the same links
        self._rng = random.Random(
            shaper._seed ^ zlib.crc32(peer_id.encode()))
        # token bucket for bandwidth; lazily (re)filled against the
        # live bw_kbps so runtime re-shaping takes effect mid-stream
        self._bucket_bytes = 0.0
        self._bucket_at = time.monotonic()
        self._closed = False
        # delayed-delivery queue; the drain thread starts on the first
        # shaped write so unshaped links never pay for a thread
        self._q: collections.deque = collections.deque()
        self._q_cv = threading.Condition()
        self._q_bytes = 0
        self._drain_err: Optional[Exception] = None
        self._drain_thread: Optional[threading.Thread] = None

    def _throttle(self, spec: LinkSpec, n: int) -> float:
        """Seconds until n bytes fit the bw_kbps token bucket."""
        rate = spec.bw_kbps * 1024.0  # bytes/s
        now = time.monotonic()
        self._bucket_bytes = min(
            rate * 0.25,  # burst: at most 250ms of pipe
            self._bucket_bytes + (now - self._bucket_at) * rate)
        self._bucket_at = now
        self._bucket_bytes -= n
        if self._bucket_bytes >= 0:
            return 0.0
        return -self._bucket_bytes / rate

    def _stall_while_partitioned(self) -> None:
        if not self.shaper.is_partitioned(self.peer_id):
            return
        _m.p2p_shape_drops.inc(kind="partition")
        deadline = time.monotonic() + PARTITION_STALL_MAX_S
        while self.shaper.is_partitioned(self.peer_id):
            if self._closed:
                raise OSError("connection closed during partition")
            if time.monotonic() > deadline:
                raise OSError("link partitioned: write stalled out")
            time.sleep(_PARTITION_POLL_S)

    # -- the drain thread ----------------------------------------------------

    def _ensure_drain(self) -> None:
        if self._drain_thread is None:
            t = threading.Thread(target=self._drain, daemon=True,
                                 name=f"link-drain-{self.peer_id[:8]}")
            self._drain_thread = t
            t.start()

    def _drain(self) -> None:
        while True:
            with self._q_cv:
                while not self._q and not self._closed:
                    self._q_cv.wait(0.5)
                if self._closed:
                    return
                deliver_at, data = self._q[0]
            wait = deliver_at - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            try:
                # a partition that lands while packets are in flight
                # holds them too (they were not yet on the wire); the
                # pong timeout or close() ends a too-long stall
                while self.shaper.is_partitioned(self.peer_id):
                    if self._closed:
                        return
                    time.sleep(_PARTITION_POLL_S)
                self.conn.write(data)
            except Exception as e:  # noqa: BLE001 — surface via write()
                with self._q_cv:
                    self._drain_err = e
                    self._q.clear()
                    self._q_bytes = 0
                    self._q_cv.notify_all()
                return
            with self._q_cv:
                self._q.popleft()
                self._q_bytes -= len(data)
                self._q_cv.notify_all()

    def _enqueue(self, data: bytes, delay: float) -> int:
        self._ensure_drain()
        deadline = time.monotonic() + PARTITION_STALL_MAX_S
        with self._q_cv:
            while self._q_bytes >= self.QUEUE_MAX_BYTES:
                if self._closed:
                    raise OSError("connection closed")
                if self._drain_err is not None:
                    raise OSError(f"shaped link died: {self._drain_err}")
                if time.monotonic() > deadline:
                    raise OSError("shaped link backed up: send stalled")
                self._q_cv.wait(0.5)
            if self._drain_err is not None:
                raise OSError(f"shaped link died: {self._drain_err}")
            self._q.append((time.monotonic() + delay, data))
            self._q_bytes += len(data)
            self._q_cv.notify_all()
        return len(data)

    # -- the conn surface ----------------------------------------------------

    def write(self, data: bytes) -> int:
        self._stall_while_partitioned()
        if self._drain_err is not None:
            raise OSError(f"shaped link died: {self._drain_err}")
        spec = self.shaper.spec_for(self.peer_id)
        delay = 0.0
        if spec is not None and not spec.is_noop():
            if spec.drop and self._rng.random() < spec.drop:
                # loss on a reliable stream = retransmission, not
                # vanished bytes (see module docstring)
                _m.p2p_shape_drops.inc(kind="loss")
                delay += max(0.2, 3.0 * spec.latency_ms / 1000.0)
            if spec.latency_ms or spec.jitter_ms:
                delay += (spec.latency_ms
                          + self._rng.random() * spec.jitter_ms) / 1000.0
            if spec.bw_kbps:
                delay += self._throttle(spec, len(data))
        if delay <= 0 and self._drain_thread is None:
            return self.conn.write(data)  # unshaped fast path
        if delay > 0:
            _m.p2p_shape_delay.observe(delay)
        # once the drain thread owns the socket, EVERY write must queue
        # behind it (two writers would interleave frames mid-packet)
        return self._enqueue(data, delay)

    def read_exact(self, n: int) -> bytes:
        # ingress is shaped by the SENDER's egress policy; reading
        # through untouched keeps the stream framing intact
        return self.conn.read_exact(n)

    def close(self) -> None:
        with self._q_cv:
            self._closed = True  # unblocks stalled writes + the drain
            self._q_cv.notify_all()
        try:
            self.conn.close()
        except OSError:
            pass

    def __getattr__(self, name):
        return getattr(self.conn, name)
