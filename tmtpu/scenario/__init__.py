"""Declarative adversarial-localnet scenarios.

A scenario is data: a topology (validators + full nodes, optional shared
verification sidecar), a per-link WAN shape, a seeded fault timeline
(process kills, partitions, sidecar crash storms, faultinject scripts),
a byzantine roster (misbehavior schedules per node), and a list of
oracles that judge PASS/FAIL from the evidence the net emitted —
heights, watchdog verdicts, timeline journals, metrics, committed
evidence. The engine never inspects node internals: everything it knows
arrives over public RPC, exactly like an operator debugging a real net.

    from tmtpu.scenario import library, ScenarioEngine
    spec = library.get("split_brain")
    verdict = ScenarioEngine(spec, outdir="/tmp/sb").run()
    assert verdict["pass"], verdict

Modules: ``spec`` (the declarative dataclasses), ``net`` (the e2e-runner
subclass that owns processes and the shaping/partition fan-out),
``oracles`` (the named pass/fail predicates over gathered evidence),
``engine`` (timeline execution + evidence gathering + judging) and
``library`` (the named starter scenarios).
"""

from tmtpu.scenario.spec import (FaultAction, OracleSpec,  # noqa: F401
                                 ScenarioSpec)
from tmtpu.scenario.engine import ScenarioEngine  # noqa: F401
from tmtpu.scenario import library  # noqa: F401
