"""Scenario engine: execute a spec's fault timeline, gather evidence,
judge PASS/FAIL.

One run is four phases on a single clock (t=0 at net start):

1. **Run** — nodes come up (plus the sidecar and/or lightserve daemon
   when the spec wants them, and the light-session flood feeding the
   dispatch_avoided_rate oracle), tx load starts, and a sampler thread
   polls every node's height
   and watchdog verdict (the health time-series that stall/convergence
   oracles read).
2. **Perturb** — fault actions execute at their ``at_s`` offsets:
   signals, partitions (unsafe_net_shape fan-out), faultinject scripts,
   sidecar kill/restart storms, validator-set txs, statesync joins.
3. **Settle** — load stops and the net quiesces for ``settle_s`` so
   convergence is judged on steady state, not on an in-flight burst.
4. **Judge** — a final RPC sweep per node (status, health_detail,
   metrics, timeline, block bodies) becomes the ``Evidence`` bundle;
   each oracle in the spec renders a verdict over it. PASS = every
   oracle passed. The engine never inspects process internals — a
   scenario that cannot be judged from public RPC evidence fails.

The verdict (and the evidence the judgment used, minus block bodies)
is persisted under the run's outdir for post-mortems.

Composed scenarios (spec.compose) run through the same four phases;
every fault action and oracle carries its contributing layer, and the
verdict adds a per-layer attribution block so a failed composed run
names which layer's faults misfired and which layer's invariants broke.

The engine's lifecycle is also consumable piecewise — ``boot()``,
``execute_action()``, ``gather_evidence()``, ``judge()``,
``shutdown()`` — which is how tools/chaos_soak.py drives an open-ended
rotating fault schedule with periodic verdicts instead of one fixed
timeline. ``shutdown()`` is idempotent and joins the sampler thread
(the PR-14 shutdown-join guarantees extended to the engine), so a
SIGTERM mid-run drains cleanly.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

from tmtpu.scenario import oracles as oracle_mod
from tmtpu.scenario.net import ScenarioNet
from tmtpu.scenario.oracles import Evidence
from tmtpu.scenario.spec import ScenarioSpec

_SAMPLE_INTERVAL_S = 0.7
_BLOCK_FETCH_CAP = 200          # per node; scenarios run far shorter


class ScenarioEngine:
    def __init__(self, spec: ScenarioSpec, outdir: str, log=None):
        self.spec = spec
        self.outdir = outdir
        self._log = log or (lambda msg: None)
        self.net = ScenarioNet(spec, outdir)
        self.samples: list = []
        self.events: list = []
        self._t0 = 0.0
        self._sampling = threading.Event()
        self._sampling_stopped = threading.Event()
        self._sampler_thread = None
        self._timers: list = []
        self._booted = False

    # -- clock ---------------------------------------------------------------

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def now(self) -> float:
        """Seconds since net start (0.0 before boot)."""
        return self._now() if self._booted else 0.0

    # -- sampling ------------------------------------------------------------

    def _sample_once(self) -> None:
        t = self._now()
        for node in self.net.nodes:
            if node.proc is None:        # never started (manual joiner)
                continue
            entry = {"t": round(t, 3), "node": node.spec.name,
                     "height": -1, "healthy": False, "reasons": []}
            try:
                st = node.client.status()
                entry["height"] = int(
                    st["sync_info"]["latest_block_height"])
                hd = node.client.health_detail()
                entry["healthy"] = bool(hd.get("healthy"))
                entry["reasons"] = list(hd.get("reasons", []))
            except Exception as e:
                entry["reasons"] = [f"rpc: {e}"]
            self.samples.append(entry)

    def _sampler(self) -> None:
        while self._sampling.is_set():
            self._sample_once()
            # wait on the event, not a bare sleep: shutdown() flips the
            # flag and the thread exits within one RPC round, so the
            # join in shutdown() is bounded by sampling work, not naps
            self._sampling_stopped.wait(_SAMPLE_INTERVAL_S)

    def start_sampler(self) -> None:
        if self._sampler_thread is not None and \
                self._sampler_thread.is_alive():
            return
        self._sampling.set()
        self._sampling_stopped = threading.Event()
        self._sampler_thread = threading.Thread(
            target=self._sampler, name="scenario-sampler", daemon=True)
        self._sampler_thread.start()

    def stop_sampler(self, timeout: float = 10.0) -> bool:
        """Stop and JOIN the health sampler; True when the thread is
        down. Bounded by one in-flight RPC sweep (5 s client timeout
        per call), never by the sampling nap."""
        self._sampling.clear()
        if getattr(self, "_sampling_stopped", None) is not None:
            self._sampling_stopped.set()
        t = self._sampler_thread
        if t is not None and t.is_alive():
            t.join(timeout)
        return t is None or not t.is_alive()

    def trim_samples(self, keep: int) -> None:
        """Drop all but the newest ``keep`` sample rows — long soaks
        sample for hours and judge on a rolling window, so the full
        time-series would only grow the process."""
        if keep >= 0 and len(self.samples) > keep:
            del self.samples[:len(self.samples) - keep]

    # -- fault execution -----------------------------------------------------

    def execute_action(self, action) -> dict:
        """Execute one FaultAction NOW (its ``at_s`` is recorded, not
        waited on) and append the outcome to the event log. Returns the
        event row. This is the public single-step surface the timeline
        loop and the chaos-soak scheduler share."""
        t = round(self._now(), 3)
        try:
            detail = self._dispatch(action)
            ok = True
        except Exception as e:
            detail, ok = f"{type(e).__name__}: {e}", False
        self._log(f"[{t:7.2f}s] {action.op} {action.node or '*'}"
                  + (f" [{action.layer}]" if action.layer else "")
                  + f": {detail}")
        event = {"t": t, "op": action.op, "node": action.node,
                 "ok": ok, "detail": detail}
        if action.layer:
            event["layer"] = action.layer
        self.events.append(event)
        return event

    def _dispatch(self, action) -> str:
        net, p = self.net, action.params
        op = action.op
        if op == "kill":
            node = net.node(action.node)
            node.signal(signal.SIGKILL)
            if node.proc is not None:
                node.proc.wait(10)
            return "killed"
        if op == "start":
            net.node(action.node).start()
            return "started"
        if op == "restart":
            node = net.node(action.node)
            node.stop()
            down = float(p.get("down_s", 0.5))
            if down:
                time.sleep(down)
            node.start()
            return f"restarted after {down}s"
        if op == "sigterm":
            node = net.node(action.node)
            node.signal(signal.SIGTERM)
            if node.proc is not None:
                node.proc.wait(15)
            return "terminated"
        if op == "pause":
            node = net.node(action.node)
            node.signal(signal.SIGSTOP)
            for_s = float(p.get("for_s", 3.0))
            timer = threading.Timer(
                for_s, lambda: node.signal(signal.SIGCONT))
            timer.daemon = True
            timer.start()
            self._timers.append(timer)
            return f"paused for {for_s}s"
        if op == "amnesia":
            net.amnesia(action.node)
            return "privval state wiped, restarted"
        if op == "partition":
            res = net.partition(p["groups"])
            return f"partitioned {p['groups']}: " + self._fanout_digest(res)
        if op == "heal":
            return "healed: " + self._fanout_digest(net.heal())
        if op == "shape":
            res = net.shape(p["links"], p.get("nodes"))
            return f"shaped {p['links']!r}: " + self._fanout_digest(res)
        if op == "clear_shape":
            return "cleared: " + self._fanout_digest(
                net.clear_shape(p.get("nodes")))
        if op == "inject":
            kw = {k: v for k, v in p.items()
                  if k in ("count", "after", "ms", "p", "seed")}
            net.node(action.node).client.unsafe_inject_fault(
                site=p["site"], mode=p["mode"], **kw)
            return f"scripted {p['site']}={p['mode']}"
        if op == "clear_faults":
            targets = [net.node(action.node)] if action.node else \
                [n for n in net.nodes if n.running]
            for node in targets:
                node.client.unsafe_inject_fault(clear=True)
            return f"cleared faults on {len(targets)} nodes"
        if op == "sidecar_kill":
            net.kill_sidecar()
            return f"sidecar SIGKILL #{net.sidecar_kills}"
        if op == "sidecar_term":
            net.term_sidecar()
            return "sidecar SIGTERM (drained)"
        if op == "sidecar_restart":
            net.start_sidecar()
            return "sidecar restarted"
        if op == "tx":
            tx = p["tx"].encode() if isinstance(p["tx"], str) else p["tx"]
            self._any_live_client().broadcast_tx_sync(tx)
            return f"broadcast {len(tx)}B tx"
        if op == "add_validator":
            from tmtpu.abci.example.kvstore import make_validator_tx
            from tmtpu.crypto.ed25519 import gen_priv_key_from_secret
            power = int(p.get("power", 10))
            # deterministic key: same seed -> same validator set history
            secret = f"scenario:{self.spec.name}:{self.spec.seed}:" \
                     f"{action.at_s}".encode()
            pub = gen_priv_key_from_secret(secret).pub_key().bytes()
            self._any_live_client().broadcast_tx_sync(
                make_validator_tx(pub, power))
            return f"validator-update tx power={power}"
        if op == "join_statesync":
            res = net.join_statesync(
                action.node, trust_height=int(p.get("trust_height", 1)))
            return f"statesync join: {res}"
        raise ValueError(f"unknown fault op {op!r}")

    @staticmethod
    def _fanout_digest(res: dict) -> str:
        bad = {n: r["error"] for n, r in res.items() if not r["ok"]}
        return f"{len(res) - len(bad)}/{len(res)} ok" + \
            (f", errors {bad}" if bad else "")

    def _any_live_client(self):
        for node in self.net.nodes:
            if node.running:
                return node.client
        raise RuntimeError("no live node")

    def _run_timeline(self) -> None:
        for action in sorted(self.spec.faults, key=lambda a: a.at_s):
            delay = action.at_s - self._now()
            if delay > 0:
                time.sleep(delay)
            self.execute_action(action)
        tail = self.spec.duration_s - self._now()
        if tail > 0:
            time.sleep(tail)

    # -- evidence ------------------------------------------------------------

    def gather_evidence(self, block_cap: int = _BLOCK_FETCH_CAP) \
            -> Evidence:
        return self._gather(block_cap)

    def _gather(self, block_cap: int = _BLOCK_FETCH_CAP) -> Evidence:
        nodes = {}
        for node in self.net.nodes:
            snap = {"final_height": -1, "running": node.running,
                    "health": None, "metrics": None, "timeline": None,
                    "txlat": None, "validator_stats": None, "blocks": {}}
            if node.proc is not None:
                # two attempts: on a big starved net a single RPC
                # timeout is routine, and one failed status() must not
                # erase the node's whole snapshot (an absent snapshot
                # reads as "no evidence" to every oracle downstream)
                for attempt in (0, 1):
                    try:
                        st = node.client.status()
                        snap["final_height"] = int(
                            st["sync_info"]["latest_block_height"])
                        snap["health"] = node.client.health_detail()
                        snap["metrics"] = node.client.metrics()
                        snap["timeline"] = node.client.timeline(last=100)
                        snap["txlat"] = node.client.txlat(limit=256)
                        snap["validator_stats"] = \
                            node.client.validator_stats(limit=256)
                        snap["blocks"] = self._fetch_blocks(
                            node, snap["final_height"], block_cap)
                        snap.pop("error", None)
                        break
                    except Exception as e:
                        snap["error"] = str(e)
            nodes[node.spec.name] = snap
        return Evidence(self.spec, self.events, self.samples, nodes,
                        sidecar_kills=self.net.sidecar_kills,
                        lightserve=(self.net.light_stats()
                                    if self.spec.lightserve else None))

    @staticmethod
    def _fetch_blocks(node, top: int,
                      block_cap: int = _BLOCK_FETCH_CAP) -> dict:
        if top < 2:
            return {}
        lo = max(2, top - block_cap + 1)
        heights = list(range(lo, top + 1))
        blocks = {}
        for i in range(0, len(heights), 25):
            chunk = heights[i:i + 25]
            results = node.client.call_batch(
                [("block", {"height": str(h)}) for h in chunk])
            for h, res in zip(chunk, results):
                if not isinstance(res, Exception):
                    blocks[h] = res["block"]
        return blocks

    # -- lifecycle -----------------------------------------------------------

    def boot(self) -> None:
        """Provision and start the net (sidecar first when the spec
        wants one), zero the scenario clock, start the health sampler
        and the tx load. After boot() the engine is live: drive it with
        execute_action()/gather_evidence()/judge(), then shutdown()."""
        spec = self.spec
        self._log(f"scenario {spec.name!r}: {spec.validators} validators"
                  + (f" + {spec.full_nodes} full nodes"
                     if spec.full_nodes else "")
                  + (" + sidecar" if spec.sidecar else "")
                  + (" + lightserve" if spec.lightserve else "")
                  + (f", layers {spec.layers}" if spec.layers else "")
                  + f", seed {spec.seed}")
        self.net.setup()
        if spec.sidecar:
            self.net.start_sidecar()
        self.net.start(log=self._log)
        self._t0 = time.monotonic()
        self._booted = True
        self.start_sampler()
        if spec.load_rate > 0:
            self.net.start_load()
        if spec.lightserve:
            # after start_load: the daemon anchors on the live chain's
            # height-1 commit, so the net must be committing first
            self.net.start_lightserve()
            self.net.start_light_load()
            self._log(f"[{self._now():7.2f}s] lightserve up on "
                      f"{self.net.lightserve_addr}, light flood started")

    def shutdown(self) -> None:
        """Tear everything down in join-clean order: sampler thread
        joined (not abandoned), pending SIGCONT timers cancelled, load
        threads joined, every node SIGTERMed. Idempotent — safe from
        run()'s finally AND from a SIGINT/SIGTERM handler that fires
        mid-phase."""
        self.stop_sampler()
        for timer in self._timers:
            timer.cancel()
        self._timers = []
        self.net.stop()

    # -- judging -------------------------------------------------------------

    def judge(self, evidence: Evidence, oracle_specs=None) -> list:
        """Render every oracle's verdict over ``evidence``; composed
        specs keep each oracle's layer tag on its verdict row."""
        verdicts = []
        for ospec in (oracle_specs if oracle_specs is not None
                      else self.spec.oracles):
            fn = oracle_mod.get(ospec.name)
            try:
                ok, detail = fn(evidence, **ospec.params)
            except Exception as e:
                ok, detail = False, f"oracle crashed: " \
                    f"{type(e).__name__}: {e}"
            row = {"name": ospec.name, "params": dict(ospec.params),
                   "pass": bool(ok), "detail": detail}
            if getattr(ospec, "layer", ""):
                row["layer"] = ospec.layer
            verdicts.append(row)
            self._log(f"  {'PASS' if ok else 'FAIL'} {ospec.name}"
                      + (f" [{ospec.layer}]" if row.get("layer") else "")
                      + f": {detail}")
        return verdicts

    def _layer_attribution(self, verdicts: list) -> dict:
        """Per-layer rollup for composed specs: which layer's fault
        actions errored and which layer's invariants failed. A composed
        FAIL therefore names the contributing layer(s), not just the
        oracle."""
        layers = {}
        for name in self.spec.layers:
            evs = [e for e in self.events if e.get("layer") == name]
            vs = [v for v in verdicts if v.get("layer") == name]
            layers[name] = {
                "faults_executed": len(evs),
                "fault_errors": [
                    {"t": e["t"], "op": e["op"], "detail": e["detail"]}
                    for e in evs if not e["ok"]],
                "oracles": len(vs),
                "oracles_failed": [v["name"] for v in vs
                                   if not v["pass"]],
            }
        return layers

    # -- the run -------------------------------------------------------------

    def run(self) -> dict:
        spec = self.spec
        problems = spec.validate()
        if problems:
            raise ValueError(f"invalid scenario: {problems}")
        started_unix = time.time()
        try:
            self.boot()
            self._run_timeline()
            self.net.stop_load()
            if spec.lightserve:
                self.net.stop_light_load()
            if spec.settle_s > 0:
                self._log(f"[{self._now():7.2f}s] settling "
                          f"{spec.settle_s}s before judging")
                time.sleep(spec.settle_s)
            self.stop_sampler()
            self._sample_once()        # one last row at judge time
            evidence = self._gather()
        finally:
            self.shutdown()

        verdicts = self.judge(evidence)
        verdict = {
            "scenario": spec.name,
            "seed": spec.seed,
            "pass": all(v["pass"] for v in verdicts),
            "oracles": verdicts,
            "final_heights": evidence.final_heights(),
            "events": self.events,
            "sidecar_kills": self.net.sidecar_kills,
            "started_unix": round(started_unix, 3),
            "wall_s": round(time.time() - started_unix, 3),
            "outdir": self.outdir,
        }
        if spec.lightserve:
            verdict["lightserve"] = evidence.lightserve
        if spec.layers:
            verdict["layers"] = self._layer_attribution(verdicts)
        self._persist(verdict)
        self._log(f"verdict: {'PASS' if verdict['pass'] else 'FAIL'} "
                  f"({verdict['wall_s']}s)")
        return verdict

    def _persist(self, verdict: dict) -> None:
        try:
            os.makedirs(self.outdir, exist_ok=True)
            with open(os.path.join(self.outdir, "verdict.json"),
                      "w") as f:
                json.dump(verdict, f, indent=2, sort_keys=True)
            with open(os.path.join(self.outdir, "samples.json"),
                      "w") as f:
                json.dump(self.samples, f)
        except OSError:
            pass  # judging succeeded; persistence is best-effort


def run_scenario(spec: ScenarioSpec, outdir: str, log=None) -> dict:
    return ScenarioEngine(spec, outdir, log=log).run()
