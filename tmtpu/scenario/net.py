"""ScenarioNet: the process-owning half of the scenario engine.

Subclasses the e2e Runner (tmtpu/e2e/runner.py) — same home-dir layout,
genesis, subprocess nodes and tx load — and adds what adversarial
scenarios need on top:

- every node runs with ``[rpc] unsafe`` on, so the engine can re-shape
  links, blackhole peers and script faultinject sites over RPC while
  the net runs;
- an optional shared verification sidecar daemon (``crypto.backend =
  sidecar`` on every node) that the fault timeline can kill, drain and
  restart — the crash-storm surface;
- partition/heal/shape fan-out helpers that translate group-level
  intent ("split {v00,v01,v02} from {v03}") into per-node
  ``unsafe_net_shape`` calls (each node blackholes its own egress, so
  applying the rule on every member severs both directions);
- a statesync join helper that derives the light-client trust anchor
  from a live node's ``commit`` RPC and rewrites the joiner's config
  before starting it.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

from tmtpu.config import toml as cfg_toml
from tmtpu.e2e.localnet import make_manifest
from tmtpu.e2e.manifest import Manifest
from tmtpu.e2e.runner import Runner, _hold_port, _REPO_ROOT
from tmtpu.scenario.spec import ScenarioSpec

# scenario nets watch for stalls on a tight leash: the default watchdog
# deadline (30 s) is longer than most whole scenarios, so a partitioned
# minority would never report unhealthy before the heal
_STALL_TIMEOUT_NS = 5 * 10**9


def build_manifest(spec: ScenarioSpec, sidecar_addr: str = "") -> Manifest:
    """Translate a ScenarioSpec into the e2e Manifest the Runner
    understands (shared boot path: tmtpu/e2e/localnet.py). Perturbations
    stay empty — the engine drives its own wall-clock fault timeline
    instead of the Runner's height-triggered one."""
    base = {
        "rpc.unsafe": True,
        "health.consensus_stall_timeout_ns": _STALL_TIMEOUT_NS,
    }
    if spec.links:
        base["p2p.shape_links"] = spec.links
        base["p2p.shape_seed"] = spec.seed
    if spec.sidecar:
        base["base.crypto_backend"] = "sidecar"
        base["sidecar.addr"] = sidecar_addr
    base.update(spec.config)

    def start_at(name, validator):
        # -1 = provisioned, never auto-started (manual joiners)
        if not validator and spec.full_node_start == "manual":
            return -1
        return 0

    return make_manifest(
        f"scenario-{spec.name}", spec.node_names(),
        base_config=base, node_config=spec.node_config,
        key_type=spec.key_type, key_types=spec.key_types,
        misbehaviors=spec.misbehaviors,
        start_at=start_at, load_rate=spec.load_rate,
        load_size=spec.load_size, target_height=12,
        timeout_s=spec.timeout_s)


class ScenarioNet(Runner):
    def __init__(self, spec: ScenarioSpec, outdir: str):
        self.spec = spec
        self.sidecar_proc = None
        self.sidecar_kills = 0
        self.sidecar_home = os.path.join(outdir, "_sidecar")
        if spec.sidecar:
            port, self._sidecar_hold = _hold_port()
            self.sidecar_addr = f"tcp://127.0.0.1:{port}"
        else:
            self.sidecar_addr = ""
            self._sidecar_hold = None
        super().__init__(build_manifest(spec, self.sidecar_addr), outdir)

    def node(self, name: str):
        for n in self.nodes:
            if n.spec.name == name:
                return n
        raise KeyError(f"no node named {name!r}")

    # -- sidecar daemon ------------------------------------------------------

    def start_sidecar(self, timeout: float = 20.0) -> None:
        """Launch (or relaunch) the shared verification daemon and block
        until its listener accepts — nodes started before this point
        would burn breaker budget on connection refusals."""
        if self.sidecar_proc is not None and \
                self.sidecar_proc.poll() is None:
            return
        if self._sidecar_hold is not None:
            try:
                self._sidecar_hold.close()
            except OSError:
                pass
            self._sidecar_hold = None
        os.makedirs(self.sidecar_home, exist_ok=True)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["TMTPU_CRYPTO_BACKEND"] = "cpu"
        log = open(os.path.join(self.sidecar_home, "sidecar.log"), "ab")
        self.sidecar_proc = subprocess.Popen(
            [sys.executable, "-m", "tmtpu.cmd", "sidecar",
             "--home", self.sidecar_home, "--addr", self.sidecar_addr,
             "--backend", "cpu", "--no-warm"],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        host, port = self.sidecar_addr.split("://", 1)[1].rsplit(":", 1)
        deadline = time.monotonic() + timeout
        while True:
            try:
                socket.create_connection((host, int(port)),
                                         timeout=1.0).close()
                return
            except OSError:
                if self.sidecar_proc.poll() is not None:
                    raise RuntimeError(
                        f"sidecar exited rc={self.sidecar_proc.returncode} "
                        f"(see {self.sidecar_home}/sidecar.log)")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"sidecar not accepting on {self.sidecar_addr}")
                time.sleep(0.1)

    def kill_sidecar(self) -> None:
        if self.sidecar_proc is None or self.sidecar_proc.poll() is not None:
            return
        os.killpg(self.sidecar_proc.pid, signal.SIGKILL)
        self.sidecar_proc.wait(10)
        self.sidecar_kills += 1

    def term_sidecar(self, timeout: float = 30.0) -> None:
        if self.sidecar_proc is None or self.sidecar_proc.poll() is not None:
            return
        os.killpg(self.sidecar_proc.pid, signal.SIGTERM)
        try:
            self.sidecar_proc.wait(timeout)
        except subprocess.TimeoutExpired:
            os.killpg(self.sidecar_proc.pid, signal.SIGKILL)
            self.sidecar_proc.wait(10)

    # -- runtime shaping fan-out ---------------------------------------------

    def _fanout(self, nodes, fn) -> dict:
        """Apply ``fn(node)`` to each running target; collect per-node
        outcomes instead of dying on the first RPC error (a node the
        timeline just killed is an expected miss, not a run failure)."""
        out = {}
        for node in nodes:
            try:
                out[node.spec.name] = {"ok": True, "result": fn(node)}
            except Exception as e:
                out[node.spec.name] = {"ok": False, "error": str(e)}
        return out

    def partition(self, groups) -> dict:
        """Sever traffic BETWEEN groups: each member stalls its egress
        to every node outside its own group (TCP-backpressure emulation,
        see p2p/shaping.py). Nodes in no group keep full connectivity
        (scenarios that want a clean split list everyone)."""
        by_name = {n.spec.name: n for n in self.nodes}
        results = {}
        for group in groups:
            inside = set(group)
            outside_ids = [by_name[n].node_id for n in by_name
                           if n not in inside]
            members = [by_name[n] for n in group]
            results.update(self._fanout(
                members,
                lambda nd, ids=outside_ids:
                    nd.client.unsafe_net_shape(partition=ids)))
        return results

    def heal(self) -> dict:
        return self._fanout(
            [n for n in self.nodes if n.running],
            lambda nd: nd.client.unsafe_net_shape(partition=[]))

    def shape(self, links: str, names=None) -> dict:
        targets = [self.node(n) for n in names] if names else \
            [n for n in self.nodes if n.running]
        return self._fanout(
            targets, lambda nd: nd.client.unsafe_net_shape(links=links))

    def clear_shape(self, names=None) -> dict:
        targets = [self.node(n) for n in names] if names else \
            [n for n in self.nodes if n.running]
        return self._fanout(
            targets, lambda nd: nd.client.unsafe_net_shape(clear=True))

    # -- late joins ----------------------------------------------------------

    def _rewrite_config(self, node, mutate) -> None:
        """Regenerate a down node's config.toml through the same path
        setup() used, apply ``mutate(cfg)``, and persist."""
        from tmtpu.e2e.localnet import chord_peer_names
        cfg = self._node_config(node)
        peers = {n.spec.name: f"{n.node_id}@127.0.0.1:{n.p2p_port}"
                 for n in self.nodes}
        plan = chord_peer_names([n.spec.name for n in self.nodes])
        cfg.p2p.persistent_peers = ",".join(
            peers[name] for name in plan[node.spec.name])
        mutate(cfg)
        cfg_toml.write_config(
            cfg, os.path.join(node.home, "config", "config.toml"))

    def join_statesync(self, name: str, trust_height: int = 1) -> dict:
        """Start ``name`` as a statesync joiner: trust anchor = the
        block-id hash served by a live node's ``commit`` RPC at
        ``trust_height``, snapshot/light-block sources = every running
        validator's RPC."""
        joiner = self.node(name)
        live = [n for n in self.nodes
                if n.running and n.spec.name != name]
        if not live:
            raise RuntimeError("no live node to anchor statesync trust")
        commit = live[0].client.commit(height=trust_height)
        trust_hash = commit["signed_header"]["commit"]["block_id"]["hash"]
        rpc_servers = [f"http://127.0.0.1:{n.rpc_port}" for n in live[:2]]

        def mutate(cfg):
            cfg.state_sync.enable = True
            cfg.state_sync.rpc_servers = rpc_servers
            cfg.state_sync.trust_height = trust_height
            cfg.state_sync.trust_hash = trust_hash
            cfg.state_sync.discovery_time_ns = 10**9

        self._rewrite_config(joiner, mutate)
        joiner.start()
        return {"trust_height": trust_height, "trust_hash": trust_hash,
                "rpc_servers": rpc_servers}

    def amnesia(self, name: str) -> None:
        """Crash ``name`` and wipe its double-sign protection (the
        privval last-signed state) before restarting — the amnesiac
        validator from the fork-accountability literature. The state
        file is RESET to the zeroed watermark, not deleted: FilePV.load
        refuses to start when the file is missing outright (a missing
        file is indistinguishable from corruption), while a height-0
        watermark is exactly what a validator that forgot everything it
        signed looks like."""
        node = self.node(name)
        node.signal(signal.SIGKILL)
        if node.proc is not None:
            node.proc.wait(10)
        cfg = self._node_config(node)
        state = cfg.rooted(cfg.base.priv_validator_state_file)
        with open(state, "w") as f:
            json.dump({"height": "0", "round": 0, "step": 0}, f)
        node.start()

    def stop(self):
        super().stop()
        if self.sidecar_proc is not None and \
                self.sidecar_proc.poll() is None:
            self.term_sidecar(timeout=5.0)
