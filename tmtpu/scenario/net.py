"""ScenarioNet: the process-owning half of the scenario engine.

Subclasses the e2e Runner (tmtpu/e2e/runner.py) — same home-dir layout,
genesis, subprocess nodes and tx load — and adds what adversarial
scenarios need on top:

- every node runs with ``[rpc] unsafe`` on, so the engine can re-shape
  links, blackhole peers and script faultinject sites over RPC while
  the net runs;
- an optional shared verification sidecar daemon (``crypto.backend =
  sidecar`` on every node) that the fault timeline can kill, drain and
  restart — the crash-storm surface;
- an optional light-client commit-proof serving daemon
  (``tmtpu lightserve``) anchored on the live chain's height-1 header,
  plus a pipelined light-session flood whose served/avoided/error
  counters feed the ``dispatch_avoided_rate`` oracle;
- partition/heal/shape fan-out helpers that translate group-level
  intent ("split {v00,v01,v02} from {v03}") into per-node
  ``unsafe_net_shape`` calls (each node blackholes its own egress, so
  applying the rule on every member severs both directions);
- a statesync join helper that derives the light-client trust anchor
  from a live node's ``commit`` RPC and rewrites the joiner's config
  before starting it.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque

from tmtpu.config import toml as cfg_toml
from tmtpu.e2e.localnet import make_manifest
from tmtpu.e2e.manifest import Manifest
from tmtpu.e2e.runner import Runner, _hold_port, _REPO_ROOT
from tmtpu.scenario.spec import ScenarioSpec

# scenario nets watch for stalls on a tight leash: the default watchdog
# deadline (30 s) is longer than most whole scenarios, so a partitioned
# minority would never report unhealthy before the heal
_STALL_TIMEOUT_NS = 5 * 10**9


def build_manifest(spec: ScenarioSpec, sidecar_addr: str = "") -> Manifest:
    """Translate a ScenarioSpec into the e2e Manifest the Runner
    understands (shared boot path: tmtpu/e2e/localnet.py). Perturbations
    stay empty — the engine drives its own wall-clock fault timeline
    instead of the Runner's height-triggered one."""
    base = {
        "rpc.unsafe": True,
        "health.consensus_stall_timeout_ns": _STALL_TIMEOUT_NS,
    }
    if spec.links:
        base["p2p.shape_links"] = spec.links
        base["p2p.shape_seed"] = spec.seed
    if spec.sidecar:
        base["base.crypto_backend"] = "sidecar"
        base["sidecar.addr"] = sidecar_addr
    base.update(spec.config)

    def start_at(name, validator):
        # -1 = provisioned, never auto-started (manual joiners)
        if not validator and spec.full_node_start == "manual":
            return -1
        return 0

    return make_manifest(
        f"scenario-{spec.name}", spec.node_names(),
        base_config=base, node_config=spec.node_config,
        key_type=spec.key_type, key_types=spec.key_types,
        misbehaviors=spec.misbehaviors,
        start_at=start_at, load_rate=spec.load_rate,
        load_size=spec.load_size, target_height=12,
        timeout_s=spec.timeout_s)


class ScenarioNet(Runner):
    def __init__(self, spec: ScenarioSpec, outdir: str):
        self.spec = spec
        self.sidecar_proc = None
        self.sidecar_kills = 0
        self.sidecar_home = os.path.join(outdir, "_sidecar")
        if spec.sidecar:
            port, self._sidecar_hold = _hold_port()
            self.sidecar_addr = f"tcp://127.0.0.1:{port}"
        else:
            self.sidecar_addr = ""
            self._sidecar_hold = None
        self.lightserve_proc = None
        self.lightserve_home = os.path.join(outdir, "_lightserve")
        self._light_trust = None          # (height, hex hash) once anchored
        self._light_thread = None
        self._light_stop = threading.Event()
        self._light_lock = threading.Lock()
        self._light_lat: list = []
        self._light_stats = {"sessions": 0, "avoided": 0, "errors": 0,
                             "warmed": 0}
        if spec.lightserve:
            port, self._lightserve_hold = _hold_port()
            self.lightserve_addr = f"tcp://127.0.0.1:{port}"
        else:
            self.lightserve_addr = ""
            self._lightserve_hold = None
        super().__init__(build_manifest(spec, self.sidecar_addr), outdir)

    def node(self, name: str):
        for n in self.nodes:
            if n.spec.name == name:
                return n
        raise KeyError(f"no node named {name!r}")

    # -- sidecar daemon ------------------------------------------------------

    def start_sidecar(self, timeout: float = 20.0) -> None:
        """Launch (or relaunch) the shared verification daemon and block
        until its listener accepts — nodes started before this point
        would burn breaker budget on connection refusals."""
        if self.sidecar_proc is not None and \
                self.sidecar_proc.poll() is None:
            return
        if self._sidecar_hold is not None:
            try:
                self._sidecar_hold.close()
            except OSError:
                pass
            self._sidecar_hold = None
        os.makedirs(self.sidecar_home, exist_ok=True)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["TMTPU_CRYPTO_BACKEND"] = "cpu"
        log = open(os.path.join(self.sidecar_home, "sidecar.log"), "ab")
        self.sidecar_proc = subprocess.Popen(
            [sys.executable, "-m", "tmtpu.cmd", "sidecar",
             "--home", self.sidecar_home, "--addr", self.sidecar_addr,
             "--backend", "cpu", "--no-warm"],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        host, port = self.sidecar_addr.split("://", 1)[1].rsplit(":", 1)
        deadline = time.monotonic() + timeout
        while True:
            try:
                socket.create_connection((host, int(port)),
                                         timeout=1.0).close()
                return
            except OSError:
                if self.sidecar_proc.poll() is not None:
                    raise RuntimeError(
                        f"sidecar exited rc={self.sidecar_proc.returncode} "
                        f"(see {self.sidecar_home}/sidecar.log)")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"sidecar not accepting on {self.sidecar_addr}")
                time.sleep(0.1)

    def kill_sidecar(self) -> None:
        if self.sidecar_proc is None or self.sidecar_proc.poll() is not None:
            return
        os.killpg(self.sidecar_proc.pid, signal.SIGKILL)
        self.sidecar_proc.wait(10)
        self.sidecar_kills += 1

    def term_sidecar(self, timeout: float = 30.0) -> None:
        if self.sidecar_proc is None or self.sidecar_proc.poll() is not None:
            return
        os.killpg(self.sidecar_proc.pid, signal.SIGTERM)
        try:
            self.sidecar_proc.wait(timeout)
        except subprocess.TimeoutExpired:
            os.killpg(self.sidecar_proc.pid, signal.SIGKILL)
            self.sidecar_proc.wait(10)

    # -- lightserve daemon + light-session flood -----------------------------

    def _light_anchor(self, timeout: float = 60.0) -> str:
        """The serving tier's trust anchor: the height-1 block-id hash
        (== header hash) from any live node's ``commit`` RPC — the same
        social-consensus root join_statesync derives. Polls until the
        young chain actually serves it."""
        deadline = time.monotonic() + timeout
        last_err = "no live node"
        while time.monotonic() < deadline:
            for n in self.nodes:
                if not n.running:
                    continue
                try:
                    commit = n.client.commit(height=1)
                    return commit["signed_header"]["commit"][
                        "block_id"]["hash"]
                except Exception as e:
                    last_err = str(e)
            time.sleep(0.3)
        raise TimeoutError(f"no node served commit(1) within {timeout}s "
                           f"({last_err})")

    def start_lightserve(self, timeout: float = 60.0) -> None:
        """Launch the commit-proof serving daemon against node0's live
        RPC and block until its listener accepts. Must run AFTER
        net.start(): the daemon fetches and verifies its trust anchor
        from the upstream at startup, so the chain has to be committing
        first."""
        if self.lightserve_proc is not None and \
                self.lightserve_proc.poll() is None:
            return
        trust_hash = self._light_anchor(timeout)
        self._light_trust = (1, trust_hash)
        if self._lightserve_hold is not None:
            try:
                self._lightserve_hold.close()
            except OSError:
                pass
            self._lightserve_hold = None
        os.makedirs(self.lightserve_home, exist_ok=True)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["TMTPU_CRYPTO_BACKEND"] = "cpu"
        log = open(os.path.join(self.lightserve_home,
                                "lightserve.log"), "ab")
        self.lightserve_proc = subprocess.Popen(
            [sys.executable, "-m", "tmtpu.cmd", "lightserve",
             "--home", self.lightserve_home,
             "--addr", self.lightserve_addr,
             "--upstream", f"http://127.0.0.1:{self.nodes[0].rpc_port}",
             "--chain-id", self.m.chain_id,
             "--trust-height", "1", "--trust-hash", trust_hash,
             "--backend", "cpu"],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        host, port = self.lightserve_addr.split("://", 1)[1] \
            .rsplit(":", 1)
        deadline = time.monotonic() + timeout
        while True:
            try:
                socket.create_connection((host, int(port)),
                                         timeout=1.0).close()
                return
            except OSError:
                if self.lightserve_proc.poll() is not None:
                    raise RuntimeError(
                        f"lightserve exited "
                        f"rc={self.lightserve_proc.returncode} (see "
                        f"{self.lightserve_home}/lightserve.log)")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"lightserve not accepting on "
                        f"{self.lightserve_addr}")
                time.sleep(0.1)

    def term_lightserve(self, timeout: float = 10.0) -> None:
        if self.lightserve_proc is None or \
                self.lightserve_proc.poll() is not None:
            return
        os.killpg(self.lightserve_proc.pid, signal.SIGTERM)
        try:
            self.lightserve_proc.wait(timeout)
        except subprocess.TimeoutExpired:
            os.killpg(self.lightserve_proc.pid, signal.SIGKILL)
            self.lightserve_proc.wait(10)

    def start_light_load(self, clients: int = 4, window: int = 96,
                         targets: int = 6,
                         deadline_s: float = 30.0) -> None:
        """Flood the serving daemon with pipelined light-client
        sessions: ``clients`` connections each holding ``window``
        submits in flight, rotating over ``targets`` warmed heights.
        Warm-phase resolves are counted separately (``warmed``) so the
        avoided-rate judges steady state, the way a long-lived daemon
        actually serves."""
        if self._light_thread is not None and \
                self._light_thread.is_alive():
            return
        self._light_stop = threading.Event()
        with self._light_lock:
            self._light_lat = []
            self._light_stats = {"sessions": 0, "avoided": 0,
                                 "errors": 0, "warmed": 0}
        self._light_thread = threading.Thread(
            target=self._light_flood,
            args=(clients, window, targets, deadline_s),
            name="light-load", daemon=True)
        self._light_thread.start()

    def stop_light_load(self, timeout: float = 60.0) -> None:
        if self._light_thread is None:
            return
        self._light_stop.set()
        self._light_thread.join(timeout)
        self._light_thread = None

    def light_stats(self) -> dict:
        """Snapshot of the flood counters (+ completed-session latency
        percentiles) — the evidence dispatch_avoided_rate judges."""
        with self._light_lock:
            out = dict(self._light_stats)
            lat = sorted(self._light_lat)
        for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
            out[key] = round(
                lat[min(len(lat) - 1, int(q * len(lat)))] * 1e3, 2) \
                if lat else None
        return out

    def _light_count(self, key: str, n: int = 1) -> None:
        with self._light_lock:
            self._light_stats[key] += n

    def _light_flood(self, clients: int, window: int, targets: int,
                     deadline_s: float) -> None:
        from tmtpu.lightserve.client import LightserveClient

        trust_h, trust_hex = self._light_trust
        anchor = bytes.fromhex(trust_hex)
        # wait for the chain to commit past every flood target so the
        # warmed heights never race the tip
        while not self._light_stop.is_set():
            try:
                st = self.nodes[0].client.status()
                if int(st["sync_info"]["latest_block_height"]) \
                        >= targets + 2:
                    break
            except Exception:
                pass
            self._light_stop.wait(0.5)
        if self._light_stop.is_set():
            return
        heights = list(range(2, targets + 2))
        try:
            warm = LightserveClient(self.lightserve_addr,
                                    chain_id=self.m.chain_id,
                                    client_id="scenario-warm")
            try:
                for h in heights:
                    warm.sync(trust_h, anchor, h, deadline_s=deadline_s)
                    self._light_count("warmed")
            finally:
                warm.close()
        except Exception:
            self._light_count("errors")
            return
        workers = [threading.Thread(
            target=self._light_worker,
            args=(ci, heights, window, deadline_s, trust_h, anchor),
            name=f"light-load-{ci}", daemon=True)
            for ci in range(clients)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()

    def _light_worker(self, ci: int, heights: list, window: int,
                      deadline_s: float, trust_h: int,
                      anchor: bytes) -> None:
        from tmtpu.lightserve.client import LightserveClient

        try:
            cli = LightserveClient(self.lightserve_addr,
                                   chain_id=self.m.chain_id,
                                   client_id=f"scenario-flood-{ci}")
        except Exception:
            self._light_count("errors")
            return
        pending: deque = deque()
        i = ci
        try:
            while not self._light_stop.is_set():
                while len(pending) < window and \
                        not self._light_stop.is_set():
                    h = heights[i % len(heights)]
                    i += 1
                    try:
                        pending.append(
                            cli.sync_submit(trust_h, anchor, h))
                    except Exception:
                        self._light_count("errors")
                        self._light_stop.wait(0.2)
                        break
                if not pending:
                    continue
                handle = pending.popleft()
                try:
                    r = handle.result(deadline_s=deadline_s)
                    done = time.perf_counter()
                    with self._light_lock:
                        self._light_stats["sessions"] += 1
                        self._light_lat.append(done - handle.submitted_at)
                        if r.dispatches == 0:
                            self._light_stats["avoided"] += 1
                except Exception:
                    self._light_count("errors")
            for handle in pending:      # drain, uncounted
                try:
                    handle.result(deadline_s=deadline_s)
                except Exception:
                    pass
        finally:
            cli.close()

    # -- runtime shaping fan-out ---------------------------------------------

    def _fanout(self, nodes, fn) -> dict:
        """Apply ``fn(node)`` to each running target; collect per-node
        outcomes instead of dying on the first RPC error (a node the
        timeline just killed is an expected miss, not a run failure)."""
        out = {}
        for node in nodes:
            try:
                out[node.spec.name] = {"ok": True, "result": fn(node)}
            except Exception as e:
                out[node.spec.name] = {"ok": False, "error": str(e)}
        return out

    def partition(self, groups) -> dict:
        """Sever traffic BETWEEN groups: each member stalls its egress
        to every node outside its own group (TCP-backpressure emulation,
        see p2p/shaping.py). Nodes in no group keep full connectivity
        (scenarios that want a clean split list everyone)."""
        by_name = {n.spec.name: n for n in self.nodes}
        results = {}
        for group in groups:
            inside = set(group)
            outside_ids = [by_name[n].node_id for n in by_name
                           if n not in inside]
            members = [by_name[n] for n in group]
            results.update(self._fanout(
                members,
                lambda nd, ids=outside_ids:
                    nd.client.unsafe_net_shape(partition=ids)))
        return results

    def heal(self) -> dict:
        return self._fanout(
            [n for n in self.nodes if n.running],
            lambda nd: nd.client.unsafe_net_shape(partition=[]))

    def shape(self, links: str, names=None) -> dict:
        targets = [self.node(n) for n in names] if names else \
            [n for n in self.nodes if n.running]
        return self._fanout(
            targets, lambda nd: nd.client.unsafe_net_shape(links=links))

    def clear_shape(self, names=None) -> dict:
        targets = [self.node(n) for n in names] if names else \
            [n for n in self.nodes if n.running]
        return self._fanout(
            targets, lambda nd: nd.client.unsafe_net_shape(clear=True))

    # -- late joins ----------------------------------------------------------

    def _rewrite_config(self, node, mutate) -> None:
        """Regenerate a down node's config.toml through the same path
        setup() used, apply ``mutate(cfg)``, and persist."""
        from tmtpu.e2e.localnet import chord_peer_names
        cfg = self._node_config(node)
        peers = {n.spec.name: f"{n.node_id}@127.0.0.1:{n.p2p_port}"
                 for n in self.nodes}
        plan = chord_peer_names([n.spec.name for n in self.nodes])
        cfg.p2p.persistent_peers = ",".join(
            peers[name] for name in plan[node.spec.name])
        mutate(cfg)
        cfg_toml.write_config(
            cfg, os.path.join(node.home, "config", "config.toml"))

    def join_statesync(self, name: str, trust_height: int = 1) -> dict:
        """Start ``name`` as a statesync joiner: trust anchor = the
        block-id hash served by a live node's ``commit`` RPC at
        ``trust_height``, snapshot/light-block sources = every running
        validator's RPC."""
        joiner = self.node(name)
        live = [n for n in self.nodes
                if n.running and n.spec.name != name]
        if not live:
            raise RuntimeError("no live node to anchor statesync trust")
        commit = live[0].client.commit(height=trust_height)
        trust_hash = commit["signed_header"]["commit"]["block_id"]["hash"]
        rpc_servers = [f"http://127.0.0.1:{n.rpc_port}" for n in live[:2]]

        def mutate(cfg):
            cfg.state_sync.enable = True
            cfg.state_sync.rpc_servers = rpc_servers
            cfg.state_sync.trust_height = trust_height
            cfg.state_sync.trust_hash = trust_hash
            cfg.state_sync.discovery_time_ns = 10**9

        self._rewrite_config(joiner, mutate)
        joiner.start()
        return {"trust_height": trust_height, "trust_hash": trust_hash,
                "rpc_servers": rpc_servers}

    def amnesia(self, name: str) -> None:
        """Crash ``name`` and wipe its double-sign protection (the
        privval last-signed state) before restarting — the amnesiac
        validator from the fork-accountability literature. The state
        file is RESET to the zeroed watermark, not deleted: FilePV.load
        refuses to start when the file is missing outright (a missing
        file is indistinguishable from corruption), while a height-0
        watermark is exactly what a validator that forgot everything it
        signed looks like."""
        node = self.node(name)
        node.signal(signal.SIGKILL)
        if node.proc is not None:
            node.proc.wait(10)
        cfg = self._node_config(node)
        state = cfg.rooted(cfg.base.priv_validator_state_file)
        with open(state, "w") as f:
            json.dump({"height": "0", "round": 0, "step": 0}, f)
        node.start()

    def stop(self):
        self.stop_light_load(timeout=10.0)
        super().stop()
        if self.sidecar_proc is not None and \
                self.sidecar_proc.poll() is None:
            self.term_sidecar(timeout=5.0)
        self.term_lightserve(timeout=10.0)
