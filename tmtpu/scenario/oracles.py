"""Scenario oracles: named pass/fail predicates over run evidence.

An oracle never talks to the net — it reads the ``Evidence`` bundle the
engine gathered (health samples, final RPC snapshots, block bodies,
metrics, timeline journals, the executed fault timeline) and returns
``(ok, detail)``. Keeping oracles pure makes verdicts reproducible from
the persisted evidence file and lets tools/check_scenarios.py lint
specs against this registry offline.

Registry contract: specs reference oracles by function name; params in
``OracleSpec.params`` are passed as keyword arguments.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

_builtin_min, _builtin_max = min, max


class Evidence:
    """Everything a judged run left behind. ``nodes`` maps node name to
    the final RPC snapshot::

        {"final_height": int, "running": bool,
         "health": health_detail result | None,
         "metrics": metrics result | None,
         "timeline": timeline result | None,
         "txlat": txlat result | None,
         "validator_stats": validator_stats result | None,
         "blocks": {height: block json}}

    ``samples`` is the health time-series ({"t", "node", "height",
    "healthy", "reasons"}, t = seconds since net start) and ``events``
    the executed fault timeline ({"t", "op", "node", "ok", "detail"}).
    ``lightserve`` carries the light-session flood counters when the
    spec ran a serving tier ({"sessions", "avoided", "errors",
    "warmed", "p50_ms", "p99_ms"}), else None.
    """

    def __init__(self, spec, events: List[dict], samples: List[dict],
                 nodes: Dict[str, dict], sidecar_kills: int = 0,
                 lightserve: Optional[dict] = None):
        self.spec = spec
        self.events = events
        self.samples = samples
        self.nodes = nodes
        self.sidecar_kills = sidecar_kills
        self.lightserve = lightserve

    # -- accessors -----------------------------------------------------------

    def node_names(self) -> List[str]:
        return sorted(self.nodes)

    def honest(self) -> List[str]:
        byz = set(self.spec.misbehaviors) if self.spec else set()
        return [n for n in self.node_names() if n not in byz]

    def final_heights(self, names: Optional[Iterable[str]] = None) \
            -> Dict[str, int]:
        names = list(names) if names else self.node_names()
        return {n: self.nodes[n].get("final_height", -1) for n in names}

    def event_times(self, op: str) -> List[float]:
        return [e["t"] for e in self.events if e["op"] == op]

    def heights_at(self, t: float) -> Dict[str, int]:
        """Last sampled height per node at or before ``t``."""
        out: Dict[str, int] = {}
        for s in self.samples:
            if s["t"] <= t and s["height"] >= 0:
                out[s["node"]] = s["height"]
        return out

    def metric(self, node: str, name: str, series: str = "") -> float:
        """Sum of a metric's series values on one node; ``series``
        substring-filters the series keys (label renderings like
        ``reason=overloaded``). Histograms contribute their count."""
        snap = (self.nodes.get(node, {}).get("metrics") or {})
        m = (snap.get("metrics") or {}).get(name)
        if not m:
            return 0.0
        total = 0.0
        for key, val in (m.get("series") or {}).items():
            if series and series not in key:
                continue
            total += val["count"] if isinstance(val, dict) else float(val)
        return total

    def metric_total(self, name: str, series: str = "") -> float:
        return sum(self.metric(n, name, series) for n in self.nodes)

    def committed_evidence(self, node: str) -> List[dict]:
        out = []
        for h in sorted(self.nodes.get(node, {}).get("blocks", {})):
            blk = self.nodes[node]["blocks"][h]
            for ev in (blk.get("evidence", {}) or {}).get("evidence", []):
                out.append({"height": h, **ev})
        return out

    def txlat_stats(self, node: str) -> Dict:
        """One node's recent submit→commit stats ({"count", "p50_ms",
        "p99_ms", "max_ms"}; count 0 when it submitted nothing)."""
        snap = self.nodes.get(node, {}).get("txlat") or {}
        return snap.get("submit_to_commit") or {"count": 0}

    def validator_address(self, node: str) -> str:
        """The validator address ``node`` itself reports in its
        validator_stats envelope ('' when unavailable)."""
        snap = self.nodes.get(node, {}).get("validator_stats") or {}
        return (snap.get("node") or {}).get("validator_address", "")

    def blamed_validator(self, node: str) -> Optional[str]:
        """The validator address ``node``'s forensics ledger names as
        the net's laggard: the strictly-worst scorecard when the ledger
        has a clear verdict, else the head of the worst-scored list."""
        snap = self.nodes.get(node, {}).get("validator_stats") or {}
        blamed = snap.get("laggard")
        if not blamed:
            worst = snap.get("worst") or []
            blamed = worst[0]["address"] if worst else None
        return blamed

    def timeline_event_names(self, node: str) -> List[str]:
        tl = self.nodes.get(node, {}).get("timeline") or {}
        names = []
        for rec in tl.get("heights", []):
            for ev in rec.get("events", []):
                names.append(ev.get("event", ""))
        return names


# -- registry -----------------------------------------------------------------

ORACLES: Dict[str, callable] = {}


def oracle(fn):
    ORACLES[fn.__name__] = fn
    return fn


def names() -> List[str]:
    return sorted(ORACLES)


def get(name: str):
    try:
        return ORACLES[name]
    except KeyError:
        raise KeyError(f"unknown oracle {name!r}; known: {names()}")


# -- progress / agreement -----------------------------------------------------

@oracle
def height_min(ev: Evidence, min: int = 3, nodes=None) -> Tuple[bool, str]:
    """Every (selected) node's final height reached ``min``."""
    hs = ev.final_heights(nodes)
    low = {n: h for n, h in hs.items() if h < min}
    return (not low,
            f"final heights {hs}" + (f"; below {min}: {low}" if low else ""))


@oracle
def height_spread(ev: Evidence, max: int = 2, nodes=None) \
        -> Tuple[bool, str]:
    """No straggler: heights within ``max`` of the leader at the final
    sampler sweep. Samples are used instead of the judge-time RPC
    snapshots because the gather pass polls nodes seconds apart while an
    idle net keeps committing empty blocks — sequential-poll skew would
    masquerade as a straggler."""
    hs = ev.heights_at(float("inf"))
    if nodes:
        hs = {n: h for n, h in hs.items() if n in set(nodes)}
    if not hs:
        hs = {n: h for n, h in ev.final_heights(nodes).items() if h >= 0}
    if not hs:
        return False, "no node reported a height"
    spread = _builtin_max(hs.values()) - _builtin_min(hs.values())
    return spread <= max, f"spread {spread} over {hs} (limit {max})"


@oracle
def chain_agreement(ev: Evidence) -> Tuple[bool, str]:
    """App hash + header linkage agree at every height two nodes both
    serve. A single wrong verify accepted anywhere shows up here as a
    state divergence."""
    names_ = ev.node_names()
    if len(names_) < 2:
        return True, "single node"
    ref = _builtin_max(names_,
                       key=lambda n: len(ev.nodes[n].get("blocks", {})))
    ref_blocks = ev.nodes[ref].get("blocks", {})
    compared = 0
    for other in names_:
        if other == ref:
            continue
        for h, blk in ev.nodes[other].get("blocks", {}).items():
            rblk = ref_blocks.get(h)
            if rblk is None:
                continue
            compared += 1
            a, b = rblk["header"], blk["header"]
            if a["app_hash"] != b["app_hash"]:
                return False, f"app hash divergence {ref}/{other} at {h}"
            if a["last_block_id"] != b["last_block_id"]:
                return False, f"chain divergence {ref}/{other} at {h}"
    return compared > 0, f"{compared} cross-node height comparisons agree" \
        if compared else "no common heights to compare"


@oracle
def progress_after(ev: Evidence, op: str, min_blocks: int = 1) \
        -> Tuple[bool, str]:
    """The net kept committing after the LAST ``op`` event."""
    times = ev.event_times(op)
    if not times:
        return False, f"no {op!r} event executed"
    at = ev.heights_at(times[-1])
    before = _builtin_max(at.values(), default=-1)
    after = _builtin_max(ev.final_heights().values(), default=-1)
    return (after - before >= min_blocks,
            f"height {before} at last {op!r} -> {after} final "
            f"(need +{min_blocks})")


# -- health -------------------------------------------------------------------

@oracle
def all_healthy(ev: Evidence, nodes=None) -> Tuple[bool, str]:
    """Every (selected) node's final watchdog verdict is healthy."""
    names_ = list(nodes) if nodes else ev.node_names()
    sick = {}
    for n in names_:
        h = ev.nodes.get(n, {}).get("health")
        if not h or not h.get("healthy"):
            sick[n] = (h or {}).get("reasons", ["no health snapshot"])
    return not sick, f"unhealthy: {sick}" if sick else \
        f"all {len(names_)} nodes healthy"


@oracle
def latency_p99_under_slo(ev: Evidence, slo_ms: float = 2000.0,
                          min_count: int = 20, nodes=None) \
        -> Tuple[bool, str]:
    """Every node that submitted txs (txlat submit→commit count >=
    ``min_count``) saw a recent-window p99 at or under ``slo_ms``, and
    at least one node actually has that coverage — a latency scenario
    whose load never landed must fail loudly, not vacuously pass."""
    names_ = list(nodes) if nodes else ev.node_names()
    covered, over = {}, {}
    for n in names_:
        stats = ev.txlat_stats(n)
        if stats.get("count", 0) < min_count:
            continue
        p99 = stats.get("p99_ms")
        covered[n] = p99
        if p99 is None or p99 > slo_ms:
            over[n] = p99
    if not covered:
        return False, (f"no node has >= {min_count} submit->commit "
                       f"journeys (txlat off or load never landed)")
    if over:
        return False, f"p99 over {slo_ms}ms SLO: {over} (all: {covered})"
    return True, f"p99 under {slo_ms}ms SLO on {covered}"


@oracle
def stall_detected(ev: Evidence, node: str, check: str = "consensus",
                   after_op: Optional[str] = None,
                   before_op: Optional[str] = None) -> Tuple[bool, str]:
    """The watchdog on ``node`` reported a ``check`` stall inside the
    [after_op, before_op] event window — the detection half of a
    partition scenario (the minority MUST notice it is stalled)."""
    t_lo = ev.event_times(after_op)[-1] if after_op and \
        ev.event_times(after_op) else 0.0
    ts_hi = ev.event_times(before_op) if before_op else []
    t_hi = ts_hi[-1] if ts_hi else float("inf")
    seen = []
    for s in ev.samples:
        if s["node"] != node or not (t_lo <= s["t"] <= t_hi + 2.0):
            continue
        if not s["healthy"] and any(check in r for r in s["reasons"]):
            seen.append(round(s["t"], 1))
    return (bool(seen),
            f"{node} {check}-stall verdicts at t={seen[:5]}" if seen else
            f"{node} never reported a {check} stall in "
            f"[{t_lo:.1f}, {t_hi if t_hi != float('inf') else 'end'}]")


@oracle
def rejoin(ev: Evidence, op: str = "heal", within_s: float = 30.0,
           spread: int = 2) -> Tuple[bool, str]:
    """After the ``op`` event, every node converges to within ``spread``
    of the leader — with fresh progress — inside ``within_s``."""
    times = ev.event_times(op)
    if not times:
        return False, f"no {op!r} event executed"
    t_heal = times[-1]
    base = _builtin_max(ev.heights_at(t_heal).values(), default=-1)
    # walk the sample timeline: earliest instant where all nodes are
    # within `spread` of the then-leader AND the leader has moved on
    last_by_node: Dict[str, int] = {}
    for s in sorted(ev.samples, key=lambda s: s["t"]):
        if s["t"] <= t_heal or s["height"] < 0:
            continue
        last_by_node[s["node"]] = s["height"]
        if len(last_by_node) < len(ev.nodes):
            continue
        top = _builtin_max(last_by_node.values())
        if top > base and top - _builtin_min(last_by_node.values()) \
                <= spread:
            dt = s["t"] - t_heal
            return (dt <= within_s,
                    f"converged {dt:.1f}s after {op!r} "
                    f"(limit {within_s}s) at heights {last_by_node}")
    return False, (f"never converged within spread {spread} after "
                   f"{op!r} at t={t_heal:.1f} (baseline height {base})")


# -- byzantine accountability -------------------------------------------------

@oracle
def evidence_committed(ev: Evidence,
                       type: str = "tendermint/DuplicateVoteEvidence",
                       nodes: str = "honest") -> Tuple[bool, str]:
    """Every honest node committed at least one evidence item of
    ``type`` — accountability actually landed on the chain, not just in
    a mempool."""
    names_ = ev.honest() if nodes == "honest" else list(nodes)
    missing, found = [], {}
    for n in names_:
        items = [e for e in ev.committed_evidence(n)
                 if e.get("type") == type]
        if items:
            found[n] = [e["height"] for e in items]
        else:
            missing.append(n)
    return (not missing,
            f"committed on {found}" if not missing else
            f"no {type} on {missing} (found: {found})")


@oracle
def no_evidence(ev: Evidence) -> Tuple[bool, str]:
    """Zero committed evidence anywhere — crash/restart and spam
    scenarios must not manufacture double-signs."""
    hits = {n: ev.committed_evidence(n) for n in ev.node_names()}
    hits = {n: [f"{e['type']}@{e['height']}" for e in v]
            for n, v in hits.items() if v}
    return not hits, f"unexpected evidence: {hits}" if hits else \
        "no evidence committed"


@oracle
def laggard_identified(ev: Evidence, node: str, min_reporters: int = 2) \
        -> Tuple[bool, str]:
    """Every honest node's validator-forensics ledger independently
    blames the validator operated by ``node`` — attribution from public
    RPC evidence alone. The expected address comes out of the accused
    node's own ``validator_stats`` envelope (each node reports its own
    validator address there), so the oracle never peeks at process
    internals; every other honest node's ledger must name that address
    as its worst-scored laggard, and at least ``min_reporters`` of them
    must have reached a verdict."""
    expected = ev.validator_address(node)
    if not expected:
        return False, (f"{node} reported no validator address in its "
                       f"validator_stats envelope")
    verdicts: Dict[str, str] = {}
    for n in ev.honest():
        if n == node:
            continue
        blamed = ev.blamed_validator(n)
        if blamed:
            verdicts[n] = blamed
    agree = sorted(n for n, a in verdicts.items() if a == expected)
    wrong = {n: a[:12] for n, a in verdicts.items() if a != expected}
    if wrong:
        return False, (f"disagreement: {wrong} blame someone other than "
                       f"{node} ({expected[:12]}…); agreeing: {agree}")
    if len(agree) < min_reporters:
        return False, (f"only {len(agree)} honest nodes reached a "
                       f"laggard verdict (need {min_reporters}); "
                       f"verdicts: {verdicts}")
    return True, (f"{len(agree)} honest nodes independently name {node} "
                  f"({expected[:12]}…) as the laggard: {agree}")


# -- metrics / timeline -------------------------------------------------------

@oracle
def metric_min(ev: Evidence, name: str, min: float = 1.0,
               node: Optional[str] = None, series: str = "",
               nodes: str = "any") -> Tuple[bool, str]:
    """A metric crossed a floor: on one named node, summed over the net
    (nodes="sum"), on every honest node (nodes="each_honest"), or on at
    least one node (default)."""
    if node:
        v = ev.metric(node, name, series)
        return v >= min, f"{name}[{series}] on {node} = {v} (floor {min})"
    if nodes == "sum":
        v = ev.metric_total(name, series)
        return v >= min, f"{name}[{series}] net total = {v} (floor {min})"
    per = {n: ev.metric(n, name, series)
           for n in (ev.honest() if nodes == "each_honest"
                     else ev.node_names())}
    if nodes == "each_honest":
        low = {n: v for n, v in per.items() if v < min}
        return not low, f"{name}[{series}] per honest node {per}" + \
            (f"; below {min}: {sorted(low)}" if low else "")
    ok = any(v >= min for v in per.values())
    return ok, f"{name}[{series}] per node {per} (floor {min} on any)"


@oracle
def metric_max(ev: Evidence, name: str, max: float = 0.0,
               node: Optional[str] = None, series: str = "") \
        -> Tuple[bool, str]:
    """A metric stayed under a ceiling (summed net-wide unless ``node``
    pins it)."""
    v = ev.metric(node, name, series) if node else \
        ev.metric_total(name, series)
    where = node or "net total"
    return v <= max, f"{name}[{series}] {where} = {v} (ceiling {max})"


@oracle
def sidecar_fallbacks_cover_kills(ev: Evidence, min_per_kill: float = 1.0) \
        -> Tuple[bool, str]:
    """Every daemon kill forced at least ``min_per_kill`` penalty-free
    in-process fallback lanes somewhere on the net — proof the clients
    actually absorbed each outage instead of wedging."""
    if ev.sidecar_kills == 0:
        return False, "no sidecar kills executed"
    got = ev.metric_total("tendermint_sidecar_client_fallback_total")
    need = ev.sidecar_kills * min_per_kill
    return (got >= need,
            f"{got} fallback lanes vs {ev.sidecar_kills} kills "
            f"(need >= {need})")


@oracle
def dispatch_avoided_rate(ev: Evidence, min_rate: float = 0.99,
                          min_sessions: int = 200,
                          max_errors: int = 0) -> Tuple[bool, str]:
    """The light-client serving tier answered nearly every flood
    session without touching the verification engine — the "verify
    once, serve millions" invariant. Judges the steady-state counters
    the light flood recorded (warm-phase resolves are excluded by the
    loader, the way a long-lived daemon serves after warmup), and
    demands enough completed sessions that the rate means something —
    a flood that never landed must fail loudly, not vacuously pass."""
    st = ev.lightserve or {}
    sessions = int(st.get("sessions", 0))
    if sessions < min_sessions:
        return False, (f"only {sessions} light sessions completed "
                       f"(need >= {min_sessions}); stats {st}")
    avoided = int(st.get("avoided", 0))
    errors = int(st.get("errors", 0))
    rate = avoided / sessions
    detail = (f"{avoided}/{sessions} sessions avoided a dispatch "
              f"(rate {rate:.4f}, floor {min_rate}), {errors} errors "
              f"(ceiling {max_errors}), p99 {st.get('p99_ms')}ms, "
              f"{st.get('warmed', 0)} warm resolves excluded")
    return rate >= min_rate and errors <= max_errors, detail


@oracle
def block_rate_stable(ev: Evidence, split_s: float,
                      max_drop: float = 0.2) -> Tuple[bool, str]:
    """Commit rate after ``split_s`` (when the adversarial phase is on)
    is within ``max_drop`` of the rate before it — spam absorbed, not
    amplified."""
    before = ev.heights_at(split_s)
    final = ev.final_heights()
    h_split = _builtin_max(before.values(), default=-1)
    h_end = _builtin_max(final.values(), default=-1)
    ts = [s["t"] for s in ev.samples]
    if h_split < 0 or not ts:
        return False, "no samples before the split point"
    t_end = _builtin_max(ts)
    first = _builtin_min(ts)
    if t_end <= split_s or split_s <= first:
        return False, f"split {split_s}s outside run [{first:.1f},{t_end:.1f}]"
    rate_before = h_split / split_s
    rate_after = (h_end - h_split) / (t_end - split_s)
    if rate_before <= 0:
        return False, f"no progress before t={split_s}s"
    ratio = rate_after / rate_before
    return (ratio >= 1.0 - max_drop,
            f"rate {rate_before:.2f} -> {rate_after:.2f} blocks/s "
            f"(x{ratio:.2f}, floor x{1.0 - max_drop:.2f})")


@oracle
def timeline_saw(ev: Evidence, event: str, node: Optional[str] = None) \
        -> Tuple[bool, str]:
    """Some node's per-height timeline journal recorded ``event`` (e.g.
    ``crypto.sidecar`` proves verifies actually rode the daemon)."""
    targets = [node] if node else ev.node_names()
    hits = [n for n in targets if event in ev.timeline_event_names(n)]
    return (bool(hits),
            f"{event!r} on {hits}" if hits else
            f"{event!r} absent from timeline journals of {targets}")
