"""The named starter scenarios.

Each builder returns a fresh ``ScenarioSpec`` (callers may mutate their
copy — shrink durations for CI, crank load for soak runs). ``FAST``
lists the set cheap enough to ride tier-1 under the ``scenarios``
pytest marker; the rest run on demand via tools/scenario_run.py.

Timing notes: scenario nets run the e2e fast consensus profile
(~0.4 s propose timeout), so an unperturbed 4-validator localnet
commits a block roughly every 0.3–1 s. Stall watchdogs run on a 5 s
leash (net.py), which sets the floor on how short a detectable
partition can be.
"""

from __future__ import annotations

from tmtpu.scenario.spec import (FaultAction, OracleSpec, ScenarioSpec,
                                 compose)

SECOND_NS = 10**9

# deterministic mixed-curve assignment for scale-rung nets: every third
# node draws off ed25519 so big nets exercise the multi-curve verify
# paths without making the slowest curve the whole net's cadence
_CURVE_CYCLE = ("ed25519", "ed25519", "sr25519", "ed25519", "secp256k1")


def mixed_key_types(names) -> dict:
    return {n: _CURVE_CYCLE[i % len(_CURVE_CYCLE)]
            for i, n in enumerate(names)
            if _CURVE_CYCLE[i % len(_CURVE_CYCLE)] != "ed25519"}


def split_brain() -> ScenarioSpec:
    """Partition a 4-validator net 3|1 for 10 s, then heal. The majority
    keeps committing (3/4 power > 2/3); the minority must NOTICE it is
    stalled (watchdog verdict — the detection half of the exercise) and
    then catch back up to within 2 heights of the leader inside 30 s of
    the heal."""
    return ScenarioSpec(
        name="split_brain",
        description="3|1 partition + heal: minority stalls, detects it, "
                    "rejoins",
        validators=4, load_rate=10.0, duration_s=29.0, settle_s=5.0,
        faults=[
            # the split waits until the net is demonstrably committing:
            # a node partitioned during startup blocksync gets a syncing
            # pass from the watchdog and the stall oracle has nothing
            # to observe
            FaultAction(8.0, "partition", params={
                "groups": [["v00", "v01", "v02"], ["v03"]]}),
            FaultAction(18.0, "heal"),
        ],
        oracles=[
            OracleSpec("stall_detected", {"node": "v03",
                                          "check": "consensus",
                                          "after_op": "partition",
                                          "before_op": "heal"}),
            OracleSpec("rejoin", {"op": "heal", "within_s": 30.0,
                                  "spread": 2}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 5}),
            OracleSpec("all_healthy"),
        ])


def sidecar_crash_storm() -> ScenarioSpec:
    """SIGKILL the shared verification daemon five times under tx flood,
    restarting it 2 s later each time. Nodes must absorb every outage on
    the penalty-free in-process path (fallback lanes >= kills), keep
    perfect agreement (a single wrong verify result would fork state),
    and end healthy with the daemon path back in use."""
    kills = [5.0, 10.0, 15.0, 20.0, 25.0]
    faults = []
    for t in kills:
        faults.append(FaultAction(t, "sidecar_kill", node="sidecar"))
        faults.append(FaultAction(t + 2.0, "sidecar_restart",
                                  node="sidecar"))
    return ScenarioSpec(
        name="sidecar_crash_storm",
        description="5x sidecar SIGKILL under load: fallback covers "
                    "every outage, zero divergence",
        validators=3, sidecar=True, load_rate=30.0,
        duration_s=30.0, settle_s=6.0,
        faults=faults,
        oracles=[
            OracleSpec("sidecar_fallbacks_cover_kills",
                       {"min_per_kill": 1}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6}),
            OracleSpec("all_healthy"),
            OracleSpec("timeline_saw", {"event": "crypto.sidecar"}),
        ])


def equivocation() -> ScenarioSpec:
    """One validator double-prevotes at height 3. Honest nodes must turn
    the conflict into DuplicateVoteEvidence and COMMIT it — every honest
    node's chain carries the proof, not just a mempool."""
    return ScenarioSpec(
        name="equivocation",
        description="double-prevote byzantine validator: duplicate-vote "
                    "evidence lands on every honest chain",
        validators=4, load_rate=5.0, duration_s=14.0, settle_s=4.0,
        misbehaviors={"v03": {3: "double-prevote"}},
        oracles=[
            OracleSpec("evidence_committed",
                       {"type": "tendermint/DuplicateVoteEvidence"}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6}),
        ])


def garbage_sig_flood() -> ScenarioSpec:
    """A byzantine validator sprays bursts of random-signature votes at
    three heights. The batch-verify admission filter must reject every
    lane (invalid-vote counter ticks, no evidence manufactured) without
    the block rate collapsing more than 20%."""
    return ScenarioSpec(
        name="garbage_sig_flood",
        description="garbage-signature vote spam: rejected at admission, "
                    "block rate holds",
        validators=4, load_rate=10.0, duration_s=24.0, settle_s=5.0,
        misbehaviors={"v03": {6: "garbage-sig", 9: "garbage-sig",
                              12: "garbage-sig"}},
        oracles=[
            OracleSpec("metric_min",
                       {"name": "tendermint_consensus_invalid_votes_total",
                        "min": 1, "nodes": "sum"}),
            OracleSpec("no_evidence"),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 10}),
            OracleSpec("block_rate_stable", {"split_s": 8.0,
                                             "max_drop": 0.2}),
        ])


def wan_200ms() -> ScenarioSpec:
    """Every link shaped to 200 ms +-40 ms with 5% loss — a
    cross-continent WAN on localhost. Consensus timeouts widen to
    production scale; the net must still commit and stay healthy, and
    the shape metrics must prove the WAN was actually in the path."""
    return ScenarioSpec(
        name="wan_200ms",
        description="200ms/5%-loss WAN shaping: liveness holds at "
                    "production timeouts",
        validators=4, load_rate=5.0, duration_s=30.0, settle_s=8.0,
        links="*:latency_ms=200,jitter_ms=40,drop=0.05",
        config={
            "consensus.timeout_propose_ns": 2 * SECOND_NS,
            "consensus.timeout_prevote_ns": SECOND_NS,
            "consensus.timeout_precommit_ns": SECOND_NS,
            "consensus.timeout_commit_ns": SECOND_NS // 2,
            # production timeouts need the production commit WAIT too:
            # skipping it charges the quorum-surplus straggler (always
            # late at 200 ms RTT) as a participation miss and the flap
            # watchdog smears across honest validators (see laggard)
            "consensus.skip_timeout_commit": False,
            "health.consensus_stall_timeout_ns": 20 * SECOND_NS,
            # even with the wait, 5% loss flaps real participation;
            # window the check tighter and absorb WAN-tail stragglers
            "health.validator_flap_window_ns": 30 * SECOND_NS,
            "health.validator_flap_threshold": 8,
        },
        oracles=[
            OracleSpec("height_min", {"min": 3}),
            OracleSpec("chain_agreement"),
            OracleSpec("all_healthy"),
            OracleSpec("metric_min",
                       {"name": "tendermint_p2p_shape_delay_seconds",
                        "min": 100, "nodes": "sum"}),
        ])


def churn_rotation() -> ScenarioSpec:
    """Rolling validator restarts while a validator-set update tx adds a
    fifth key mid-run: membership churn on top of process churn. The
    set change must reach every node (validators gauge hits 5) with no
    divergence."""
    return ScenarioSpec(
        name="churn_rotation",
        description="rolling restarts + validator-set rotation tx",
        validators=4, load_rate=10.0, duration_s=26.0, settle_s=6.0,
        faults=[
            FaultAction(5.0, "restart", node="v01",
                        params={"down_s": 1.0}),
            FaultAction(10.0, "add_validator", params={"power": 10}),
            FaultAction(16.0, "restart", node="v02",
                        params={"down_s": 1.0}),
        ],
        oracles=[
            OracleSpec("metric_min",
                       {"name": "tendermint_consensus_validators",
                        "min": 5, "nodes": "any"}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 8}),
            OracleSpec("all_healthy"),
        ])


def statesync_join() -> ScenarioSpec:
    """A fresh full node statesyncs into a net that is mid-flood:
    snapshot restore + light-client verification + blocksync tail, all
    while the validators keep committing at load. The joiner must land
    within 3 heights of the leader by judge time."""
    return ScenarioSpec(
        name="statesync_join",
        description="statesync join under tx flood: snapshot restore "
                    "catches the live chain",
        validators=3, full_nodes=1, full_node_start="manual",
        load_rate=20.0, duration_s=34.0, settle_s=8.0,
        config={"base.app_snapshot_interval": 4},
        faults=[
            FaultAction(14.0, "join_statesync", node="f00",
                        params={"trust_height": 1}),
        ],
        oracles=[
            OracleSpec("height_min", {"min": 10,
                                      "nodes": ["v00", "v01", "v02"]}),
            OracleSpec("height_spread", {"max": 3}),
            OracleSpec("chain_agreement"),
        ])


def latency_under_load() -> ScenarioSpec:
    """Steady 4-validator net under sustained tx flood — no faults, the
    adversary is the load itself. Every node's per-tx journey ring must
    show a p99 submit->commit latency under the SLO, with enough
    completed journeys per node that the percentile means something.
    The fast e2e consensus profile commits roughly every 0.3-1 s; the
    measured tail on a loaded shared-CPU host sits near 5 s (queueing
    behind the gather window and block cadence), so the SLO carries
    ~2x headroom: it trips on real stalls, not host jitter."""
    return ScenarioSpec(
        name="latency_under_load",
        description="sustained tx flood: per-tx p99 submit->commit "
                    "latency holds under SLO on every node",
        validators=4, load_rate=25.0, duration_s=24.0, settle_s=5.0,
        oracles=[
            OracleSpec("latency_p99_under_slo",
                       {"slo_ms": 10_000.0, "min_count": 20}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 8}),
            OracleSpec("all_healthy"),
        ])


def crash_restart_wal() -> ScenarioSpec:
    """SIGKILL a validator twice under load. Each restart replays the
    WAL with a cold signature cache and must rejoin without ever
    double-signing (zero evidence on any chain) while the net keeps
    committing."""
    return ScenarioSpec(
        name="crash_restart_wal",
        description="kill -9 a validator twice under load: WAL replay "
                    "rejoins, zero double-signs",
        validators=3, load_rate=10.0, duration_s=16.0, settle_s=5.0,
        faults=[
            FaultAction(5.0, "kill", node="v01"),
            FaultAction(7.0, "start", node="v01"),
            FaultAction(11.0, "kill", node="v01"),
            FaultAction(12.5, "start", node="v01"),
        ],
        oracles=[
            OracleSpec("no_evidence"),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6}),
            OracleSpec("height_spread", {"max": 2}),
            OracleSpec("all_healthy"),
        ])


def laggard() -> ScenarioSpec:
    """SIGSTOP one validator for 10 s under load — the classic 'one box
    went dark' incident. The majority keeps committing (3/4 power); the
    forensics ledgers on every honest node must accumulate the frozen
    validator's missed votes into the worst scorecard, so judge time
    names the exact validator from public RPC evidence alone
    (laggard_identified). The pause starts only after the net is
    demonstrably committing so the ledgers have a participation
    baseline to decay from.

    Runs with a real commit wait (production profile) instead of the
    e2e fast profile's skip_timeout_commit: the forensics rollup judges
    height H from last_commit when H+1 commits, and last_commit only
    absorbs straggler precommits during the NEW_HEIGHT wait — with a
    zero wait a fast node charges the quorum-surplus 4th precommit as
    a miss and the scorecards smear across honest validators."""
    return ScenarioSpec(
        name="laggard",
        description="SIGSTOP a validator 10s: every honest forensics "
                    "ledger names it as the laggard",
        validators=4, load_rate=10.0, duration_s=24.0, settle_s=5.0,
        config={
            "consensus.skip_timeout_commit": False,
            "consensus.timeout_commit_ns": SECOND_NS // 4,
        },
        faults=[
            FaultAction(6.0, "pause", node="v03",
                        params={"for_s": 10.0}),
        ],
        oracles=[
            OracleSpec("laggard_identified", {"node": "v03",
                                              "min_reporters": 2}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6,
                                      "nodes": ["v00", "v01", "v02"]}),
        ])


def amnesia() -> ScenarioSpec:
    """Wipe a validator's double-sign protection (privval last-sign
    state) twice under load — the amnesiac validator from the
    fork-accountability literature. Each wipe is a SIGKILL + state
    delete + restart, so the amnesiac misses votes across both
    downtimes and flaps its participation state; every honest node's
    forensics ledger must pin the worst scorecard on it (amnesiac
    named from public RPC evidence) while the chain stays in perfect
    agreement — amnesia must never fork state on a net that keeps
    2/3+ honest. Same commit-wait profile as ``laggard`` (see there:
    straggler absorption needs a real NEW_HEIGHT window)."""
    return ScenarioSpec(
        name="amnesia",
        description="double privval-state wipe: honest ledgers name the "
                    "amnesiac, zero divergence",
        validators=4, load_rate=10.0, duration_s=22.0, settle_s=6.0,
        config={
            "consensus.skip_timeout_commit": False,
            "consensus.timeout_commit_ns": SECOND_NS // 4,
        },
        faults=[
            FaultAction(6.0, "amnesia", node="v03"),
            FaultAction(13.0, "amnesia", node="v03"),
        ],
        oracles=[
            OracleSpec("laggard_identified", {"node": "v03",
                                              "min_reporters": 2}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6,
                                      "nodes": ["v00", "v01", "v02"]}),
        ])


def light_flood() -> ScenarioSpec:
    """A commit-proof serving daemon (``tmtpu lightserve``) anchored on
    the live chain serves a pipelined light-session flood while the
    validators keep committing under tx load. After the loader warms
    its target heights, >99% of sessions must be answered with ZERO
    verify dispatches (the serving tier's whole point: verify once,
    serve millions) with no session errors — while the usual liveness
    and latency invariants hold on the chain underneath. The session
    floor keeps the rate honest: on this single-core host the flood
    completes thousands of sessions in the window, so 200 is a
    landed-at-all bar, not a throughput benchmark."""
    return ScenarioSpec(
        name="light_flood",
        description="light-session flood against the serving tier: "
                    ">99% of sessions dodge the verify engine",
        validators=4, lightserve=True, load_rate=10.0,
        duration_s=22.0, settle_s=5.0,
        oracles=[
            OracleSpec("dispatch_avoided_rate",
                       {"min_rate": 0.99, "min_sessions": 200}),
            OracleSpec("latency_p99_under_slo",
                       {"slo_ms": 15_000.0, "min_count": 10}),
            OracleSpec("chain_agreement"),
            OracleSpec("height_min", {"min": 6}),
        ])


# -- composition layers & composed scenarios ----------------------------------
#
# Layers below exist to be composed (spec.compose): each is a valid
# standalone spec, but its real job is contributing one concern — a
# fault storm, a network shape, a load tier — to a composed run whose
# verdict attributes failures back to the layer.


def lan_50ms() -> ScenarioSpec:
    """Mild 50 ms / 1%-loss shaping on every link — enough to move
    every message off the loopback fast path without dragging commit
    cadence below the fast-profile timeouts. The cheap WAN-ish layer
    for composed runs that must stay inside a CI budget."""
    return ScenarioSpec(
        name="lan_50ms",
        description="50ms/1%-loss shaping: liveness holds on the fast "
                    "profile",
        validators=3, load_rate=5.0, duration_s=16.0, settle_s=4.0,
        links="*:latency_ms=50,jitter_ms=10,drop=0.01",
        config={
            "health.consensus_stall_timeout_ns": 10 * SECOND_NS,
        },
        oracles=[
            OracleSpec("height_min", {"min": 3}),
            OracleSpec("metric_min",
                       {"name": "tendermint_p2p_shape_delay_seconds",
                        "min": 5, "nodes": "sum"}),
        ])


def scale_rung(validators: int = 25) -> ScenarioSpec:
    """The 10-50 validator rung as a composable base layer: a big
    mixed-curve net booted through the pooled/staggered path, judged on
    the floor that matters at this size: the net COMMITS, in agreement,
    with every validator inside the spread.

    Timeouts scale with the net. Per-height work is ~N^2 (every node
    verifies every vote, every vote crosses every gossip hop) and the
    whole net shares one host, so vote diffusion for one height runs
    tens of seconds at 25 validators. A propose timeout below the
    diffusion time is a round-churn machine: nodes nil-prevote before
    the proposal reaches them, every round restarts the diffusion, and
    the net only commits ~10 minutes later when the per-round timeout
    escalation finally overtakes diffusion (observed). Giving round 0
    room to finish beats churning to round 40."""
    names = [f"v{i:02d}" for i in range(validators)]
    big = validators >= 16
    return ScenarioSpec(
        name=f"scale_{validators}v",
        description=f"{validators}-validator mixed-curve net boots "
                    f"pooled and commits",
        validators=validators, load_rate=0.0,
        # the 25v floor: first commit lands ~6 min after the readiness
        # gate (~N^2 verify work + thread-scheduling latency per gossip
        # hop on one shared core), and each following height costs
        # minutes again. 12 min of injected runtime is what "commits,
        # in agreement" needs; small rungs keep the 1-min profile.
        duration_s=720.0 if big else 60.0,
        settle_s=15.0 if big else 10.0, timeout_s=900.0,
        key_types=mixed_key_types(names),
        # NO shared sidecar here: on a single-host net this size the
        # round trip runs ~900ms under the VoteSet lock (the daemon
        # shares the same starved core), an order of magnitude worse
        # than the 20-78ms in-process verify it replaces. Sidecar
        # compositions live in the smaller-net scenarios.
        config={
            "consensus.timeout_propose_ns":
                (15 if big else 5) * SECOND_NS,
            "consensus.timeout_prevote_ns":
                (8 if big else 2) * SECOND_NS,
            "consensus.timeout_precommit_ns":
                (8 if big else 2) * SECOND_NS,
            "consensus.timeout_commit_ns":
                (2 if big else 1) * SECOND_NS,
            "consensus.skip_timeout_commit": False,
            # idle gossip polling is the other big-net killer: ~2 loops
            # per peer-end at the default 10ms pace is ~50k wakeups/s on
            # a 25-node chord net, all against one GIL. 250ms adds at
            # most ~sleep x log2(n) hops of relay latency (the send path
            # never sleeps) — noise against 15s propose timeouts.
            "consensus.gossip_sleep_ns":
                (SECOND_NS // 4) if big else (SECOND_NS // 100),
            "health.consensus_stall_timeout_ns":
                (180 if big else 60) * SECOND_NS,
        },
        oracles=[
            OracleSpec("height_min", {"min": 2 if big else 3}),
            OracleSpec("height_spread", {"max": 3}),
            OracleSpec("chain_agreement"),
        ])


def trickle_load(rate: float = 4.0,
                 slo_ms: float = 30_000.0) -> ScenarioSpec:
    """Low-rate open-loop load tier for compositions whose other
    layers already saturate the host: keeps real txs flowing through
    the mempool/commit path (and the per-tx journey rings populated)
    without the throughput tier's cadence pressure. ``slo_ms`` is the
    p99 submit->commit budget — calibrate it to the composed net's
    block cadence (a 25-validator single-host net commits in minutes,
    not seconds)."""
    return ScenarioSpec(
        name="trickle_load",
        description=f"{rate} tx/s trickle: journeys complete under a "
                    "relaxed SLO",
        validators=3, load_rate=rate, load_size=32,
        duration_s=20.0, settle_s=5.0,
        oracles=[
            OracleSpec("latency_p99_under_slo",
                       {"slo_ms": slo_ms, "min_count": 5}),
            OracleSpec("chain_agreement"),
        ])


def storm_under_wan_load() -> ScenarioSpec:
    """The ROADMAP composition, literally: sidecar crash storm UNDER
    WAN reshaping UNDER throughput-tier load, one net, one verdict.
    Every layer's oracles must hold simultaneously: fallback lanes
    cover every daemon kill while 200 ms/5%-loss shaping stretches the
    gossip fabric and the load tier keeps per-tx p99 under its SLO."""
    return compose(
        "storm_under_wan_load",
        sidecar_crash_storm(), wan_200ms(), latency_under_load(),
        description="sidecar crash storm ∘ wan 200ms ∘ throughput "
                    "load: all three layers' invariants hold at once",
        overrides={
            # three layers on one host: hold the throughput tier's
            # rate but widen its p99 SLO to the WAN cadence (the
            # un-composed entries budget for loopback block intervals)
            "load_rate": 25.0,
            "timeout_s": 300.0,
        })


def churn_under_wan() -> ScenarioSpec:
    """Process churn composed onto WAN shaping: rolling validator
    restarts and a mid-run validator-set rotation tx, all under
    200 ms/5%-loss links. Restarted nodes must blocksync back through
    the shaped fabric and the set change must still reach every node."""
    return compose(
        "churn_under_wan",
        churn_rotation(), wan_200ms(),
        description="rolling restarts + valset rotation ∘ wan 200ms",
        overrides={"timeout_s": 300.0})


def wal_under_lan() -> ScenarioSpec:
    """The FAST composed pair-member: crash_restart_wal's double
    SIGKILL composed onto mild 50 ms shaping and a tx trickle — cheap
    enough to ride tier-1, while still exercising the full composition
    machinery (three layers, interleaved timeline, per-layer verdict
    attribution) on every CI run."""
    return compose(
        "wal_under_lan",
        crash_restart_wal(), lan_50ms(), trickle_load(),
        description="kill -9 twice ∘ lan 50ms ∘ trickle load: WAL "
                    "replay rejoins through a shaped fabric")


def scale_rung_25() -> ScenarioSpec:
    """The scale acceptance rung: a 25-validator mixed-curve net under
    trickle load, with one mid-run validator restart. Boots via pooled
    waves + /readyz gating; PASS = commits land in agreement on all 25
    with the restarted node back inside the spread.

    No shaping layer here, deliberately: per-connection shaping threads
    on top of ~125 chord connections starve the single-core host so
    thoroughly that even health RPCs time out and prevote quorum never
    aggregates (every node frozen at 1/0/Prevote for the whole run).
    Shaped compositions live in the smaller-net scenarios
    (storm_under_wan_load, churn_under_wan); this rung exists to prove
    the 10-50 validator floor boots and commits."""
    base = scale_rung(25)
    # p99 budget = a few of the big net's minute-scale block intervals
    # (the first block sweeps up every tx submitted while it diffused)
    load = trickle_load(1.0, slo_ms=900_000.0)
    spec = compose(
        "scale_rung_25", base, load,
        description="25 validators ∘ trickle load: the 10-50 rung "
                    "boots pooled and commits",
        overrides={"settle_s": 15.0, "load_rate": 1.0})
    # restart lands mid-run: late enough that the first commits are
    # down, early enough that the node must rejoin before the judge
    spec.faults.append(FaultAction(180.0, "restart", node="v24",
                                   params={"down_s": 1.0},
                                   layer=base.name))
    return spec


COMPOSED = ("storm_under_wan_load", "churn_under_wan", "wal_under_lan",
            "scale_rung_25")

SCENARIOS = {
    "split_brain": split_brain,
    "sidecar_crash_storm": sidecar_crash_storm,
    "equivocation": equivocation,
    "garbage_sig_flood": garbage_sig_flood,
    "wan_200ms": wan_200ms,
    "churn_rotation": churn_rotation,
    "statesync_join": statesync_join,
    "latency_under_load": latency_under_load,
    "light_flood": light_flood,
    "crash_restart_wal": crash_restart_wal,
    "laggard": laggard,
    "amnesia": amnesia,
    "lan_50ms": lan_50ms,
    "scale_rung_25": scale_rung_25,
    "storm_under_wan_load": storm_under_wan_load,
    "churn_under_wan": churn_under_wan,
    "wal_under_lan": wal_under_lan,
}

# cheap enough for tier-1 (the ``scenarios`` pytest marker)
FAST = ("equivocation", "wal_under_lan", "light_flood")


def names() -> list:
    return sorted(SCENARIOS)


def get(name: str) -> ScenarioSpec:
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise KeyError(f"unknown scenario {name!r}; known: {names()}")
